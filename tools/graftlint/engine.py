"""graftlint engine: rule registry, suppressions, baseline, runner.

The solver's correctness rests on invariants pytest cannot see — canonical
iteration order feeding fingerprints, host-sync-free jit regions, lock
discipline around the threaded solverd, encode/decode field parity on the
wire. graftlint machine-checks them on every diff. This module is the
project-agnostic half: file loading, the rule-author API, inline
suppressions, the frozen baseline, and the CLI runner. The invariants
themselves live in ``tools/graftlint/rules/`` (one module per family).

Rule-author API
---------------
Subclass :class:`Rule` and decorate with :func:`register`::

    from tools.graftlint.engine import Rule, register

    @register
    class NoSleepInReconcile(Rule):
        id = "GL501"
        name = "reconcile-sleep"
        rationale = "time.sleep in a reconciler stalls the whole pass"

        def applies(self, pf):           # optional file filter
            return "controllers/" in pf.relpath

        def check(self, pf):             # per-file rule
            for node in pf.walk(ast.Call):
                if pf.call_name(node) == "time.sleep":
                    yield self.finding(pf, node, "time.sleep in reconcile path")

Project-scope rules (cross-file: parity checks) set ``scope = "project"``
and implement ``check_project(files)`` instead. Import the module from
``tools/graftlint/rules/__init__.py`` so registration runs.

Suppressions
------------
``# graftlint: disable=GL201 -- <justification>`` on the flagged line (or a
standalone comment on the line above) silences that rule there. The
justification after ``--`` is mandatory: a bare disable is itself reported
as GL000. ``disable=all`` silences every rule for the line.

Baseline
--------
``tools/graftlint/baseline.json`` freezes reviewed pre-existing violations
(fingerprinted by rule + path + source text, so unrelated edits don't shift
them). ``--baseline`` rewrites it from the current findings; anything not
in it fails the run. The repo policy (ISSUE 4) is an EMPTY baseline for the
shipped rule families — real violations get fixed or inline-justified.
"""
from __future__ import annotations

import ast
import io
import json
import re
import time
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"

# single source of truth for the tier-1 wall-time budget: the test gate
# (tests/test_graftlint.py) and bench.py --lint both enforce this value
LINT_BUDGET_SECONDS = 10.0

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+?)\s*(?:--\s*(\S.*))?$"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str

    def fingerprint(self, source_line: str) -> str:
        """Line-number-independent identity for baseline entries."""
        return f"{self.rule}|{self.path}|{source_line.strip()}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


class ParsedFile:
    """One source file plus the per-file artifacts every rule shares."""

    def __init__(self, path: Path, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._gl_parent = parent  # type: ignore[attr-defined]
        # line -> (rule ids | {"all"}, has_justification). Parsed from
        # COMMENT tokens only — a string literal containing the disable
        # syntax (docs, error messages) must neither suppress nor trip
        # GL000.
        self.suppressions: Dict[int, Tuple[set, bool]] = {}
        self.comment_lines: set = set()
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type != tokenize.COMMENT:
                    continue
                lineno = tok.start[0]
                if tok.start[1] == 0 or not self.lines[
                    lineno - 1
                ][: tok.start[1]].strip():
                    self.comment_lines.add(lineno)  # standalone comment
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    rules = {
                        r.strip() for r in m.group(1).split(",") if r.strip()
                    }
                    self.suppressions[lineno] = (rules, m.group(2) is not None)
        except tokenize.TokenError:
            pass  # ast.parse above succeeded; treat the tail as comment-free

    def walk(self, *types) -> Iterator[ast.AST]:
        for node in ast.walk(self.tree):
            if not types or isinstance(node, types):
                yield node

    def parents(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = getattr(node, "_gl_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_gl_parent", None)

    def enclosing_function(self, node: ast.AST):
        for p in self.parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return p
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for p in self.parents(node):
            if isinstance(p, ast.ClassDef):
                return p
        return None

    def call_name(self, node: ast.Call) -> str:
        """Dotted name of a call target: ``time.sleep``, ``sorted`` — ''
        when the callee is not a plain name/attribute chain."""
        return dotted_name(node.func)

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def is_suppressed(self, finding: Finding) -> bool:
        """Same-line disable, or a disable anywhere in the contiguous
        standalone-comment block immediately above the flagged line (so a
        justification may wrap over several comment lines)."""
        candidates = [finding.line]
        lineno = finding.line - 1
        while lineno >= 1 and lineno in self.comment_lines:
            candidates.append(lineno)
            lineno -= 1
        for ln in candidates:
            entry = self.suppressions.get(ln)
            if entry is None:
                continue
            rules, _ = entry
            if finding.rule in rules or "all" in rules:
                return True
        return False


def dotted_name(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class Rule:
    """Base class for graftlint rules; see the module docstring."""

    id: str = ""
    name: str = ""
    rationale: str = ""
    scope: str = "file"  # "file" | "project"

    def applies(self, pf: ParsedFile) -> bool:
        return True

    def check(self, pf: ParsedFile) -> Iterable[Finding]:
        return ()

    def check_project(self, files: List[ParsedFile]) -> Iterable[Finding]:
        return ()

    def finding(self, pf: ParsedFile, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.id,
            path=pf.relpath,
            line=getattr(node, "lineno", 1),
            message=message,
        )


RULES: Dict[str, Rule] = {}


def register(cls):
    inst = cls()
    if not inst.id or not inst.name:
        raise ValueError(f"rule {cls.__name__} needs id and name")
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    RULES[inst.id] = inst
    return cls


def _collect_files(paths: List[str]) -> List[ParsedFile]:
    files: List[ParsedFile] = []
    seen = set()
    for raw in paths:
        p = Path(raw)
        if not p.is_absolute():
            p = REPO_ROOT / p
        if not p.exists():
            # a typo'd path must fail the gate, not lint zero files green
            raise SystemExit(f"graftlint: path not found: {raw}")
        candidates = [p] if p.is_file() else sorted(p.rglob("*.py"))
        for f in candidates:
            if "__pycache__" in f.parts or f in seen:
                continue
            seen.add(f)
            try:
                rel = f.resolve().relative_to(REPO_ROOT).as_posix()
            except ValueError:
                rel = f.as_posix()
            source = f.read_text()
            try:
                files.append(ParsedFile(f, rel, source))
            except SyntaxError as e:
                raise SystemExit(f"graftlint: cannot parse {rel}: {e}")
    if not files:
        raise SystemExit(
            f"graftlint: no Python files found under {', '.join(paths)}"
        )
    return files


@dataclass
class RunResult:
    new: List[Tuple[Finding, str]]  # (finding, source line)
    baselined: List[Finding]
    suppressed: List[Finding]
    files: int
    rule_seconds: Dict[str, float]

    @property
    def ok(self) -> bool:
        return not self.new


def _load_baseline(path: Optional[Path] = None) -> Dict[str, int]:
    path = path or BASELINE_PATH
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    return dict(data.get("entries", {}))


def _bad_suppression_findings(pf: ParsedFile) -> List[Finding]:
    out = []
    for lineno, (rules, has_why) in sorted(pf.suppressions.items()):
        unknown = {
            r for r in rules if r != "all" and r not in RULES and r != "GL000"
        }
        if not has_why:
            out.append(Finding(
                "GL000", pf.relpath, lineno,
                "suppression without justification: write"
                " '# graftlint: disable=RULE -- why'",
            ))
        if unknown:
            out.append(Finding(
                "GL000", pf.relpath, lineno,
                f"suppression names unknown rule(s): {', '.join(sorted(unknown))}",
            ))
    return out


def run(
    paths: List[str],
    use_baseline: bool = True,
    rule_ids: Optional[List[str]] = None,
    baseline_path: Optional[Path] = None,
) -> RunResult:
    """Run every registered rule over ``paths``; returns the partitioned
    findings. ``rule_ids`` restricts the pass (rule unit tests)."""
    from tools.graftlint import rules as _rules  # noqa: F401 (registration)

    files = _collect_files(paths)
    if rule_ids is not None:
        unknown = set(rule_ids) - set(RULES) - {"GL000"}
        if unknown:
            # same policy as a typo'd path: fail the gate, don't run zero
            # rules green
            raise SystemExit(
                f"graftlint: unknown rule id(s): {', '.join(sorted(unknown))}"
            )
    active = [
        r for rid, r in sorted(RULES.items())
        if rule_ids is None or rid in rule_ids
    ]
    rule_seconds: Dict[str, float] = {}
    raw: List[Tuple[Finding, ParsedFile]] = []
    by_rel = {pf.relpath: pf for pf in files}

    for rule in active:
        t0 = time.perf_counter()
        if rule.scope == "project":
            for f in rule.check_project(files):
                pf = by_rel.get(f.path)
                if pf is not None:
                    raw.append((f, pf))
        else:
            for pf in files:
                if rule.applies(pf):
                    for f in rule.check(pf):
                        raw.append((f, pf))
        rule_seconds[rule.id] = time.perf_counter() - t0

    if rule_ids is None or "GL000" in rule_ids:
        t0 = time.perf_counter()
        for pf in files:
            for f in _bad_suppression_findings(pf):
                raw.append((f, pf))
        rule_seconds["GL000"] = time.perf_counter() - t0

    baseline = _load_baseline(baseline_path) if use_baseline else {}
    budget = dict(baseline)
    new: List[Tuple[Finding, str]] = []
    baselined: List[Finding] = []
    suppressed: List[Finding] = []
    for f, pf in sorted(raw, key=lambda t: (t[0].path, t[0].line, t[0].rule)):
        if f.rule != "GL000" and pf.is_suppressed(f):
            suppressed.append(f)
            continue
        src = pf.source_line(f.line)
        fp = f.fingerprint(src)
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            baselined.append(f)
            continue
        new.append((f, src))
    return RunResult(new, baselined, suppressed, len(files), rule_seconds)


def write_baseline(result: RunResult, path: Optional[Path] = None) -> int:
    """Freeze the current new findings into the baseline file. Callers run
    with use_baseline=False first so every occurrence lands in ``new``."""
    entries: Dict[str, int] = {}
    for f, src in result.new:
        fp = f.fingerprint(src)
        entries[fp] = entries.get(fp, 0) + 1
    (path or BASELINE_PATH).write_text(
        json.dumps({"entries": entries}, indent=2, sort_keys=True) + "\n"
    )
    return len(entries)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="project-native static analysis for karpenter-core-tpu",
    )
    ap.add_argument(
        "paths", nargs="*", default=[],
        help="files/dirs to lint (default: karpenter_core_tpu)",
    )
    ap.add_argument(
        "--baseline", action="store_true",
        help="rewrite tools/graftlint/baseline.json from current findings",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--timing", action="store_true", help="per-rule wall time report"
    )
    ap.add_argument(
        "--rule", action="append", default=None,
        help="restrict to one rule id (repeatable)",
    )
    args = ap.parse_args(argv)

    from tools.graftlint import rules as _rules  # noqa: F401

    if args.list_rules:
        for rid, r in sorted(RULES.items()):
            print(f"{rid}  {r.name:24s} {r.rationale}")
        return 0

    if args.baseline and (args.rule or args.paths):
        # a rule- or path-restricted regeneration would silently drop
        # every other rule's/path's frozen entries from the file
        raise SystemExit(
            "graftlint: --baseline regenerates over the full default tree;"
            " it cannot be combined with --rule or explicit paths"
        )

    paths = args.paths or ["karpenter_core_tpu"]
    result = run(paths, use_baseline=not args.baseline, rule_ids=args.rule)

    if args.baseline:
        n = write_baseline(result)
        print(f"graftlint: baseline rewritten with {n} entr{'y' if n == 1 else 'ies'}")
        return 0

    for f, _src in result.new:
        print(f.render())
    if args.timing:
        for rid, dt in sorted(
            result.rule_seconds.items(), key=lambda kv: -kv[1]
        ):
            print(f"# {rid}: {dt * 1000:.1f} ms")
    print(
        f"graftlint: {len(result.new)} finding(s)"
        f" ({len(result.baselined)} baselined,"
        f" {len(result.suppressed)} suppressed)"
        f" across {result.files} file(s), {len(result.rule_seconds)} rule(s)"
    )
    return 0 if result.ok else 1
