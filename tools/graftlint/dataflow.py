"""Interprocedural dataflow for graftlint: the sharding provenance lattice.

PR 6 made pjit-over-the-slot-axis the production solve path. Its
correctness contract is *placement discipline*: every SlotState plane must
land on the device pre-sharded through ``parallel.mesh`` (slot_shardings /
axis_sharding / batch_sharding), and host code must never materialize a
slot-sharded plane wholesale (an implicit cross-device gather). Per-file
AST matching cannot see that contract — the placement happens in
``DeviceScheduler._dev_slots``, three calls away from the jit entry that
consumes the state — so this module gives the GL5xx rules an
interprocedural view:

- a **project-wide call graph**: every def (functions and methods) indexed
  by name across the scanned file set, with call resolution by dotted-name
  tail (``self._dev_slots(...)`` resolves to every ``_dev_slots`` def);
- a **provenance lattice** for array values, tags accumulated as a set::

      HOST ──┐                 host memory (numpy, device_get results)
      DEVICE ─┼─► value tags   on device, placement unannotated
      REPL ──┤                 explicitly replicated over the mesh
      SHARD ──┘                 routed through the slot-axis sharding API

  ``PLACED = {REPL, SHARD}``. An empty tag set means "unknown" and is
  never flagged — the analysis under-approximates: it only reports when
  it can positively trace a value to its sources.
- **function return summaries** (the provenance a call produces, joined
  over every return site) and **attribute summaries** (keyword-constructed
  pytree fields: ``_Prepared(init_state=self._make_init_state(...))``
  records ``init_state -> {SHARD, ...}``), so a chain like

      ffd_solve_donated(prep.init_state, ...)
        <- _Prepared(init_state=...) <- _make_init_state
        <- self._dev_slots <- jax.device_put(a, pmesh.axis_sharding(...))

  resolves to SHARD across four hops and two classes.

The whole index is built once per scanned file set and cached by content
hash (every relpath + source digest), so repeated ``run()`` calls in one
process — the tier-1 gate, bench.py --lint, editor integrations — pay the
fixpoint once. Known over-approximations, deliberate and documented:
attribute summaries are keyed by bare attribute name project-wide (not
per-class), and call resolution is by name tail (not import graph). Both
can only ADD tags, and every consumer flags on positive evidence, so the
imprecision degrades to silence, not noise.
"""
from __future__ import annotations

import ast
import hashlib
import weakref
from typing import Dict, List, Optional, Set

from tools.graftlint.engine import ParsedFile, dotted_name

HOST = "host"
DEVICE = "device"  # on device, placement unannotated
REPL = "replicated"
SHARD = "sharded"
PLACED = frozenset({REPL, SHARD})

# the sanctioned placement API (parallel/mesh.py): call tails that mint a
# slot-axis sharding / an explicit replication (the batched_* twins mint
# the problem-batched specs for the continuous-batching vmapped solve)
_MESH_SHARDERS = {
    "slot_shardings", "axis_sharding", "batch_sharding",
    "batched_slot_shardings", "batched_step_shardings",
    "gang_plane_shardings", "batched_gang_plane_shardings",
    "relax_plane_shardings",
    # topoaware (ISSUE 20): slot-axis sharding for the per-class hop
    # planes (ClassStep.topo_rank and friends)
    "topo_plane_shardings",
    # the pallas fused kernels' placement route (ISSUE 18): whole-plane
    # replication ahead of the GSPMD-opaque pallas_call boundary
    "pallas_slot_shardings",
}
_MESH_REPLICATORS = {"replicated"}

_NP_PREFIXES = ("np.", "numpy.", "onp.")
_JNP_PREFIXES = ("jnp.", "jax.numpy.")

# array-metadata attributes: reading them yields host scalars/objects, not
# the array — branching on .shape or accounting .nbytes is never a gather
_METADATA_ATTRS = {
    "shape", "ndim", "dtype", "nbytes", "size", "sharding", "itemsize",
    "_fields",
}

_MAX_DEPTH = 6  # call-summary resolution depth cap
_MAX_CANDIDATES = 6  # same-named defs considered per call


def _content_key(files: List[ParsedFile]) -> str:
    h = hashlib.sha256()
    for pf in sorted(files, key=lambda p: p.relpath):
        h.update(pf.relpath.encode())
        h.update(hashlib.sha256(pf.source.encode()).digest())
    return h.hexdigest()


class ProjectDataflow:
    """Provenance queries over one scanned file set. Use :func:`get`."""

    def __init__(self, files: List[ParsedFile]):
        self.files = files
        # name -> [(pf, def node)] for every function/method in the project
        self.defs: Dict[str, List] = {}
        # class name -> ClassDef (constructor-call recognition)
        self.classes: Dict[str, ast.ClassDef] = {}
        for pf in files:
            for node in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
                self.defs.setdefault(node.name, []).append((pf, node))
            for node in pf.walk(ast.ClassDef):
                self.classes.setdefault(node.name, node)
        # attribute name -> joined provenance of every recorded store
        self.attr_summary: Dict[str, Set[str]] = {}
        # memo keys are the AST NODES THEMSELVES (identity hash), held
        # WEAKLY: an id() key would outlive its node (a recycled address
        # then returns a different function's env), while a strong key
        # would pin every later run's re-parsed tree forever (the index
        # itself is process-cached by content hash). Weak keys give both
        # properties: construction-time entries persist exactly as long
        # as self.files retains their trees, and query-time entries from
        # a caller's re-parse evict with that parse.
        self._summaries = weakref.WeakKeyDictionary()
        self._envs = weakref.WeakKeyDictionary()
        self._in_progress: Set[int] = set()
        # two eager passes: pass 1 populates attribute summaries from
        # constructor calls and attribute stores everywhere; pass 2
        # recomputes envs/summaries against the grown attr table so
        # cross-module attribute reads (consolidation reading
        # provisioner's _Prepared fields) see the final join
        for _ in range(2):
            self._summaries.clear()
            self._envs.clear()
            for pf in files:
                self._env_for(pf, None)
                for node in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
                    self._env_for(pf, node)

    # -- public query ------------------------------------------------------

    def prov(self, pf: ParsedFile, expr: ast.AST, fn) -> frozenset:
        """Provenance tag set of an expression evaluated in the local
        environment of ``fn`` (None = module level)."""
        env = self._env_for(pf, fn)
        return frozenset(self._eval(pf, expr, env, _MAX_DEPTH))

    # -- environments ------------------------------------------------------

    def _env_for(self, pf: ParsedFile, fn) -> Dict[str, Set[str]]:
        key = fn if fn is not None else pf.tree
        cached = self._envs.get(key)
        if cached is not None:
            return cached
        env: Dict[str, Set[str]] = {}
        self._envs[key] = env  # pre-bind: cycles read the partial env
        if isinstance(fn, ast.Lambda):
            return env  # no statements, nothing to bind
        body = pf.tree.body if fn is None else fn.body
        self._walk_stmts(pf, body, env, _MAX_DEPTH)
        return env

    def _walk_stmts(self, pf, stmts, env, depth) -> None:
        for st in stmts:
            if isinstance(
                st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # nested scopes own their env
            if isinstance(st, ast.Assign):
                p = self._eval(pf, st.value, env, depth)
                for tgt in st.targets:
                    self._bind(pf, tgt, st.value, p, env, depth)
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                p = self._eval(pf, st.value, env, depth)
                self._bind(pf, st.target, st.value, p, env, depth)
            elif isinstance(st, ast.AugAssign):
                p = self._eval(pf, st.value, env, depth)
                if isinstance(st.target, ast.Name):
                    env.setdefault(st.target.id, set()).update(p)
            elif isinstance(st, ast.For) or isinstance(st, ast.AsyncFor):
                p = self._eval(pf, st.iter, env, depth)
                self._bind(pf, st.target, st.iter, p, env, depth)
                self._walk_stmts(pf, st.body, env, depth)
                self._walk_stmts(pf, st.orelse, env, depth)
            elif isinstance(st, (ast.If, ast.While)):
                # both arms walked over one env: reassignment joins, the
                # safe over-approximation for a branch-insensitive lattice
                self._walk_stmts(pf, st.body, env, depth)
                self._walk_stmts(pf, st.orelse, env, depth)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    if item.optional_vars is not None:
                        p = self._eval(pf, item.context_expr, env, depth)
                        self._bind(
                            pf, item.optional_vars, item.context_expr, p,
                            env, depth,
                        )
                self._walk_stmts(pf, st.body, env, depth)
            elif isinstance(st, ast.Try):
                self._walk_stmts(pf, st.body, env, depth)
                for h in st.handlers:
                    self._walk_stmts(pf, h.body, env, depth)
                self._walk_stmts(pf, st.orelse, env, depth)
                self._walk_stmts(pf, st.finalbody, env, depth)
            elif isinstance(st, (ast.Return, ast.Expr)):
                if st.value is not None:
                    # evaluated for effect: constructor calls inside the
                    # expression record attribute summaries
                    self._eval(pf, st.value, env, depth)

    def _bind(self, pf, target, value, prov: Set[str], env, depth) -> None:
        if isinstance(target, ast.Name):
            env.setdefault(target.id, set()).update(prov)
        elif isinstance(target, ast.Starred):
            self._bind(pf, target.value, value, prov, env, depth)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) and len(
                value.elts
            ) == len(target.elts):
                for t, v in zip(target.elts, value.elts):
                    self._bind(pf, t, v, self._eval(pf, v, env, depth), env, depth)
            else:
                for t in target.elts:
                    self._bind(pf, t, value, prov, env, depth)
        elif isinstance(target, ast.Attribute):
            # obj.attr = expr: record in the attribute summary. A None
            # store is a tombstone (prep.init_state = None after donation),
            # not a placement decision — skip it.
            if prov and not (
                isinstance(value, ast.Constant) and value.value is None
            ):
                self.attr_summary.setdefault(target.attr, set()).update(prov)
        # Subscript targets carry no name to bind

    # -- expression evaluation ---------------------------------------------

    def _eval(self, pf, node: ast.AST, env, depth) -> Set[str]:
        if isinstance(node, ast.Constant):
            if node.value is None:
                return set()
            return {HOST}
        if isinstance(node, ast.Name):
            return set(env.get(node.id, ()))
        if isinstance(node, ast.Attribute):
            if node.attr in _METADATA_ATTRS:
                return set()
            base = self._eval(pf, node.value, env, depth)
            if base:
                return base
            return set(self.attr_summary.get(node.attr, ()))
        if isinstance(node, ast.Call):
            return self._eval_call(pf, node, env, depth)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out: Set[str] = set()
            for e in node.elts:
                out |= self._eval(pf, e, env, depth)
            return out
        if isinstance(node, ast.Subscript):
            # slicing keeps provenance: state.valmask[:n] is still sharded
            return self._eval(pf, node.value, env, depth)
        if isinstance(node, ast.IfExp):
            return self._eval(pf, node.body, env, depth) | self._eval(
                pf, node.orelse, env, depth
            )
        if isinstance(node, ast.BinOp):
            return self._eval(pf, node.left, env, depth) | self._eval(
                pf, node.right, env, depth
            )
        if isinstance(node, ast.UnaryOp):
            return self._eval(pf, node.operand, env, depth)
        if isinstance(node, ast.NamedExpr):
            p = self._eval(pf, node.value, env, depth)
            env.setdefault(node.target.id, set()).update(p)
            return p
        if isinstance(node, ast.Starred):
            return self._eval(pf, node.value, env, depth)
        return set()

    def _eval_call(self, pf, node: ast.Call, env, depth) -> Set[str]:
        name = dotted_name(node.func)
        tail = name.rsplit(".", 1)[-1] if name else ""

        if tail in _MESH_SHARDERS:
            return {SHARD}
        if tail in _MESH_REPLICATORS:
            return {REPL}
        if name in ("jax.device_put", "device_put"):
            placement = None
            if len(node.args) >= 2:
                placement = node.args[1]
            elif node.keywords:
                for kw in node.keywords:
                    if kw.arg in ("device", "sharding", None):
                        placement = kw.value
                        break
            if placement is None:
                return {DEVICE}  # bare put: unannotated placement
            sh = self._eval(pf, placement, env, depth)
            sh &= {SHARD, REPL}
            return sh or {REPL}  # explicitly placed, shape unknown -> repl
        if name in ("jax.device_get", "device_get"):
            return {HOST}
        if name.endswith("tree.map") or name in ("jax.tree_map", "tree_map"):
            out: Set[str] = set()
            for a in node.args:
                out |= self._eval(pf, a, env, depth)
            return out
        if name.startswith(_NP_PREFIXES):
            return {HOST}
        if name.startswith(_JNP_PREFIXES):
            return {DEVICE}
        if name in ("int", "float", "bool"):
            return {HOST}  # concretization: the RESULT is host
        if tail == "_replace" and isinstance(node.func, ast.Attribute):
            out = self._eval(pf, node.func.value, env, depth)
            for kw in node.keywords:
                out |= self._eval(pf, kw.value, env, depth)
            return out

        # constructor call of a class (SlotState(...), _Prepared(...)):
        # record keyword fields in the attribute summary, provenance is the
        # union of the parts. CamelCase names count even when the class def
        # lives outside the scanned set (SlotState imported from ops/ffd
        # into a partial-path run) — the keyword-record is what matters.
        cls = self.classes.get(tail)
        if cls is not None or (tail[:1].isupper() and tail not in self.defs):
            out = set()
            for a in node.args:
                out |= self._eval(pf, a, env, depth)
            for kw in node.keywords:
                kp = self._eval(pf, kw.value, env, depth)
                out |= kp
                if kw.arg and kp:
                    self.attr_summary.setdefault(kw.arg, set()).update(kp)
            return out

        # project function/method: join the return summaries of every
        # same-named def (conservative tail resolution)
        candidates = self.defs.get(tail, ())
        if candidates and depth > 0:
            out = set()
            for cpf, fn in candidates[:_MAX_CANDIDATES]:
                out |= self._summary(cpf, fn, depth - 1)
            # evaluate args for constructor-recording side effects
            for a in node.args:
                self._eval(pf, a, env, depth)
            for kw in node.keywords:
                self._eval(pf, kw.value, env, depth)
            return out
        return set()

    def _summary(self, pf, fn, depth) -> Set[str]:
        """Return-site provenance join of one def."""
        cached = self._summaries.get(fn)
        if cached is not None:
            return set(cached)
        if id(fn) in self._in_progress:
            return set()  # recursion: bottom, refined on the next pass
        self._in_progress.add(id(fn))
        try:
            env = self._env_for(pf, fn)
            out: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    owner = pf.enclosing_function(node)
                    if owner is fn:
                        out |= self._eval(pf, node.value, env, depth)
            self._summaries[fn] = frozenset(out)
            return out
        finally:
            self._in_progress.discard(id(fn))


_CACHE: Dict[str, ProjectDataflow] = {}


def get(files: List[ParsedFile]) -> ProjectDataflow:
    """The (content-hash cached) dataflow index for one scanned set."""
    key = _content_key(files)
    df = _CACHE.get(key)
    if df is None:
        df = ProjectDataflow(files)
        if len(_CACHE) > 8:  # a handful of distinct scan sets per process
            _CACHE.clear()
        _CACHE[key] = df
    return df


# ===========================================================================
# Second abstract domain (ISSUE 11): value ranges, dtype width, taints.
#
# The provenance lattice above answers "WHERE has this array been?"
# (host/device/sharded). The GL6xx rangecheck family needs a second,
# orthogonal question answered per value: "WHAT can this integer BE?" —
# its static interval, the dtype width it is stored at, whether it
# originated on the wire, whether it carries inert padding, and which
# registered sentinel domain its negative magic numbers belong to. The
# same engine shape carries it: per-function environments, constructor /
# attribute-store summaries, return summaries joined over every return
# site, all iterated eagerly to a fixpoint over the scanned set and
# cached by content hash.
#
# Join discipline (the noise/soundness split the GL5xx rules pinned):
#
# * intervals join by HULL — imprecision widens toward (-inf, +inf),
#   which every consumer treats as "unknown" and stays silent on unless a
#   taint demands otherwise;
# * TAINTS (wire, pad, padsize, sentinel domains) join by UNION — a value
#   that is wire-derived on ANY path is wire-derived;
# * GUARDS (clamped-by-normalizer, masked) join by INTERSECTION — a value
#   is only clamped if EVERY contributing store/path clamped it. This is
#   what lets GL601 see through the attribute-summary whitewash: if one
#   EvictablePod constructor site drops its priority_tier clamp, the
#   project-wide `priority` summary loses the guard even though the
#   other sites kept theirs.
# * recursion widens to TOP immediately (a cyclic return summary yields
#   the unknown interval), so the fixpoint terminates on any input — the
#   widening-termination property the engine unit tests pin.
# ===========================================================================

INF = float("inf")

# taints (union-join)
WIRE = "wire"  # decoded from a solver wire payload
PAD = "pad"  # array content includes inert padding rows/slots
PADSIZE = "padsize"  # a SIZE minted by a padding helper (pad_to_devices)

# guards (intersection-join)
CLAMPED = "clamped"  # passed a registered normalizer or an explicit clip
MASKED = "masked"  # routed through a masking step (jnp.where etc.)

# dtype bounds; NARROW_INT_DTYPES are the widths GL601 polices stores into
INT_BOUNDS = {
    "int8": (-(2 ** 7), 2 ** 7 - 1),
    "int16": (-(2 ** 15), 2 ** 15 - 1),
    "int32": (-(2 ** 31), 2 ** 31 - 1),
    "int64": (-(2 ** 63), 2 ** 63 - 1),
}
NARROW_INT_DTYPES = frozenset({"int8", "int16", "int32"})

# Registered normalizers: call tails that map an arbitrary host/wire int
# into a documented codomain. Calling one both bounds the interval and
# grants the CLAMPED guard — the sanctioned way through a GL601 narrowing
# store. utils/disruption.priority_tier is THE tier normalizer (kernel /
# fallback / verifier all ride it); codec._clamp_slots is the decode-net
# clamp for the wire's slot ceiling; solver/gangs.gang_rank and
# gang_max_hops (topoaware, ISSUE 20) are the annotation-parse clamps a
# hostile wire rank/max-hops int must pass before any int32 plane store.
RANGE_NORMALIZERS: Dict[str, tuple] = {
    "priority_tier": (-(2 ** 31 - 1), 2 ** 31 - 1),
    "_clamp_slots": (1, 1 << 20),
    "gang_rank": (0, 1 << 20),
    "gang_max_hops": (0, 3),
}

# calls whose result is explicitly clipped: (lo-arg index, hi-arg index)
_CLIP_CALLS = {"clip"}  # np.clip / jnp.clip / ndarray.clip

# padding producers: results carry array-content PAD; size producers
# carry PADSIZE (an array constructed with a PADSIZE shape is PAD)
_PAD_ARRAY_CALLS = {"_pad", "pad"}  # models/provisioner._pad, np/jnp.pad
_PAD_SIZE_CALLS = {"pad_to_devices", "_bucket", "_bucket_steps",
                   "_pow2_bucket"}

# masking calls: the sanctioned step between padded content and a
# reduction (GL604)
_MASK_CALLS = {"where"}

# numpy-ish array constructors whose dtype= kw types the array
_ARRAY_CTORS = {"zeros", "ones", "full", "empty", "array", "asarray",
                "arange", "full_like", "zeros_like", "ones_like"}


def _seed_sentinel_domains() -> Dict[str, dict]:
    """The sentinel-domain registry: domain -> {values: {label: int},
    names: exact value names, prefixes: name prefixes}. The gang domain
    seeds from solver/gangs.GANG_SENTINELS — the single source the kernel
    (ops/gangsched) and the prep layer (models/provisioner) import — with
    a literal fallback so a standalone fixture lint (or a checkout whose
    package cannot import) still checks the same contract."""
    try:
        from karpenter_core_tpu.solver.gangs import GANG_SENTINELS

        gang_values = dict(GANG_SENTINELS)
    except Exception:  # pragma: no cover - import-degraded environments
        gang_values = {"gang-free": -1, "fallback-straddling": -2}
    return {
        "gang": {
            "values": gang_values,
            "names": {"step_gang", "gang_j", "goc", "gang_id", "gid"},
            "prefixes": ("gang_of",),
        },
        "template": {
            "values": {"no-template": -1},
            "names": {"new_template", "slot_template", "template_arr"},
            "prefixes": (),
        },
    }


SENTINEL_DOMAINS: Dict[str, dict] = _seed_sentinel_domains()


def sentinel_domain_of(name: str) -> Optional[str]:
    """The registered sentinel domain a bare value name belongs to, or
    None. Matched on the dotted tail (``prep.step_gang`` -> gang)."""
    if not name:
        return None
    tail = name.rsplit(".", 1)[-1]
    for dom, spec in SENTINEL_DOMAINS.items():
        if tail in spec["names"]:
            return dom
        if any(tail.startswith(p) for p in spec["prefixes"]):
            return dom
    return None


class AbsVal:
    """One value's abstract state in the range domain (mutable; joined in
    place inside environments and summaries)."""

    __slots__ = ("lo", "hi", "dtype", "taints", "guards", "values",
                 "sentinels")

    _VALUES_CAP = 8  # beyond this the exact-value set degrades to unknown

    def __init__(self, lo=-INF, hi=INF, dtype=None, taints=(), guards=(),
                 values=None, sentinels=()):
        self.lo = lo
        self.hi = hi
        self.dtype = dtype
        self.taints = set(taints)
        self.guards = set(guards)
        # None = could be anything; a set = positively-known candidates
        self.values = set(values) if values is not None else None
        self.sentinels = set(sentinels)

    # -- lattice operations ------------------------------------------------

    def copy(self) -> "AbsVal":
        return AbsVal(self.lo, self.hi, self.dtype, self.taints,
                      self.guards, self.values, self.sentinels)

    def join(self, other: "AbsVal") -> "AbsVal":
        self.lo = min(self.lo, other.lo)
        self.hi = max(self.hi, other.hi)
        if self.dtype != other.dtype:
            self.dtype = None
        self.taints |= other.taints
        self.guards &= other.guards
        if self.values is None or other.values is None:
            self.values = None
        else:
            self.values |= other.values
            if len(self.values) > self._VALUES_CAP:
                self.values = None
        self.sentinels |= other.sentinels
        return self

    def join_element(self, stored: "AbsVal") -> None:
        """An element store (``arr[i] = v``): the array keeps its dtype —
        that coercion is exactly what GL601 polices — but its CONTENT
        hull, taints and value set absorb the stored value."""
        self.lo = min(self.lo, stored.lo)
        self.hi = max(self.hi, stored.hi)
        self.taints |= stored.taints
        self.guards &= stored.guards
        if self.values is None or stored.values is None:
            self.values = None
        else:
            self.values |= stored.values
            if len(self.values) > self._VALUES_CAP:
                self.values = None
        self.sentinels |= stored.sentinels

    # -- queries the rules ask ---------------------------------------------

    @property
    def known(self) -> bool:
        return self.lo != -INF or self.hi != INF

    def within(self, lo: float, hi: float) -> bool:
        """Positively known to fit [lo, hi]."""
        return self.lo >= lo and self.hi <= hi

    def fits_dtype(self, dtype: str) -> bool:
        b = INT_BOUNDS.get(dtype)
        return b is not None and self.within(b[0], b[1])

    def live_values(self) -> frozenset:
        return frozenset(self.values or ())

    def __repr__(self) -> str:  # debugging aid, not part of any contract
        return (
            f"AbsVal([{self.lo}, {self.hi}], dtype={self.dtype},"
            f" taints={sorted(self.taints)}, guards={sorted(self.guards)},"
            f" values={self.values}, sentinels={sorted(self.sentinels)})"
        )


def _unknown() -> AbsVal:
    return AbsVal()


def _mentions_name(expr: ast.AST, name: str) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id == name for n in ast.walk(expr)
    )


def _literal_number(node: ast.AST):
    """int/float of a literal expression (``-1`` is UnaryOp(USub, 1)),
    None otherwise. Bools are NOT numbers here."""
    if isinstance(node, ast.Constant):
        v = node.value
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return v
        return None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _literal_number(node.operand)
        return -v if v is not None else None
    return None


def _dtype_name(node: ast.AST) -> Optional[str]:
    """'int32' from np.int32 / jnp.int32 / 'int32' / "int32"-ish nodes."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in INT_BOUNDS else None
    name = dotted_name(node)
    tail = name.rsplit(".", 1)[-1] if name else ""
    return tail if tail in INT_BOUNDS else None


def _wire_decoder(pf: ParsedFile, fn) -> bool:
    """Functions whose parameters are wire payloads: the solver codec's
    decode family (decode_* / _decode_*) in solver/ modules. Kept narrow
    on purpose — the models/ decode phase decodes DEVICE results, not
    attacker-reachable bytes, and a wide seed would drown GL601 in host
    noise."""
    if "/solver/" not in f"/{pf.relpath}":
        return False
    name = getattr(fn, "name", "")
    return name.startswith("decode") or name.startswith("_decode")


class RangeDataflow:
    """Interval/dtype/taint queries over one scanned file set.

    Structured exactly like :class:`ProjectDataflow` (same eager two-pass
    summary construction, same weak memoization, same name-tail call
    resolution) over :class:`AbsVal` instead of a tag set. Use
    :func:`get_ranges`."""

    def __init__(self, files: List[ParsedFile]):
        self.files = files
        self.defs: Dict[str, List] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        for pf in files:
            for node in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
                self.defs.setdefault(node.name, []).append((pf, node))
            for node in pf.walk(ast.ClassDef):
                self.classes.setdefault(node.name, node)
        self.attr_summary: Dict[str, AbsVal] = {}
        # module-level integer constants, project-wide by bare name: lets
        # `gangmod.GANG_FALLBACK_STRADDLING` (an Attribute read of another
        # module) resolve to its literal so sentinel liveness survives the
        # ISSUE 11 constant hoist instead of only seeing raw -2 literals
        self.module_constants: Dict[str, AbsVal] = {}
        for pf in files:
            for st in pf.tree.body:
                if not isinstance(st, ast.Assign):
                    continue
                v = _literal_number(st.value)
                if v is None:
                    continue
                for tgt in st.targets:
                    if isinstance(tgt, ast.Name):
                        cur = self.module_constants.get(tgt.id)
                        nv = AbsVal(
                            lo=v, hi=v,
                            values={v} if isinstance(v, int) else None,
                        )
                        if cur is None:
                            self.module_constants[tgt.id] = nv
                        else:
                            cur.join(nv)
        self._summaries = weakref.WeakKeyDictionary()
        self._envs = weakref.WeakKeyDictionary()
        self._in_progress: Set[int] = set()
        for _ in range(2):
            self._summaries.clear()
            self._envs.clear()
            for pf in files:
                self._env_for(pf, None)
                for node in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
                    self._env_for(pf, node)

    # -- public query ------------------------------------------------------

    def absval(self, pf: ParsedFile, expr: ast.AST, fn) -> AbsVal:
        """Abstract value of an expression in the local environment of
        ``fn`` (None = module level)."""
        env = self._env_for(pf, fn)
        return self._eval(pf, expr, env, _MAX_DEPTH)

    # -- environments ------------------------------------------------------

    def _env_for(self, pf: ParsedFile, fn) -> Dict[str, AbsVal]:
        key = fn if fn is not None else pf.tree
        cached = self._envs.get(key)
        if cached is not None:
            return cached
        env: Dict[str, AbsVal] = {}
        self._envs[key] = env  # pre-bind: cycles read the partial env
        if isinstance(fn, ast.Lambda):
            return env
        if fn is not None and _wire_decoder(pf, fn):
            args = fn.args
            for a in (
                list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs)
            ):
                if a.arg != "self":
                    env[a.arg] = AbsVal(taints={WIRE})
        body = pf.tree.body if fn is None else fn.body
        self._walk_stmts(pf, body, env, _MAX_DEPTH)
        return env

    def _join_into(self, env, name: str, val: AbsVal) -> None:
        cur = env.get(name)
        if cur is None:
            env[name] = val.copy()
        else:
            cur.join(val)

    def _walk_stmts(self, pf, stmts, env, depth, flow=True) -> None:
        """``flow`` marks straight-line code that unconditionally executes
        on every path through the enclosing scope: plain-Name assignments
        there are STRONG updates (the binding is replaced), while inside
        a branch/loop/try body they join with the fall-through binding.
        Without the strong update, `n = np.clip(n, lo, hi)` would join
        the clipped value with the old unclamped one and (guards being
        intersection-joined) strip the very guard the clip granted — a
        GL601 false positive on its own recommended remediation. A
        self-referencing RHS (``x = f(x)``) is strong even inside a
        branch: the old binding already flowed into the evaluation, and
        degrading the not-taken path to the refined value errs toward
        silence, never noise."""
        for st in stmts:
            if isinstance(
                st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(st, ast.Assign):
                v = self._eval(pf, st.value, env, depth)
                strong = flow or (
                    len(st.targets) == 1
                    and isinstance(st.targets[0], ast.Name)
                    and _mentions_name(st.value, st.targets[0].id)
                )
                for tgt in st.targets:
                    self._bind(pf, tgt, st.value, v, env, depth,
                               strong=strong)
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                v = self._eval(pf, st.value, env, depth)
                self._bind(pf, st.target, st.value, v, env, depth,
                           strong=flow)
            elif isinstance(st, ast.AugAssign):
                # x += t joins the RECOMPUTED x ⊕ t with the old x — the
                # branch-insensitive hull a clamp-saturation check needs
                # (GL603 reads the final accumulated interval)
                old = self._eval(pf, st.target, env, depth)
                rhs = self._eval(pf, st.value, env, depth)
                new = self._arith(type(st.op), old, rhs)
                if isinstance(st.target, ast.Name):
                    self._join_into(env, st.target.id, new)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                it = self._eval(pf, st.iter, env, depth)
                self._bind_loop_target(pf, st.target, it, env)
                self._walk_stmts(pf, st.body, env, depth, flow=False)
                self._walk_stmts(pf, st.orelse, env, depth, flow=False)
            elif isinstance(st, (ast.If, ast.While)):
                if isinstance(st, ast.If):
                    self._eval(pf, st.test, env, depth)
                self._walk_stmts(pf, st.body, env, depth, flow=False)
                self._walk_stmts(pf, st.orelse, env, depth, flow=False)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    if item.optional_vars is not None:
                        v = self._eval(pf, item.context_expr, env, depth)
                        self._bind(
                            pf, item.optional_vars, item.context_expr, v,
                            env, depth, strong=flow,
                        )
                # a with-body executes unconditionally: flow carries over
                self._walk_stmts(pf, st.body, env, depth, flow=flow)
            elif isinstance(st, ast.Try):
                # a try body may execute PARTIALLY — bindings join
                self._walk_stmts(pf, st.body, env, depth, flow=False)
                for h in st.handlers:
                    self._walk_stmts(pf, h.body, env, depth, flow=False)
                self._walk_stmts(pf, st.orelse, env, depth, flow=False)
                self._walk_stmts(pf, st.finalbody, env, depth, flow=False)
            elif isinstance(st, (ast.Return, ast.Expr)):
                if st.value is not None:
                    self._eval(pf, st.value, env, depth)

    def _bind_loop_target(self, pf, target, iter_val: AbsVal, env) -> None:
        """Iterating an array yields elements with the array's dtype,
        hull, taints and values (the evictable-plane row walk)."""
        if isinstance(target, ast.Name):
            self._join_into(env, target.id, iter_val)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for t in target.elts:
                self._bind_loop_target(pf, t, iter_val, env)

    def _bind(self, pf, target, value, val: AbsVal, env, depth,
              strong=False) -> None:
        if isinstance(target, ast.Name):
            if strong:
                env[target.id] = val.copy()
            else:
                self._join_into(env, target.id, val)
        elif isinstance(target, ast.Starred):
            self._bind(pf, target.value, value, val, env, depth,
                       strong=strong)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) and len(
                value.elts
            ) == len(target.elts):
                for t, v in zip(target.elts, value.elts):
                    self._bind(
                        pf, t, v, self._eval(pf, v, env, depth), env,
                        depth, strong=strong,
                    )
            else:
                for t in target.elts:
                    self._bind(pf, t, value, val, env, depth,
                               strong=strong)
        elif isinstance(target, ast.Attribute):
            if not (isinstance(value, ast.Constant) and value.value is None):
                cur = self.attr_summary.get(target.attr)
                if cur is None:
                    self.attr_summary[target.attr] = val.copy()
                else:
                    cur.join(val)
        elif isinstance(target, ast.Subscript):
            # element store: the base array absorbs the stored content
            if isinstance(target.value, ast.Name):
                base = env.get(target.value.id)
                if base is not None:
                    base.join_element(val)

    # -- expression evaluation ---------------------------------------------

    def _eval(self, pf, node: ast.AST, env, depth) -> AbsVal:
        if isinstance(node, ast.Constant):
            v = node.value
            if isinstance(v, bool):
                return AbsVal(lo=0, hi=1, values={int(v)})
            if isinstance(v, int):
                return AbsVal(lo=v, hi=v, values={v})
            if isinstance(v, float):
                return AbsVal(lo=v, hi=v)
            return _unknown()
        if isinstance(node, ast.Name):
            out = env.get(node.id)
            if out is None:
                out = self.module_constants.get(node.id)
            out = out.copy() if out is not None else _unknown()
            dom = sentinel_domain_of(node.id)
            if dom is not None:
                out.sentinels.add(dom)
            return out
        if isinstance(node, ast.Attribute):
            if node.attr in _METADATA_ATTRS:
                return _unknown()
            base = self._eval(pf, node.value, env, depth)
            summary = self.attr_summary.get(node.attr)
            if summary is None:
                summary = self.module_constants.get(node.attr)
            # the attribute summary is FIELD-sensitive (every recorded
            # store of this name, project-wide) while the base's own
            # abstract value conflates a struct's fields — prefer the
            # summary whenever one exists, else carry the container's
            # taints (a wire dict's unrecorded members are wire)
            if summary is not None:
                out = summary.copy()
            elif base.taints or base.sentinels:
                # a tainted container's field reads keep the taints (a
                # wire dict's members are wire) but not its numeric state
                out = AbsVal(taints=base.taints, sentinels=base.sentinels)
            else:
                out = _unknown()
            dom = sentinel_domain_of(node.attr)
            if dom is not None:
                out.sentinels.add(dom)
            return out
        if isinstance(node, ast.Call):
            return self._eval_call(pf, node, env, depth)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = None
            for e in node.elts:
                v = self._eval(pf, e, env, depth)
                out = v if out is None else out.join(v)
            return out if out is not None else _unknown()
        if isinstance(node, ast.Dict):
            out = None
            for v_node in node.values:
                if v_node is None:
                    continue
                v = self._eval(pf, v_node, env, depth)
                out = v if out is None else out.join(v)
            return out if out is not None else _unknown()
        if isinstance(node, ast.Subscript):
            base = self._eval(pf, node.value, env, depth)
            self._eval(pf, node.slice, env, depth)
            out = base.copy()
            # slicing/indexing off an array is how padding is windowed
            # away (the used-slot fetch) — drop the pad taint, keep the
            # rest (an element of a wire dict is wire; an element of an
            # int32 plane is an int32 scalar)
            out.taints.discard(PAD)
            return out
        if isinstance(node, ast.IfExp):
            self._eval(pf, node.test, env, depth)
            return self._eval(pf, node.body, env, depth).join(
                self._eval(pf, node.orelse, env, depth)
            )
        if isinstance(node, ast.BinOp):
            left = self._eval(pf, node.left, env, depth)
            right = self._eval(pf, node.right, env, depth)
            return self._arith(type(node.op), left, right)
        if isinstance(node, ast.UnaryOp):
            v = self._eval(pf, node.operand, env, depth)
            if isinstance(node.op, ast.USub):
                lo, hi = v.lo, v.hi
                v.lo, v.hi = -hi, -lo
                if v.values is not None:
                    v.values = {-x for x in v.values}
            return v
        if isinstance(node, ast.BoolOp):
            out = None
            for e in node.values:
                v = self._eval(pf, e, env, depth)
                out = v if out is None else out.join(v)
            return out if out is not None else _unknown()
        if isinstance(node, ast.Compare):
            self._eval(pf, node.left, env, depth)
            for c in node.comparators:
                self._eval(pf, c, env, depth)
            return AbsVal(lo=0, hi=1, values={0, 1})
        if isinstance(node, ast.NamedExpr):
            v = self._eval(pf, node.value, env, depth)
            self._join_into(env, node.target.id, v)
            return v
        if isinstance(node, ast.Starred):
            return self._eval(pf, node.value, env, depth)
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            # the codec's decode loops are comprehensions (`tuple(
            # EvictablePod(...) for e in d.get("evictable", ()))`): bind
            # each target from its iter so the element expression — and
            # its constructor-recording side effects — evaluates in scope
            for gen in node.generators:
                it = self._eval(pf, gen.iter, env, depth)
                self._bind_loop_target(pf, gen.target, it, env)
                for cond in gen.ifs:
                    self._eval(pf, cond, env, depth)
            if isinstance(node, ast.DictComp):
                self._eval(pf, node.key, env, depth)
                return self._eval(pf, node.value, env, depth)
            return self._eval(pf, node.elt, env, depth)
        return _unknown()

    @staticmethod
    def _arith(op, left: AbsVal, right: AbsVal) -> AbsVal:
        out = AbsVal(
            taints=left.taints | right.taints,
            sentinels=left.sentinels | right.sentinels,
        )
        if op is ast.Add:
            out.lo, out.hi = left.lo + right.lo, left.hi + right.hi
        elif op is ast.Sub:
            out.lo, out.hi = left.lo - right.hi, left.hi - right.lo
        elif op is ast.Mult and left.known and right.known:
            prods = [left.lo * right.lo, left.lo * right.hi,
                     left.hi * right.lo, left.hi * right.hi]
            out.lo, out.hi = min(prods), max(prods)
        elif op is ast.Div and right.known and (
            right.lo > 0 or right.hi < 0
        ) and left.known:
            quots = [left.lo / right.lo, left.lo / right.hi,
                     left.hi / right.lo, left.hi / right.hi]
            out.lo, out.hi = min(quots), max(quots)
        # every other operator: unknown interval, taints carried
        return out

    def _eval_call(self, pf, node: ast.Call, env, depth) -> AbsVal:
        name = dotted_name(node.func)
        tail = name.rsplit(".", 1)[-1] if name else ""

        # registered normalizers: bounded codomain + the CLAMPED guard
        norm = RANGE_NORMALIZERS.get(tail)
        if norm is not None:
            for a in node.args:
                self._eval(pf, a, env, depth)
            return AbsVal(lo=norm[0], hi=norm[1], guards={CLAMPED})

        if tail in _CLIP_CALLS:
            # np.clip(x, lo, hi) / x.clip(lo, hi): the explicit-clip form
            args = list(node.args)
            if isinstance(node.func, ast.Attribute) and name not in (
                "np.clip", "jnp.clip", "numpy.clip", "jax.numpy.clip",
            ):
                args = [node.func.value] + args  # method form
            vals = [self._eval(pf, a, env, depth) for a in args]
            for kw in node.keywords:
                self._eval(pf, kw.value, env, depth)
            out = vals[0].copy() if vals else _unknown()
            if len(vals) >= 2 and vals[1].known:
                out.lo = max(out.lo, vals[1].lo)
            if len(vals) >= 3 and vals[2].known:
                out.hi = min(out.hi, vals[2].hi)
                out.lo = min(out.lo, out.hi)
            out.guards.add(CLAMPED)
            out.values = None
            return out

        if tail in ("min", "max") and name in ("min", "max") and len(
            node.args
        ) >= 2:
            vals = [self._eval(pf, a, env, depth) for a in node.args]
            out = AbsVal(
                taints=set().union(*(v.taints for v in vals)),
                sentinels=set().union(*(v.sentinels for v in vals)),
            )
            if tail == "min":
                out.lo = min(v.lo for v in vals)
                out.hi = min(v.hi for v in vals)
            else:
                out.lo = max(v.lo for v in vals)
                out.hi = max(v.hi for v in vals)
            return out

        if name == "abs" and node.args:
            v = self._eval(pf, node.args[0], env, depth)
            out = AbsVal(taints=v.taints, sentinels=v.sentinels)
            if v.known:
                mags = [abs(v.lo), abs(v.hi)]
                out.hi = max(mags)
                out.lo = 0.0 if v.lo <= 0 <= v.hi else min(mags)
            else:
                out.lo = 0.0
            return out

        if name in ("int", "float", "bool") and node.args:
            v = self._eval(pf, node.args[0], env, depth)
            out = v.copy()
            out.dtype = None  # a python scalar has no storage width
            return out

        if tail in _MASK_CALLS and len(node.args) >= 2:
            # jnp.where(cond, x, y): the masking step — padded content is
            # neutralized by construction
            self._eval(pf, node.args[0], env, depth)
            out = self._eval(pf, node.args[1], env, depth)
            for a in node.args[2:]:
                out.join(self._eval(pf, a, env, depth))
            out.guards.add(MASKED)
            return out

        if tail in _PAD_SIZE_CALLS:
            for a in node.args:
                self._eval(pf, a, env, depth)
            return AbsVal(lo=0, taints={PADSIZE})

        if tail in _PAD_ARRAY_CALLS:
            args = [self._eval(pf, a, env, depth) for a in node.args]
            out = args[0].copy() if args else _unknown()
            out.lo, out.hi = -INF, INF  # the fill extends the hull
            out.values = None
            out.taints.add(PAD)
            return out

        if tail in _ARRAY_CTORS and (
            name.startswith(_NP_PREFIXES) or name.startswith(_JNP_PREFIXES)
        ):
            return self._eval_array_ctor(pf, node, env, depth, tail)

        if tail == "astype" and isinstance(node.func, ast.Attribute):
            src = self._eval(pf, node.func.value, env, depth)
            out = src.copy()
            if node.args:
                dt = _dtype_name(node.args[0])
                if dt is not None:
                    out.dtype = dt
                    if not src.fits_dtype(dt):
                        # astype WRAPS out-of-range values: the interval
                        # is no longer the source's
                        out.lo, out.hi = -INF, INF
                        out.values = None
            return out

        if tail == "get" and isinstance(node.func, ast.Attribute):
            base = self._eval(pf, node.func.value, env, depth)
            out = AbsVal(taints=base.taints, sentinels=base.sentinels)
            if len(node.args) >= 2:
                out.join(self._eval(pf, node.args[1], env, depth))
            return out

        if tail == "_replace" and isinstance(node.func, ast.Attribute):
            out = self._eval(pf, node.func.value, env, depth)
            for kw in node.keywords:
                out.join(self._eval(pf, kw.value, env, depth))
            return out

        # constructor call: record keyword fields in the attribute
        # summary (the EvictablePod(priority=...) chain GL601 resolves)
        cls = self.classes.get(tail)
        if cls is not None or (tail[:1].isupper() and tail not in self.defs):
            out = None
            for a in node.args:
                v = self._eval(pf, a, env, depth)
                out = v if out is None else out.join(v)
            for kw in node.keywords:
                kv = self._eval(pf, kw.value, env, depth)
                if kw.arg:
                    cur = self.attr_summary.get(kw.arg)
                    if cur is None:
                        self.attr_summary[kw.arg] = kv.copy()
                    else:
                        cur.join(kv)
                out = kv if out is None else out.join(kv)
            return out if out is not None else _unknown()

        # project function/method: join the return summaries
        candidates = self.defs.get(tail, ())
        if candidates and depth > 0:
            out = None
            for cpf, fn in candidates[:_MAX_CANDIDATES]:
                s = self._summary(cpf, fn, depth - 1)
                out = s.copy() if out is None else out.join(s)
            for a in node.args:
                self._eval(pf, a, env, depth)
            for kw in node.keywords:
                self._eval(pf, kw.value, env, depth)
            return out if out is not None else _unknown()

        for a in node.args:
            self._eval(pf, a, env, depth)
        for kw in node.keywords:
            self._eval(pf, kw.value, env, depth)
        return _unknown()

    def _eval_array_ctor(self, pf, node, env, depth, tail) -> AbsVal:
        out = AbsVal()
        dt = None
        for kw in node.keywords:
            if kw.arg == "dtype":
                dt = _dtype_name(kw.value)
            else:
                self._eval(pf, kw.value, env, depth)
        args = [self._eval(pf, a, env, depth) for a in node.args]
        if dt is None and len(node.args) >= 3 and tail == "full":
            dt = _dtype_name(node.args[2])
        out.dtype = dt
        if tail in ("zeros", "ones", "zeros_like", "ones_like"):
            fill = 0 if tail.startswith("zeros") else 1
            out.lo = out.hi = float(fill)
            out.values = {fill}
        elif tail in ("full", "full_like") and len(args) >= 2:
            fill = args[1]
            out.lo, out.hi = fill.lo, fill.hi
            out.values = set(fill.values) if fill.values is not None else None
            out.taints |= fill.taints
            out.sentinels |= fill.sentinels
        elif tail == "arange":
            out.lo = 0.0
            out.values = None
        elif tail in ("array", "asarray") and args:
            src = args[0]
            out.lo, out.hi = src.lo, src.hi
            out.values = set(src.values) if src.values is not None else None
            out.taints |= src.taints
            out.guards = set(src.guards)
            out.sentinels |= src.sentinels
        # a PADSIZE-shaped constructor mints padded content
        if args and PADSIZE in args[0].taints:
            out.taints.add(PAD)
        return out

    def _summary(self, pf, fn, depth) -> AbsVal:
        cached = self._summaries.get(fn)
        if cached is not None:
            return cached
        if id(fn) in self._in_progress or depth <= 0:
            # recursion (or the depth cap): widen to TOP immediately — the
            # termination guarantee the engine tests pin
            return _unknown()
        self._in_progress.add(id(fn))
        try:
            env = self._env_for(pf, fn)
            out = None
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    owner = pf.enclosing_function(node)
                    if owner is fn:
                        v = self._eval(pf, node.value, env, depth)
                        out = v if out is None else out.join(v)
            result = out if out is not None else _unknown()
            self._summaries[fn] = result
            return result
        finally:
            self._in_progress.discard(id(fn))


_RANGE_CACHE: Dict[str, RangeDataflow] = {}


def get_ranges(files: List[ParsedFile]) -> RangeDataflow:
    """The (content-hash cached) range-domain index for one scanned set."""
    key = _content_key(files)
    df = _RANGE_CACHE.get(key)
    if df is None:
        df = RangeDataflow(files)
        if len(_RANGE_CACHE) > 8:
            _RANGE_CACHE.clear()
        _RANGE_CACHE[key] = df
    return df


# ===========================================================================
# Third abstract domain (ISSUE 19): lock identity, may-held sets, thread
# reachability — the engine under the GL7xx lockgraph family.
#
# The provenance lattice answers "WHERE has this array been?", the range
# domain "WHAT can this integer BE?". The solver tier's concurrency
# contract needs a third question answered per program point: "WHICH
# locks may be held HERE, and which thread can get here?" — the inputs to
# a lock-order graph (deadlock cycles), to guard inference (which lock
# owns which mutable attribute), and to thread-escape checks.
#
# Identity and join discipline:
#
# * a LOCK is identified by (owning class, attribute) — "FleetGateway.
#   _lock" — for ``self._x = threading.Lock()`` attributes, and by
#   (module relpath, name) for module-level locks. ``self.X`` only ever
#   resolves against the ENCLOSING class: merging every class's ``_lock``
#   into one node would invent edges between unrelated objects.
# * HELD SETS are may-held and join by UNION over call sites. That is the
#   sound polarity for every consumer: GL701 edges only ADD (a spurious
#   may-edge needs a full spurious cycle before it reports), and GL702
#   flags only when the inferred guard is ABSENT from the may-held set —
#   absent-from-an-over-approximation means definitely never held.
# * held-set propagation resolves calls PRECISELY only: ``self.meth()``
#   to the enclosing class (plus textual bases), ``self.attr.meth()``
#   through constructor-typed attributes (``self.gateway =
#   FleetGateway()``), and bare names to same-file module defs. Name-tail
#   fallback is deliberately excluded here — resolving ``t.start()`` into
#   every ``start`` def would flood entry sets with phantom locks.
# * THREAD REACHABILITY starts from Thread(target=...) functions and
#   ``do_*`` methods of HTTP handler classes and closes over the call
#   graph; here the loose name-tail fallback (stoplisted, candidate-
#   capped) IS used, because the HTTP handler reaches the daemon through
#   ``self.server.daemon.solve()`` — an attribute chain precise
#   resolution cannot type.
# * GUARD INFERENCE is per (class, attribute): the lock held at a STRICT
#   MAJORITY of the attribute's write sites. A tie — or no lock reaching
#   half — infers nothing, and every consumer of a missing inference
#   stays silent.
# ===========================================================================

_LOCK_CTOR_KINDS = {
    "threading.Lock": "Lock", "Lock": "Lock",
    "threading.RLock": "RLock", "RLock": "RLock",
    "threading.Condition": "Condition", "Condition": "Condition",
}
_EVENT_CTORS = {"threading.Event", "Event"}
_THREAD_CTORS = {"threading.Thread", "Thread"}

# mutable-container constructors: an attribute initialized to one of
# these is a SHARED MUTABLE VALUE (GL703's escape subjects); scalars are
# rebound, never mutated in place
_MUTABLE_CTORS = {
    "dict", "list", "set", "OrderedDict", "collections.OrderedDict",
    "deque", "collections.deque", "defaultdict", "collections.defaultdict",
}

# in-place mutator method names (the write-site forms beyond = and +=)
_MUTATING_METHODS = {
    "append", "extend", "insert", "pop", "remove", "clear", "update",
    "setdefault", "popitem", "add", "discard", "move_to_end",
}

_REACH_MAX_CANDIDATES = 4
# ubiquitous call tails reachability must not resolve through: name-tail
# resolution would connect ``cache.get`` to every ``get`` def and mark
# half the project thread-reachable
_REACH_STOPLIST = frozenset({
    "get", "put", "set", "add", "pop", "remove", "clear", "update",
    "append", "extend", "items", "values", "keys", "close", "encode",
    "decode", "info", "debug", "warning", "error", "exception", "log",
    "inc", "observe", "wait", "join", "acquire", "release", "next",
    "copy", "sort", "split", "strip", "read", "write", "open", "format",
    "render", "render_line", "stats", "len", "min", "max",
})


def _module_stem(relpath: str) -> str:
    base = relpath.rsplit("/", 1)[-1]
    return base[:-3] if base.endswith(".py") else base


class LockSite:
    """One attribute write site with its may-held lock set."""

    __slots__ = ("pf", "node", "fn", "held", "kind")

    def __init__(self, pf, node, fn, held, kind):
        self.pf = pf
        self.node = node
        self.fn = fn  # enclosing FunctionDef (None at class/module level)
        self.held = held  # frozenset of lock ids
        self.kind = kind  # "assign" | "augassign" | "mutate" | "del"


class LockDataflow:
    """Lock/thread queries over one scanned file set. Use :func:`get_locks`.

    Public surface the GL7xx rules (and the runtime witness test) consume:

    - ``order_edges``: {(held_id, acquired_id): [(relpath, line, via)]} —
      the directed acquired-while-held graph, ``via`` in
      {"nested", "wait", "join"};
    - ``self_deadlocks``: [(lock_id, relpath, line, reason)] — one-edge
      deadlocks (non-reentrant re-acquire, waiting on an event whose
      setter needs a lock the waiter holds, joining a thread that
      acquires one);
    - ``cycles()``: the strongly-connected components of the order graph
      with ≥ 2 locks;
    - ``inferred_guards``: {class: {attr: lock_id}};
    - ``write_sites``: {(class, attr): [LockSite]};
    - ``held_at(pf, node)``: may-held lock ids at one AST node;
    - ``thread_reachable(pf, fn)``: whether a def can run on a spawned
      thread (Thread targets, HTTP ``do_*`` handlers, and everything the
      call graph reaches from them);
    - ``lock_kinds``: {lock_id: "Lock" | "RLock" | "Condition"};
    - ``class_locks`` / ``event_attrs`` / ``cond_attrs``: the per-class
      attribute registries.
    """

    def __init__(self, files: List[ParsedFile]):
        self.files = files
        # class name -> set of lock attr names / lock_id -> ctor kind
        self.class_locks: Dict[str, Set[str]] = {}
        self.lock_kinds: Dict[str, str] = {}
        # (class, attr) -> "Event" | "Condition"; plus a name-keyed union
        # for receiver objects precise typing cannot reach (ticket.event)
        self.event_attrs: Dict[tuple, str] = {}
        self.cond_attrs: Dict[tuple, str] = {}
        self._event_names: Dict[str, str] = {}
        # (class, attr) -> class name of the constructor-assigned value
        self.attr_types: Dict[tuple, str] = {}
        # (class, attr) -> attr holds a mutable container (GL703 subjects)
        self.mutable_attrs: Set[tuple] = set()
        # (class, attr) -> thread-target def ids (self._thread = Thread(
        # target=self._loop)) for join-edge resolution
        self._thread_attr_targets: Dict[tuple, List[int]] = {}
        # per-relpath module-level lock names -> lock id
        self._module_locks: Dict[str, Dict[str, str]] = {}

        # def indexes: stable fid -> (pf, fn, owning class name or None).
        # fids are (relpath, lineno, name) — NOT id(fn) — because this
        # index is content-hash cached across run() calls while every run
        # hands the rules freshly parsed nodes; an id()-keyed lookup would
        # silently miss on the warm run and every query would lie
        self.fn_index: Dict[tuple, tuple] = {}
        self._methods: Dict[tuple, List[int]] = {}
        self._module_defs: Dict[tuple, List[int]] = {}
        self._defs_by_tail: Dict[str, List[int]] = {}
        self._class_bases: Dict[str, List[str]] = {}
        self._class_defs: Dict[str, List[tuple]] = {}

        self._index(files)
        # per-fn lexical lock spans: fid -> [(lock_id, lo, hi, node)]
        self._spans: Dict[int, list] = {
            fid: self._lock_spans(*self.fn_index[fid][:2])
            for fid in self.fn_index
        }
        self._entry_held: Dict[int, Set[str]] = {
            fid: set() for fid in self.fn_index
        }
        self._acquires: Dict[int, Set[str]] = {}
        self._propagate()
        self._reachable: Set[int] = set()
        self._mark_thread_reachable()

        self.order_edges: Dict[tuple, list] = {}
        self.self_deadlocks: list = []
        self._build_order_graph()

        self.write_sites: Dict[tuple, List[LockSite]] = {}
        self.inferred_guards: Dict[str, Dict[str, str]] = {}
        self._collect_writes()
        self._infer_guards()

    # -- indexing ----------------------------------------------------------

    def _index(self, files: List[ParsedFile]) -> None:
        pending_threads: list = []
        for pf in files:
            mod_locks: Dict[str, str] = {}
            for st in pf.tree.body:
                if not isinstance(st, ast.Assign):
                    continue
                if not isinstance(st.value, ast.Call):
                    continue
                kind = _LOCK_CTOR_KINDS.get(dotted_name(st.value.func))
                if kind is None:
                    continue
                for tgt in st.targets:
                    if isinstance(tgt, ast.Name):
                        lid = f"{_module_stem(pf.relpath)}.{tgt.id}"
                        mod_locks[tgt.id] = lid
                        self.lock_kinds[lid] = kind
            self._module_locks[pf.relpath] = mod_locks

            for cls in pf.walk(ast.ClassDef):
                self._class_defs.setdefault(cls.name, []).append((pf, cls))
                bases = [dotted_name(b) for b in cls.bases]
                self._class_bases.setdefault(cls.name, []).extend(
                    b for b in bases if b
                )
                for node in ast.walk(cls):
                    if not isinstance(node, ast.Assign):
                        continue
                    # mutable literals: self.x = {} / [] / {…} / comps
                    if isinstance(node.value, (
                        ast.Dict, ast.List, ast.Set, ast.ListComp,
                        ast.DictComp, ast.SetComp,
                    )):
                        for tgt in node.targets:
                            attr = _self_attr_of(tgt)
                            if attr is not None:
                                self.mutable_attrs.add((cls.name, attr))
                        continue
                    for value in _ctor_candidates(node.value):
                        ctor = dotted_name(value.func)
                        tail = ctor.rsplit(".", 1)[-1] if ctor else ""
                        for tgt in node.targets:
                            attr = _self_attr_of(tgt)
                            if attr is None:
                                continue
                            kind = _LOCK_CTOR_KINDS.get(ctor)
                            if kind is not None:
                                self.class_locks.setdefault(
                                    cls.name, set()
                                ).add(attr)
                                self.lock_kinds[f"{cls.name}.{attr}"] = kind
                                if kind == "Condition":
                                    self.cond_attrs[(cls.name, attr)] = kind
                                    self._event_names.setdefault(
                                        attr, "Condition"
                                    )
                                continue
                            if ctor in _EVENT_CTORS:
                                self.event_attrs[(cls.name, attr)] = "Event"
                                self._event_names.setdefault(attr, "Event")
                                continue
                            if ctor in _THREAD_CTORS:
                                # resolved after the def index exists —
                                # _methods is still empty on this pass
                                pending_threads.append(
                                    (cls.name, attr, pf, value)
                                )
                                continue
                            if (ctor in _MUTABLE_CTORS
                                    or tail in _MUTABLE_CTORS):
                                self.mutable_attrs.add((cls.name, attr))
                            if tail[:1].isupper():
                                # constructor-assigned type, for cross-
                                # object method resolution
                                self.attr_types[(cls.name, attr)] = tail

            for fn in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
                cls = pf.enclosing_class(fn)
                cname = cls.name if cls is not None else None
                fid = _fn_key(pf, fn)
                self.fn_index[fid] = (pf, fn, cname)
                self._defs_by_tail.setdefault(fn.name, []).append(fid)
                if cname is not None:
                    self._methods.setdefault(
                        (cname, fn.name), []
                    ).append(fid)
                if pf.enclosing_function(fn) is None and cls is None:
                    self._module_defs.setdefault(
                        (pf.relpath, fn.name), []
                    ).append(fid)

        for cname, attr, pf, call in pending_threads:
            tgt_ids = self._thread_target_ids(pf, cname, call)
            if tgt_ids:
                self._thread_attr_targets[(cname, attr)] = tgt_ids

    def _thread_target_ids(self, pf, cname, call: ast.Call) -> List[int]:
        """Resolve ``threading.Thread(target=X)``'s X to def ids."""
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None:
            return []
        attr = _self_attr_of(target)
        if attr is not None and cname is not None:
            return list(self._methods.get((cname, attr), ()))
        if isinstance(target, ast.Name):
            out = list(self._module_defs.get((pf.relpath, target.id), ()))
            if out:
                return out
            # a local def in the enclosing function (the autoscaler's
            # ``loop`` closure): resolved lazily by name within the file
            return [
                fid for fid, (fpf, fn, _c) in self.fn_index.items()
                if fpf.relpath == pf.relpath and fn.name == target.id
            ]
        return []

    # -- lexical lock spans ------------------------------------------------

    def _lock_id_of_expr(
        self, pf, cname: Optional[str], expr: ast.AST
    ) -> Optional[str]:
        """Lock id of a context/receiver expression, or None. ``self.X``
        resolves only against the enclosing class; a bare name against
        the module's lock table."""
        if isinstance(expr, ast.Call):
            expr = expr.func
        attr = _self_attr_of(expr)
        if attr is not None:
            if cname and attr in self.class_locks.get(cname, ()):
                return f"{cname}.{attr}"
            return None
        if isinstance(expr, ast.Name):
            return self._module_locks.get(pf.relpath, {}).get(expr.id)
        return None

    def _lock_spans(self, pf, fn) -> list:
        """[(lock_id, lo_line_exclusive, hi_line_inclusive, acquire_node)]
        for one def: ``with`` blocks plus explicit acquire()/release()
        call pairs (an unmatched acquire holds to the end of the def)."""
        cls = pf.enclosing_class(fn)
        cname = cls.name if cls is not None else None
        spans = []
        acquires: Dict[str, list] = {}
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith, ast.Call)):
                # honor nested-def boundaries: a closure's spans are its own
                if pf.enclosing_function(node) is not fn:
                    continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = self._lock_id_of_expr(pf, cname, item.context_expr)
                    if lid is not None:
                        spans.append((
                            lid, node.lineno,
                            getattr(node, "end_lineno", node.lineno),
                            item.context_expr,
                        ))
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if node.func.attr == "acquire":
                    lid = self._lock_id_of_expr(pf, cname, node.func.value)
                    if lid is not None:
                        acquires.setdefault(lid, []).append(node)
                elif node.func.attr == "release":
                    lid = self._lock_id_of_expr(pf, cname, node.func.value)
                    if lid is not None:
                        for pending in acquires.get(lid, ()):
                            spans.append((
                                lid, pending.lineno, node.lineno, pending
                            ))
                        acquires[lid] = []
        end = getattr(fn, "end_lineno", fn.lineno)
        for lid, pendings in acquires.items():
            for pending in pendings:
                spans.append((lid, pending.lineno, end, pending))
        return spans

    def _lexical_held(self, fid: int, lineno: int) -> Set[str]:
        return {
            lid for lid, lo, hi, _n in self._spans.get(fid, ())
            if lo < lineno <= hi
        }

    # -- call resolution ---------------------------------------------------

    def _bases_chain(self, cname: str, depth: int = 3) -> List[str]:
        out, frontier = [cname], [cname]
        for _ in range(depth):
            nxt = []
            for c in frontier:
                for b in self._class_bases.get(c, ()):
                    tail = b.rsplit(".", 1)[-1]
                    if tail not in out:
                        out.append(tail)
                        nxt.append(tail)
            frontier = nxt
        return out

    def _resolve_precise(
        self, pf, cname: Optional[str], call: ast.Call
    ) -> List[int]:
        func = call.func
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id == "self" and cname:
                out: List[int] = []
                for c in self._bases_chain(cname):
                    out.extend(self._methods.get((c, func.attr), ()))
                return out
            # self.attr.meth(): constructor-typed attribute
            battr = _self_attr_of(base)
            if battr is not None and cname is not None:
                tname = self.attr_types.get((cname, battr))
                if tname is not None:
                    return list(self._methods.get((tname, func.attr), ()))
            return []
        if isinstance(func, ast.Name):
            return list(self._module_defs.get((pf.relpath, func.id), ()))
        return []

    def _resolve_loose(
        self, pf, cname: Optional[str], call: ast.Call
    ) -> List[int]:
        out = self._resolve_precise(pf, cname, call)
        if out:
            return out
        name = dotted_name(call.func)
        tail = name.rsplit(".", 1)[-1] if name else ""
        if not tail or tail in _REACH_STOPLIST:
            return []
        cands = self._defs_by_tail.get(tail, ())
        if 0 < len(cands) <= _REACH_MAX_CANDIDATES:
            return list(cands)
        return []

    # -- fixpoints ---------------------------------------------------------

    def _propagate(self) -> None:
        """Two union fixpoints over the precise call graph: may-held sets
        pushed INTO callees, transitive acquire sets pulled FROM them."""
        call_edges: Dict[int, list] = {}
        for fid, (pf, fn, cname) in self.fn_index.items():
            edges = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callees = self._resolve_precise(pf, cname, node)
                    if callees:
                        edges.append((node.lineno, callees))
            call_edges[fid] = edges
            self._acquires[fid] = {
                lid for lid, _lo, _hi, _n in self._spans[fid]
            }
        while True:
            grew = False
            for fid, edges in call_edges.items():
                base = self._entry_held[fid]
                for lineno, callees in edges:
                    held = base | self._lexical_held(fid, lineno)
                    for cid in callees:
                        if cid == fid:
                            continue
                        tgt = self._entry_held[cid]
                        if not held <= tgt:
                            tgt |= held
                            grew = True
                        acq = self._acquires[cid]
                        if not acq <= self._acquires[fid]:
                            self._acquires[fid] |= acq
                            grew = True
            if not grew:
                return

    def _mark_thread_reachable(self) -> None:
        entries: List[int] = []
        for fid, (pf, fn, cname) in self.fn_index.items():
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and dotted_name(
                    node.func
                ) in _THREAD_CTORS:
                    entries.extend(
                        self._thread_target_ids(pf, cname, node)
                    )
            # HTTP handler entries: do_* methods of *RequestHandler classes
            if cname is not None and fn.name.startswith("do_"):
                bases = self._class_bases.get(cname, ())
                if any(b.rsplit(".", 1)[-1].endswith("RequestHandler")
                       for b in bases):
                    entries.append(fid)
        frontier = [fid for fid in entries if fid in self.fn_index]
        self._reachable = set(frontier)
        while frontier:
            fid = frontier.pop()
            pf, fn, cname = self.fn_index[fid]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                for cid in self._resolve_loose(pf, cname, node):
                    if cid not in self._reachable:
                        self._reachable.add(cid)
                        frontier.append(cid)

    # -- the lock-order graph ----------------------------------------------

    def _add_edge(self, src, dst, pf, lineno, via) -> None:
        self.order_edges.setdefault((src, dst), []).append(
            (pf.relpath, lineno, via)
        )

    def _event_setter_held(self) -> Dict[str, Set[str]]:
        """attr name -> union of may-held sets at every ``X.<attr>.set()``
        / ``X.<attr>.notify*()`` site (the locks a WAITER's waker needs)."""
        out: Dict[str, Set[str]] = {}
        for fid, (pf, fn, cname) in self.fn_index.items():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if not isinstance(func, ast.Attribute):
                    continue
                if func.attr not in ("set", "notify", "notify_all"):
                    continue
                if not isinstance(func.value, ast.Attribute):
                    continue
                ename = func.value.attr
                if ename not in self._event_names:
                    continue
                if func.attr == "set" and node.args:
                    continue  # dict.set(...)-style false friend
                held = self._entry_held[fid] | self._lexical_held(
                    fid, node.lineno
                )
                out.setdefault(ename, set()).update(held)
        return out

    def _build_order_graph(self) -> None:
        setter_held = self._event_setter_held()
        for fid, (pf, fn, cname) in self.fn_index.items():
            entry = self._entry_held[fid]
            # nested acquisitions: with-items and acquire() calls
            for lid, lo, _hi, node in self._spans[fid]:
                outer = entry | {
                    olid for olid, olo, ohi, onode in self._spans[fid]
                    if onode is not node and olo <= lo <= ohi
                    and not (olo == lo and onode.col_offset
                             > getattr(node, "col_offset", 1 << 30))
                }
                for held in sorted(outer):
                    if held == lid:
                        if self.lock_kinds.get(lid) == "Lock":
                            self.self_deadlocks.append((
                                lid, pf.relpath, lo,
                                f"non-reentrant Lock {lid} re-acquired"
                                " while already held",
                            ))
                        continue
                    self._add_edge(held, lid, pf, lo, "nested")
            # wait edges: blocking on an event/condition while holding
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if not isinstance(func, ast.Attribute):
                    continue
                held = entry | self._lexical_held(fid, node.lineno)
                if func.attr in ("wait", "wait_for") and isinstance(
                    func.value, ast.Attribute
                ):
                    ename = func.value.attr
                    if ename not in self._event_names:
                        continue
                    if self._event_names[ename] == "Condition":
                        # Condition.wait releases its own lock while blocked
                        own = _self_attr_of(func.value)
                        if own is not None and cname is not None:
                            held = held - {f"{cname}.{own}"}
                    for src in sorted(held):
                        for dst in sorted(setter_held.get(ename, ())):
                            if src == dst:
                                self.self_deadlocks.append((
                                    src, pf.relpath, node.lineno,
                                    f"waits on .{ename} while holding"
                                    f" {src}, which the waker needs",
                                ))
                            else:
                                self._add_edge(
                                    src, dst, pf, node.lineno, "wait"
                                )
                elif func.attr == "join" and held:
                    for tid in self._join_target_ids(pf, fn, cname, func):
                        needed = self._acquires.get(tid, set())
                        for src in sorted(held):
                            for dst in sorted(needed):
                                if src == dst:
                                    self.self_deadlocks.append((
                                        src, pf.relpath, node.lineno,
                                        f"joins a thread that acquires"
                                        f" {src} while holding it",
                                    ))
                                else:
                                    self._add_edge(
                                        src, dst, pf, node.lineno, "join"
                                    )

    def _join_target_ids(self, pf, fn, cname, func: ast.Attribute):
        """Thread-target def ids behind ``<recv>.join()``."""
        attr = _self_attr_of(func.value)
        if attr is not None and cname is not None:
            return self._thread_attr_targets.get((cname, attr), ())
        if isinstance(func.value, ast.Name):
            # a local ``t = threading.Thread(target=...)`` in the same def
            out: List[int] = []
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                if dotted_name(node.value.func) not in _THREAD_CTORS:
                    continue
                if any(isinstance(t, ast.Name) and t.id == func.value.id
                       for t in node.targets):
                    out.extend(
                        self._thread_target_ids(pf, cname, node.value)
                    )
            return out
        return ()

    def cycles(self) -> List[List[str]]:
        """Strongly-connected components of the order graph with ≥ 2
        locks — each is a deadlock-capable cycle. Iterative Tarjan (the
        graph is tiny, but recursion depth must not depend on it)."""
        graph: Dict[str, List[str]] = {}
        for (src, dst) in self.order_edges:
            graph.setdefault(src, []).append(dst)
            graph.setdefault(dst, [])
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]
        for root in sorted(graph):
            if root in index:
                continue
            work = [(root, iter(graph[root]))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(graph[w])))
                        advanced = True
                        break
                    if w in on_stack:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    pv = work[-1][0]
                    low[pv] = min(low[pv], low[v])
                if low[v] == index[v]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == v:
                            break
                    if len(scc) > 1:
                        out.append(sorted(scc))
        return out

    # -- guard inference ---------------------------------------------------

    def held_at(self, pf, node: ast.AST) -> frozenset:
        """May-held lock ids at one AST node (entry set of the enclosing
        def ∪ the lexical spans covering the node's line)."""
        fn = pf.enclosing_function(node)
        fid = _fn_key(pf, fn) if fn is not None else None
        if fid not in self.fn_index:
            # module/class level: lexical module locks only
            return frozenset()
        return frozenset(
            self._entry_held[fid] | self._lexical_held(fid, node.lineno)
        )

    def thread_reachable(self, pf, fn) -> bool:
        return fn is not None and _fn_key(pf, fn) in self._reachable

    def _collect_writes(self) -> None:
        for pf in self.files:
            for cls in pf.walk(ast.ClassDef):
                if cls.name in self.class_locks:
                    self._collect_class_writes(pf, cls)

    def _collect_class_writes(self, pf, cls: ast.ClassDef) -> None:
        locks = self.class_locks.get(cls.name, set())
        skip = locks | {
            a for (c, a) in self.event_attrs if c == cls.name
        } | {a for (c, a) in self.cond_attrs if c == cls.name}
        for node in ast.walk(cls):
            attr = None
            kind = None
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    a = _self_attr_of(tgt)
                    if a is None and isinstance(tgt, ast.Subscript):
                        a = _self_attr_of(tgt.value)
                    if a is not None:
                        attr = a
                        kind = (
                            "augassign"
                            if isinstance(node, ast.AugAssign)
                            else "assign"
                        )
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr in _MUTATING_METHODS:
                a = _self_attr_of(node.func.value)
                if a is not None:
                    attr = a
                    kind = "mutate"
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        a = _self_attr_of(tgt.value)
                        if a is not None:
                            attr = a
                            kind = "del"
            if attr is None or attr in skip:
                continue
            fn = pf.enclosing_function(node)
            if fn is None or getattr(fn, "name", "") == "__init__":
                continue  # construction happens-before publication
            if pf.enclosing_class(fn) is not cls:
                continue  # a nested class owns its own discipline
            held = self.held_at(pf, node)
            self.write_sites.setdefault((cls.name, attr), []).append(
                LockSite(pf, node, fn, held, kind)
            )

    def _infer_guards(self) -> None:
        for (cname, attr), sites in self.write_sites.items():
            counts: Dict[str, int] = {}
            for s in sites:
                for lid in s.held:
                    counts[lid] = counts.get(lid, 0) + 1
            if not counts:
                continue
            ranked = sorted(
                counts.items(), key=lambda kv: (-kv[1], kv[0])
            )
            top_id, top_n = ranked[0]
            if len(ranked) > 1 and ranked[1][1] == top_n:
                continue  # tie between locks: no inference
            if top_n * 2 <= len(sites):
                continue  # no strict majority: no inference
            self.inferred_guards.setdefault(cname, {})[attr] = top_id


def _fn_key(pf, fn) -> tuple:
    """The cache-stable identity of a def: survives a reparse (same
    content, new AST objects), unlike ``id(fn)``."""
    return (pf.relpath, fn.lineno, fn.name)


def _self_attr_of(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _ctor_candidates(value: ast.AST):
    """The Call nodes an assigned value may come from: the value itself,
    or either arm of the ``x if x is not None else Default()`` idiom the
    daemon uses for injectable collaborators."""
    if isinstance(value, ast.Call):
        yield value
    elif isinstance(value, ast.IfExp):
        for arm in (value.body, value.orelse):
            if isinstance(arm, ast.Call):
                yield arm


_LOCK_CACHE: Dict[str, LockDataflow] = {}


def get_locks(files: List[ParsedFile]) -> LockDataflow:
    """The (content-hash cached) lock-domain index for one scanned set."""
    key = _content_key(files)
    df = _LOCK_CACHE.get(key)
    if df is None:
        df = LockDataflow(files)
        if len(_LOCK_CACHE) > 8:
            _LOCK_CACHE.clear()
        _LOCK_CACHE[key] = df
    return df
