"""Interprocedural dataflow for graftlint: the sharding provenance lattice.

PR 6 made pjit-over-the-slot-axis the production solve path. Its
correctness contract is *placement discipline*: every SlotState plane must
land on the device pre-sharded through ``parallel.mesh`` (slot_shardings /
axis_sharding / batch_sharding), and host code must never materialize a
slot-sharded plane wholesale (an implicit cross-device gather). Per-file
AST matching cannot see that contract — the placement happens in
``DeviceScheduler._dev_slots``, three calls away from the jit entry that
consumes the state — so this module gives the GL5xx rules an
interprocedural view:

- a **project-wide call graph**: every def (functions and methods) indexed
  by name across the scanned file set, with call resolution by dotted-name
  tail (``self._dev_slots(...)`` resolves to every ``_dev_slots`` def);
- a **provenance lattice** for array values, tags accumulated as a set::

      HOST ──┐                 host memory (numpy, device_get results)
      DEVICE ─┼─► value tags   on device, placement unannotated
      REPL ──┤                 explicitly replicated over the mesh
      SHARD ──┘                 routed through the slot-axis sharding API

  ``PLACED = {REPL, SHARD}``. An empty tag set means "unknown" and is
  never flagged — the analysis under-approximates: it only reports when
  it can positively trace a value to its sources.
- **function return summaries** (the provenance a call produces, joined
  over every return site) and **attribute summaries** (keyword-constructed
  pytree fields: ``_Prepared(init_state=self._make_init_state(...))``
  records ``init_state -> {SHARD, ...}``), so a chain like

      ffd_solve_donated(prep.init_state, ...)
        <- _Prepared(init_state=...) <- _make_init_state
        <- self._dev_slots <- jax.device_put(a, pmesh.axis_sharding(...))

  resolves to SHARD across four hops and two classes.

The whole index is built once per scanned file set and cached by content
hash (every relpath + source digest), so repeated ``run()`` calls in one
process — the tier-1 gate, bench.py --lint, editor integrations — pay the
fixpoint once. Known over-approximations, deliberate and documented:
attribute summaries are keyed by bare attribute name project-wide (not
per-class), and call resolution is by name tail (not import graph). Both
can only ADD tags, and every consumer flags on positive evidence, so the
imprecision degrades to silence, not noise.
"""
from __future__ import annotations

import ast
import hashlib
import weakref
from typing import Dict, List, Optional, Set

from tools.graftlint.engine import ParsedFile, dotted_name

HOST = "host"
DEVICE = "device"  # on device, placement unannotated
REPL = "replicated"
SHARD = "sharded"
PLACED = frozenset({REPL, SHARD})

# the sanctioned placement API (parallel/mesh.py): call tails that mint a
# slot-axis sharding / an explicit replication (the batched_* twins mint
# the problem-batched specs for the continuous-batching vmapped solve)
_MESH_SHARDERS = {
    "slot_shardings", "axis_sharding", "batch_sharding",
    "batched_slot_shardings", "batched_step_shardings",
    "gang_plane_shardings", "batched_gang_plane_shardings",
}
_MESH_REPLICATORS = {"replicated"}

_NP_PREFIXES = ("np.", "numpy.", "onp.")
_JNP_PREFIXES = ("jnp.", "jax.numpy.")

# array-metadata attributes: reading them yields host scalars/objects, not
# the array — branching on .shape or accounting .nbytes is never a gather
_METADATA_ATTRS = {
    "shape", "ndim", "dtype", "nbytes", "size", "sharding", "itemsize",
    "_fields",
}

_MAX_DEPTH = 6  # call-summary resolution depth cap
_MAX_CANDIDATES = 6  # same-named defs considered per call


def _content_key(files: List[ParsedFile]) -> str:
    h = hashlib.sha256()
    for pf in sorted(files, key=lambda p: p.relpath):
        h.update(pf.relpath.encode())
        h.update(hashlib.sha256(pf.source.encode()).digest())
    return h.hexdigest()


class ProjectDataflow:
    """Provenance queries over one scanned file set. Use :func:`get`."""

    def __init__(self, files: List[ParsedFile]):
        self.files = files
        # name -> [(pf, def node)] for every function/method in the project
        self.defs: Dict[str, List] = {}
        # class name -> ClassDef (constructor-call recognition)
        self.classes: Dict[str, ast.ClassDef] = {}
        for pf in files:
            for node in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
                self.defs.setdefault(node.name, []).append((pf, node))
            for node in pf.walk(ast.ClassDef):
                self.classes.setdefault(node.name, node)
        # attribute name -> joined provenance of every recorded store
        self.attr_summary: Dict[str, Set[str]] = {}
        # memo keys are the AST NODES THEMSELVES (identity hash), held
        # WEAKLY: an id() key would outlive its node (a recycled address
        # then returns a different function's env), while a strong key
        # would pin every later run's re-parsed tree forever (the index
        # itself is process-cached by content hash). Weak keys give both
        # properties: construction-time entries persist exactly as long
        # as self.files retains their trees, and query-time entries from
        # a caller's re-parse evict with that parse.
        self._summaries = weakref.WeakKeyDictionary()
        self._envs = weakref.WeakKeyDictionary()
        self._in_progress: Set[int] = set()
        # two eager passes: pass 1 populates attribute summaries from
        # constructor calls and attribute stores everywhere; pass 2
        # recomputes envs/summaries against the grown attr table so
        # cross-module attribute reads (consolidation reading
        # provisioner's _Prepared fields) see the final join
        for _ in range(2):
            self._summaries.clear()
            self._envs.clear()
            for pf in files:
                self._env_for(pf, None)
                for node in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
                    self._env_for(pf, node)

    # -- public query ------------------------------------------------------

    def prov(self, pf: ParsedFile, expr: ast.AST, fn) -> frozenset:
        """Provenance tag set of an expression evaluated in the local
        environment of ``fn`` (None = module level)."""
        env = self._env_for(pf, fn)
        return frozenset(self._eval(pf, expr, env, _MAX_DEPTH))

    # -- environments ------------------------------------------------------

    def _env_for(self, pf: ParsedFile, fn) -> Dict[str, Set[str]]:
        key = fn if fn is not None else pf.tree
        cached = self._envs.get(key)
        if cached is not None:
            return cached
        env: Dict[str, Set[str]] = {}
        self._envs[key] = env  # pre-bind: cycles read the partial env
        if isinstance(fn, ast.Lambda):
            return env  # no statements, nothing to bind
        body = pf.tree.body if fn is None else fn.body
        self._walk_stmts(pf, body, env, _MAX_DEPTH)
        return env

    def _walk_stmts(self, pf, stmts, env, depth) -> None:
        for st in stmts:
            if isinstance(
                st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # nested scopes own their env
            if isinstance(st, ast.Assign):
                p = self._eval(pf, st.value, env, depth)
                for tgt in st.targets:
                    self._bind(pf, tgt, st.value, p, env, depth)
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                p = self._eval(pf, st.value, env, depth)
                self._bind(pf, st.target, st.value, p, env, depth)
            elif isinstance(st, ast.AugAssign):
                p = self._eval(pf, st.value, env, depth)
                if isinstance(st.target, ast.Name):
                    env.setdefault(st.target.id, set()).update(p)
            elif isinstance(st, ast.For) or isinstance(st, ast.AsyncFor):
                p = self._eval(pf, st.iter, env, depth)
                self._bind(pf, st.target, st.iter, p, env, depth)
                self._walk_stmts(pf, st.body, env, depth)
                self._walk_stmts(pf, st.orelse, env, depth)
            elif isinstance(st, (ast.If, ast.While)):
                # both arms walked over one env: reassignment joins, the
                # safe over-approximation for a branch-insensitive lattice
                self._walk_stmts(pf, st.body, env, depth)
                self._walk_stmts(pf, st.orelse, env, depth)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    if item.optional_vars is not None:
                        p = self._eval(pf, item.context_expr, env, depth)
                        self._bind(
                            pf, item.optional_vars, item.context_expr, p,
                            env, depth,
                        )
                self._walk_stmts(pf, st.body, env, depth)
            elif isinstance(st, ast.Try):
                self._walk_stmts(pf, st.body, env, depth)
                for h in st.handlers:
                    self._walk_stmts(pf, h.body, env, depth)
                self._walk_stmts(pf, st.orelse, env, depth)
                self._walk_stmts(pf, st.finalbody, env, depth)
            elif isinstance(st, (ast.Return, ast.Expr)):
                if st.value is not None:
                    # evaluated for effect: constructor calls inside the
                    # expression record attribute summaries
                    self._eval(pf, st.value, env, depth)

    def _bind(self, pf, target, value, prov: Set[str], env, depth) -> None:
        if isinstance(target, ast.Name):
            env.setdefault(target.id, set()).update(prov)
        elif isinstance(target, ast.Starred):
            self._bind(pf, target.value, value, prov, env, depth)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) and len(
                value.elts
            ) == len(target.elts):
                for t, v in zip(target.elts, value.elts):
                    self._bind(pf, t, v, self._eval(pf, v, env, depth), env, depth)
            else:
                for t in target.elts:
                    self._bind(pf, t, value, prov, env, depth)
        elif isinstance(target, ast.Attribute):
            # obj.attr = expr: record in the attribute summary. A None
            # store is a tombstone (prep.init_state = None after donation),
            # not a placement decision — skip it.
            if prov and not (
                isinstance(value, ast.Constant) and value.value is None
            ):
                self.attr_summary.setdefault(target.attr, set()).update(prov)
        # Subscript targets carry no name to bind

    # -- expression evaluation ---------------------------------------------

    def _eval(self, pf, node: ast.AST, env, depth) -> Set[str]:
        if isinstance(node, ast.Constant):
            if node.value is None:
                return set()
            return {HOST}
        if isinstance(node, ast.Name):
            return set(env.get(node.id, ()))
        if isinstance(node, ast.Attribute):
            if node.attr in _METADATA_ATTRS:
                return set()
            base = self._eval(pf, node.value, env, depth)
            if base:
                return base
            return set(self.attr_summary.get(node.attr, ()))
        if isinstance(node, ast.Call):
            return self._eval_call(pf, node, env, depth)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out: Set[str] = set()
            for e in node.elts:
                out |= self._eval(pf, e, env, depth)
            return out
        if isinstance(node, ast.Subscript):
            # slicing keeps provenance: state.valmask[:n] is still sharded
            return self._eval(pf, node.value, env, depth)
        if isinstance(node, ast.IfExp):
            return self._eval(pf, node.body, env, depth) | self._eval(
                pf, node.orelse, env, depth
            )
        if isinstance(node, ast.BinOp):
            return self._eval(pf, node.left, env, depth) | self._eval(
                pf, node.right, env, depth
            )
        if isinstance(node, ast.UnaryOp):
            return self._eval(pf, node.operand, env, depth)
        if isinstance(node, ast.NamedExpr):
            p = self._eval(pf, node.value, env, depth)
            env.setdefault(node.target.id, set()).update(p)
            return p
        if isinstance(node, ast.Starred):
            return self._eval(pf, node.value, env, depth)
        return set()

    def _eval_call(self, pf, node: ast.Call, env, depth) -> Set[str]:
        name = dotted_name(node.func)
        tail = name.rsplit(".", 1)[-1] if name else ""

        if tail in _MESH_SHARDERS:
            return {SHARD}
        if tail in _MESH_REPLICATORS:
            return {REPL}
        if name in ("jax.device_put", "device_put"):
            placement = None
            if len(node.args) >= 2:
                placement = node.args[1]
            elif node.keywords:
                for kw in node.keywords:
                    if kw.arg in ("device", "sharding", None):
                        placement = kw.value
                        break
            if placement is None:
                return {DEVICE}  # bare put: unannotated placement
            sh = self._eval(pf, placement, env, depth)
            sh &= {SHARD, REPL}
            return sh or {REPL}  # explicitly placed, shape unknown -> repl
        if name in ("jax.device_get", "device_get"):
            return {HOST}
        if name.endswith("tree.map") or name in ("jax.tree_map", "tree_map"):
            out: Set[str] = set()
            for a in node.args:
                out |= self._eval(pf, a, env, depth)
            return out
        if name.startswith(_NP_PREFIXES):
            return {HOST}
        if name.startswith(_JNP_PREFIXES):
            return {DEVICE}
        if name in ("int", "float", "bool"):
            return {HOST}  # concretization: the RESULT is host
        if tail == "_replace" and isinstance(node.func, ast.Attribute):
            out = self._eval(pf, node.func.value, env, depth)
            for kw in node.keywords:
                out |= self._eval(pf, kw.value, env, depth)
            return out

        # constructor call of a class (SlotState(...), _Prepared(...)):
        # record keyword fields in the attribute summary, provenance is the
        # union of the parts. CamelCase names count even when the class def
        # lives outside the scanned set (SlotState imported from ops/ffd
        # into a partial-path run) — the keyword-record is what matters.
        cls = self.classes.get(tail)
        if cls is not None or (tail[:1].isupper() and tail not in self.defs):
            out = set()
            for a in node.args:
                out |= self._eval(pf, a, env, depth)
            for kw in node.keywords:
                kp = self._eval(pf, kw.value, env, depth)
                out |= kp
                if kw.arg and kp:
                    self.attr_summary.setdefault(kw.arg, set()).update(kp)
            return out

        # project function/method: join the return summaries of every
        # same-named def (conservative tail resolution)
        candidates = self.defs.get(tail, ())
        if candidates and depth > 0:
            out = set()
            for cpf, fn in candidates[:_MAX_CANDIDATES]:
                out |= self._summary(cpf, fn, depth - 1)
            # evaluate args for constructor-recording side effects
            for a in node.args:
                self._eval(pf, a, env, depth)
            for kw in node.keywords:
                self._eval(pf, kw.value, env, depth)
            return out
        return set()

    def _summary(self, pf, fn, depth) -> Set[str]:
        """Return-site provenance join of one def."""
        cached = self._summaries.get(fn)
        if cached is not None:
            return set(cached)
        if id(fn) in self._in_progress:
            return set()  # recursion: bottom, refined on the next pass
        self._in_progress.add(id(fn))
        try:
            env = self._env_for(pf, fn)
            out: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    owner = pf.enclosing_function(node)
                    if owner is fn:
                        out |= self._eval(pf, node.value, env, depth)
            self._summaries[fn] = frozenset(out)
            return out
        finally:
            self._in_progress.discard(id(fn))


_CACHE: Dict[str, ProjectDataflow] = {}


def get(files: List[ParsedFile]) -> ProjectDataflow:
    """The (content-hash cached) dataflow index for one scanned set."""
    key = _content_key(files)
    df = _CACHE.get(key)
    if df is None:
        df = ProjectDataflow(files)
        if len(_CACHE) > 8:  # a handful of distinct scan sets per process
            _CACHE.clear()
        _CACHE[key] = df
    return df
