"""Family 5 — shardcheck: SPMD placement discipline for the sharded solve.

PR 6 made pjit-over-the-slot-axis the production path. XLA compiles the
solve SPMD from the *argument shardings* — nothing at runtime checks that
the arguments actually carried the right ones. Three silent failure modes
follow, each invisible to pytest on a 1-chip box: a SlotState that lands
unannotated compiles, runs, and quietly degrades to replicated copies
with a reshard per dispatch; a host materialization of a slot-sharded
plane compiles into an implicit cross-device gather; and hand-rolled
slot-axis arithmetic that bypasses ``pad_to_devices`` works on any device
count that happens to divide evenly — until one doesn't. These rules ride
the interprocedural provenance lattice (tools/graftlint/dataflow.py) so
the placement can live several calls away from the consumption site.

GL501 slotstate-entry-unrouted — a SlotState jit entry reachable from
                                 DeviceScheduler/frontier_core (models/)
                                 consumes state whose arrays never routed
                                 through parallel.mesh placement
                                 (slot_shardings/axis_sharding/
                                 batch_sharding, or an explicit
                                 device_put placement)
GL502 slotstate-spec-parity    — the SlotState field set must equal the
                                 SLOT_STATE_SPECS keys in parallel/mesh.py
                                 (the runtime raise, promoted to edit time)
GL503 sharded-host-gather      — host materialization of a slot-sharded
                                 value in ops//models/ (np.asarray,
                                 .addressable_data, scalar int()/float(),
                                 bare single-arg jax.device_put — subsumes
                                 the retired GL104)
GL504 pad-to-devices-bypass    — literal slot-axis shape arithmetic
                                 (slots-name //,%,* devices-name; reshape
                                 folding a device axis) instead of
                                 parallel.mesh.pad_to_devices
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from tools.graftlint import dataflow
from tools.graftlint.engine import ParsedFile, Rule, dotted_name, register

# SlotState jit entries: defined in ops/ffd.py (plus the consolidation
# sweep's _prefix_scan), consumed by models/ and the harnesses. One list,
# shared by GL501 (routing) and GL503 (the bare-device_put precondition
# inherited from the retired GL104). The batched twins (ISSUE 9) consume
# a problem-STACKED SlotState — batch-stacked state must still route
# through parallel.mesh placement (batched_slot_shardings /
# batched_step_shardings), so they carry the same contract. The gangsched
# entries (ISSUE 10) are SlotState kernels too: gang_solve* runs the same
# scan with a gang axis riding the class batch, and preempt_pass* consumes
# the FINISHED solve's SlotState plus the EvPlanes (whose slot axis routes
# through parallel.mesh.gang_plane_shardings / the batched twin). The
# relaxsolve scorer (ISSUE 13, ops/relax.relax_score) consumes a FINISHED
# solve's SlotState too — its state must come out of a routed dispatch,
# never a bare host build (the relax assignment planes themselves carry no
# slot axis and route through parallel.mesh.relax_plane_shardings). The
# pallas_* entries (ISSUE 18, ops/pallas_ffd.py) are the hand-fused twins
# of the four ffd_solve* kernels: same SlotState contract, but the
# pallas_call boundary is opaque to GSPMD, so multi-device dispatches
# route through parallel.mesh.pallas_slot_shardings (replicated planes)
# rather than the slot-axis specs.
SLOTSTATE_JIT_ENTRIES = {
    "ffd_solve",
    "ffd_solve_donated",
    "ffd_solve_batched",
    "ffd_solve_batched_donated",
    "pallas_ffd_solve",
    "pallas_ffd_solve_donated",
    "pallas_ffd_solve_batched",
    "pallas_ffd_solve_batched_donated",
    "_prefix_scan",
    "gang_solve",
    "gang_solve_donated",
    "gang_solve_batched",
    "gang_solve_batched_donated",
    "preempt_pass",
    "preempt_pass_batched",
    "relax_score",
}


def _models_file(pf: ParsedFile) -> bool:
    return "/models/" in f"/{pf.relpath}"


def _accel_file(pf: ParsedFile) -> bool:
    return "/ops/" in f"/{pf.relpath}" or "/models/" in f"/{pf.relpath}"


def _reaches_slotstate_entry(pf: ParsedFile) -> bool:
    """Module calls a known SlotState jit entry, or defines one itself
    (an ops/ffd.py-shaped module introducing a new SlotState kernel) — in
    either case an un-annotated placement feeds the sharded solve. The
    second half reuses the jaxpurity traced-region index so the GL104
    semantics this rule subsumed carry over exactly."""
    for call in pf.walk(ast.Call):
        name = dotted_name(call.func)
        if name and name.rsplit(".", 1)[-1] in SLOTSTATE_JIT_ENTRIES:
            return True
    from tools.graftlint.rules import jaxpurity as _jp

    idx = _jp._index(pf)
    for _site, target, _kw in idx.jit_sites:
        if _jp._carries_slot_state(target) is not None:
            return True
    return False


def _traced_fns(pf: ParsedFile):
    """Functions whose interior is traced (jit roots — decorator, call,
    and partial forms — plus everything reachable from them): GL101's
    territory, excluded from GL503's host-side checks. Reuses the
    jaxpurity module index so the two rules agree on the boundary."""
    from tools.graftlint.rules import jaxpurity as _jp

    return _jp._index(pf).traced


@register
class SlotStateEntryUnrouted(Rule):
    id = "GL501"
    name = "slotstate-entry-unrouted"
    rationale = (
        "a SlotState jit entry on the DeviceScheduler/frontier_core solve"
        " path consuming state never routed through parallel.mesh"
        " placement compiles SPMD against the wrong (absent) shardings —"
        " the multi-device path silently degrades to replicated copies"
    )
    scope = "project"

    # the roots the rationale names: the production solve object and the
    # consolidation sweep entry
    _ROOT_CLASSES = {"DeviceScheduler"}
    _ROOT_FUNCS = {"frontier_core"}

    def _reachable(self, files: List[ParsedFile]) -> set:
        """Ids of every def reachable (by name-tail call edges) from a
        DeviceScheduler method or frontier_core — the documented scope,
        so an off-path models/ helper deliberately driving a single-
        device solve is not flagged against a contract it never made.
        Indexed over THIS run's parse (never the content-hash-cached
        dataflow's construction-time nodes: enclosing-function checks
        below compare against this run's node identities)."""
        defs: Dict[str, List[ast.AST]] = {}
        seeds: List[ast.AST] = []
        for pf in files:
            for node in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
                defs.setdefault(node.name, []).append(node)
                if node.name in self._ROOT_FUNCS:
                    seeds.append(node)
            for node in pf.walk(ast.ClassDef):
                if node.name in self._ROOT_CLASSES:
                    seeds.extend(
                        n
                        for n in ast.walk(node)
                        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    )
        reachable, frontier = {id(fn) for fn in seeds}, list(seeds)
        while frontier:
            fn = frontier.pop()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                tail = dotted_name(node.func).rsplit(".", 1)[-1]
                for callee in defs.get(tail, ()):
                    if id(callee) not in reachable:
                        reachable.add(id(callee))
                        frontier.append(callee)
        return reachable

    def check_project(self, files: List[ParsedFile]) -> Iterable:
        targets = [pf for pf in files if _models_file(pf)]
        if not targets:
            return
        df = dataflow.get(files)
        reachable = self._reachable(files)
        for pf in targets:
            for node in pf.walk(ast.Call):
                name = dotted_name(node.func)
                tail = name.rsplit(".", 1)[-1] if name else ""
                if tail not in SLOTSTATE_JIT_ENTRIES:
                    continue
                # the state rides first positionally in every entry, but a
                # keyword-style call site must not disarm the rule
                state_expr = node.args[0] if node.args else next(
                    (kw.value for kw in node.keywords if kw.arg == "state"),
                    None,
                )
                if state_expr is None:
                    continue
                fn = pf.enclosing_function(node)
                if fn is None or id(fn) not in reachable:
                    continue  # off the documented DeviceScheduler/frontier path
                prov = df.prov(pf, state_expr, fn)
                if prov and not (prov & dataflow.PLACED):
                    yield self.finding(
                        pf, node,
                        f"{tail} consumes SlotState with provenance"
                        f" {{{', '.join(sorted(prov))}}} — the arrays never"
                        " routed through parallel.mesh placement"
                        " (slot_shardings/axis_sharding/batch_sharding or"
                        " an explicit device_put sharding), so the"
                        " pre-sharded-placement invariant of the pjit"
                        " solve path is broken at this call site",
                    )


def _slotstate_fields(pf: ParsedFile) -> List[Tuple[ast.ClassDef, List[str]]]:
    out = []
    for node in pf.walk(ast.ClassDef):
        if node.name != "SlotState":
            continue
        if not any(dotted_name(b).endswith("NamedTuple") for b in node.bases):
            continue
        fields = [
            st.target.id
            for st in node.body
            if isinstance(st, ast.AnnAssign) and isinstance(st.target, ast.Name)
        ]
        out.append((node, fields))
    return out


def _spec_keys(pf: ParsedFile) -> List[Tuple[ast.AST, List[str]]]:
    out = []
    for node in pf.walk(ast.Assign):
        if not any(
            isinstance(t, ast.Name) and t.id == "SLOT_STATE_SPECS"
            for t in node.targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        keys = [
            k.value
            for k in node.value.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        ]
        out.append((node, keys))
    return out


@register
class SlotStateSpecParity(Rule):
    id = "GL502"
    name = "slotstate-spec-parity"
    rationale = (
        "SLOT_STATE_SPECS (parallel/mesh.py) classifies every SlotState"
        " field's slot-axis placement by name; a field added to one side"
        " only is today a runtime raise on the first multi-device solve —"
        " promote it to a lint error at edit time"
    )
    scope = "project"

    def check_project(self, files: List[ParsedFile]) -> Iterable:
        states: List[Tuple[ParsedFile, ast.AST, List[str]]] = []
        specs: List[Tuple[ParsedFile, ast.AST, List[str]]] = []
        for pf in files:
            local_states = _slotstate_fields(pf)
            local_specs = _spec_keys(pf)
            if local_states and local_specs:
                # fixture-style: both halves in one file pair locally
                for snode, fields in local_states:
                    for dnode, keys in local_specs:
                        yield from self._compare(pf, snode, fields, pf, dnode, keys)
                continue
            states.extend((pf, n, f) for n, f in local_states)
            specs.extend((pf, n, k) for n, k in local_specs)
        # the tree shape: one SlotState (ops/ffd.py), one SLOT_STATE_SPECS
        # (parallel/mesh.py). Partial-path runs that scan only one half
        # stay silent — the tier-1 full-tree run sees both.
        if len(states) == 1 and len(specs) == 1:
            (spf, snode, fields), (dpf, dnode, keys) = states[0], specs[0]
            yield from self._compare(spf, snode, fields, dpf, dnode, keys)

    def _compare(self, spf, snode, fields, dpf, dnode, keys) -> Iterable:
        missing = sorted(set(fields) - set(keys))
        stale = sorted(set(keys) - set(fields))
        if missing:
            yield self.finding(
                dpf, dnode,
                f"SLOT_STATE_SPECS is missing SlotState field(s) {missing}"
                " — classify their slot-axis placement (dim index or None"
                " for replicated) or the first multi-device solve raises",
            )
        if stale:
            yield self.finding(
                dpf, dnode,
                f"SLOT_STATE_SPECS names field(s) {stale} that SlotState"
                " no longer has — remove the stale entries so the spec"
                " table stays in lockstep with the state definition",
            )


@register
class ShardedHostGather(Rule):
    id = "GL503"
    name = "sharded-host-gather"
    rationale = (
        "materializing a slot-sharded value on host (np.asarray,"
        " .addressable_data, scalar int()/float(), a bare single-arg"
        " jax.device_put) is an implicit full cross-device gather —"
        " fetch through jax.device_get on a sliced window, or keep the"
        " reduction on device"
    )
    scope = "project"

    @staticmethod
    def _sharded(prov: frozenset) -> bool:
        """Unambiguously sharded: the attribute-summary fallback joins
        same-named stores project-wide, so a host tag in the set means
        the name ALSO carries host values somewhere — flagging would be
        noise. Ambiguity degrades to silence, never to a false finding."""
        return dataflow.SHARD in prov and dataflow.HOST not in prov

    def check_project(self, files: List[ParsedFile]) -> Iterable:
        targets = [pf for pf in files if _accel_file(pf)]
        if not targets:
            return
        df = dataflow.get(files)
        for pf in targets:
            reaches = _reaches_slotstate_entry(pf)
            traced = _traced_fns(pf)
            for node in pf.walk(ast.Call):
                fn = pf.enclosing_function(node)
                if fn is not None and fn in traced:
                    continue  # traced interior: GL101's territory
                name = dotted_name(node.func)
                tail = name.rsplit(".", 1)[-1] if name else ""
                if (
                    name.startswith(("np.", "numpy.", "onp."))
                    and tail in ("asarray", "array", "copy")
                    and node.args
                ):
                    prov = df.prov(pf, node.args[0], fn)
                    if self._sharded(prov):
                        yield self.finding(
                            pf, node,
                            f"{name} on a slot-sharded value is an implicit"
                            " full gather across the mesh — device_get a"
                            " sliced window instead (models/provisioner"
                            " windowed fetch), or justify the transfer",
                        )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "addressable_data"
                ):
                    prov = df.prov(pf, node.func.value, fn)
                    if self._sharded(prov):
                        yield self.finding(
                            pf, node,
                            ".addressable_data on a slot-sharded value"
                            " reads one device's shard on host — per-shard"
                            " host logic in the solve path breaks the"
                            " single-program model; reduce on device",
                        )
                elif name in ("int", "float") and node.args:
                    prov = df.prov(pf, node.args[0], fn)
                    if self._sharded(prov):
                        yield self.finding(
                            pf, node,
                            f"scalar {name}() on a slot-sharded value"
                            " concretizes it on host (implicit gather +"
                            " sync) — device_get the scalar explicitly or"
                            " keep it on device",
                        )
                elif (
                    name in ("jax.device_put", "device_put")
                    and len(node.args) == 1
                    and not node.keywords
                    and reaches
                ):
                    yield self.finding(
                        pf, node,
                        "jax.device_put without a sharding in a module"
                        " that drives a SlotState jit entry bypasses"
                        " parallel.mesh placement — on a multi-device mesh"
                        " the copy lands unannotated and every dispatch"
                        " pays a reshard (was GL104)",
                    )


_DEVICE_NAMES = {"devices", "n_dev", "n_devices", "num_devices"}
_SLOT_NAMES = {"n_slots", "max_slots", "num_slots", "slots", "N", "P", "n_pad"}
_SHAPE_OPS = (ast.FloorDiv, ast.Mod, ast.Mult)


def _mentioned_names(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


@register
class PadToDevicesBypass(Rule):
    id = "GL504"
    name = "pad-to-devices-bypass"
    rationale = (
        "hand-rolled slot-axis shape arithmetic (slots // devices,"
        " reshape over a device axis) silently truncates or crashes when"
        " the slot count stops dividing the mesh — route slot-axis sizing"
        " through parallel.mesh.pad_to_devices (padded slots are inert by"
        " construction, the parity-tested invariant)"
    )

    def applies(self, pf: ParsedFile) -> bool:
        return _accel_file(pf) or "/parallel/" in f"/{pf.relpath}"

    def check(self, pf: ParsedFile) -> Iterable:
        for node in pf.walk(ast.BinOp):
            if not isinstance(node.op, _SHAPE_OPS):
                continue
            fn = pf.enclosing_function(node)
            if getattr(fn, "name", "") == "pad_to_devices":
                continue  # the sanctioned helper's own arithmetic
            left, right = _mentioned_names(node.left), _mentioned_names(node.right)
            if (left & _DEVICE_NAMES and right & _SLOT_NAMES) or (
                right & _DEVICE_NAMES and left & _SLOT_NAMES
            ):
                yield self.finding(
                    pf, node,
                    "slot-axis shape arithmetic over the device count —"
                    " size the slot axis with parallel.mesh.pad_to_devices"
                    " so uneven meshes pad instead of truncating",
                )
        for node in pf.walk(ast.Call):
            name = dotted_name(node.func)
            tail = name.rsplit(".", 1)[-1] if name else ""
            if tail != "reshape":
                continue
            shape_args = list(node.args)
            if name in ("jnp.reshape", "np.reshape", "jax.numpy.reshape"):
                shape_args = shape_args[1:]  # (array, shape)
            flat: List[ast.AST] = []
            for a in shape_args:
                flat.extend(a.elts if isinstance(a, (ast.Tuple, ast.List)) else [a])
            for a in flat[:2]:  # a device axis folds in front
                names = _mentioned_names(a)
                if names & _DEVICE_NAMES:
                    yield self.finding(
                        pf, node,
                        "reshape folding a device axis into the slot dim"
                        " re-implements mesh placement by hand — shard"
                        " with parallel.mesh (axis_sharding/batch_sharding)"
                        " and size with pad_to_devices",
                    )
                    break
