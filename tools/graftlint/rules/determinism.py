"""Family 2 — fingerprint determinism.

PR 3's prepared-state caches key on ``FrozenVocab.fingerprint()`` and the
solverd scheduler cache keys on ``codec.problem_fingerprint`` — both are
only stable if every id-assigning or wire-list-building iteration runs in
canonical order. A ``set`` (or a dict whose insertion order tracks pod
arrival) iterated into an encoder silently yields a different fingerprint
for the same logical cluster: the cache misses forever at best, or two
processes disagree about id assignment at worst. These rules police the
encoding/fingerprint functions of the four modules that own that contract.

GL201 unordered-encode-iter — set/dict-view iteration inside an encoding
                              or fingerprint function without sorted(...)
GL202 fingerprint-json-keys — json.dumps in a fingerprint/digest function
                              must pass sort_keys=True
"""
from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Tuple

from tools.graftlint.engine import ParsedFile, Rule, dotted_name, register

# the modules whose encode paths feed tensor ids, wire bytes, or cache keys
# — plus the twin's scenario/ledger serialization (ISSUE 15): a shrunk
# repro fixture and the byte-identical-ledger determinism contract both
# hang off canonical encoding there
_SCOPED_FILES = (
    "solver/vocab.py",
    "solver/codec.py",
    "solver/snapshot.py",
    "models/provisioner.py",
    "twin/scenario.py",
    "twin/ledger.py",
)

_CONTEXT_FN = re.compile(
    r"(encode|fingerprint|digest|signature|observe|vocab|_fp_)", re.I
)

_ORDER_SAFE_WRAPPERS = {"sorted"}
_TRANSPARENT_WRAPPERS = {"enumerate", "list", "tuple", "reversed", "zip"}


def _in_scope(pf: ParsedFile) -> bool:
    return any(pf.relpath.endswith(s) for s in _SCOPED_FILES) or (
        "graftlint_fixtures" in pf.relpath
    )


def _context_function(pf: ParsedFile, node: ast.AST):
    """Nearest enclosing function whose name (or any enclosing function's
    name) marks an encoding/fingerprint context."""
    fn = pf.enclosing_function(node)
    cur = fn
    while cur is not None:
        name = getattr(cur, "name", "")
        if name and _CONTEXT_FN.search(name):
            return cur
        cur = pf.enclosing_function(cur)
    return None


def _is_order_safe(node: ast.AST) -> bool:
    """True when the iterable is wrapped in sorted(...) (possibly under a
    transparent wrapper like enumerate/list/zip)."""
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name in _ORDER_SAFE_WRAPPERS:
            return True
        if name in _TRANSPARENT_WRAPPERS:
            return any(_is_order_safe(a) for a in node.args)
    return False


def _unordered_reason(node: ast.AST) -> Optional[str]:
    """Why this iterable has no canonical order, or None when unknown/ok."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "set literal iteration order is undefined"
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name in ("set", "frozenset"):
            return f"{name}() iteration order is undefined"
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
            "items", "keys", "values",
        ):
            return (
                f".{node.func.attr}() iterates in dict insertion order,"
                " which tracks arrival order, not content"
            )
    if isinstance(node, ast.Attribute) and node.attr == "values":
        # project knowledge: Requirement.values is a set
        return ".values is a set attribute (Requirement.values)"
    return None


def _iteration_sites(pf: ParsedFile) -> Iterator[Tuple[ast.AST, ast.AST]]:
    """(site node, iterable expr) for for-loops and comprehensions."""
    for node in pf.walk(ast.For):
        yield node, node.iter
    for node in pf.walk(ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp):
        for gen in node.generators:
            yield node, gen.iter


@register
class UnorderedEncodeIteration(Rule):
    id = "GL201"
    name = "unordered-encode-iter"
    rationale = (
        "set/dict iteration feeding an encoder or fingerprint must be"
        " wrapped in sorted(...): unordered iteration poisons the"
        " prepared-state and solverd scheduler caches"
    )

    def applies(self, pf: ParsedFile) -> bool:
        return _in_scope(pf)

    def check(self, pf: ParsedFile):
        for site, iterable in _iteration_sites(pf):
            ctx = _context_function(pf, site)
            if ctx is None:
                continue
            if _is_order_safe(iterable):
                continue
            reason = _unordered_reason(iterable)
            if reason is None:
                continue
            yield self.finding(
                pf, site,
                f"unordered iteration in encoding/fingerprint function"
                f" {ctx.name!r}: {reason}; wrap in sorted(...) or justify"
                " order-insensitivity inline",
            )


_FP_FN = re.compile(r"(fingerprint|digest)", re.I)


@register
class FingerprintJsonSortKeys(Rule):
    id = "GL202"
    name = "fingerprint-json-keys"
    rationale = (
        "json.dumps inside a fingerprint/digest function must pass"
        " sort_keys=True or dict insertion order leaks into the hash"
    )

    def applies(self, pf: ParsedFile) -> bool:
        return _in_scope(pf) or pf.relpath.startswith("karpenter_core_tpu/")

    def check(self, pf: ParsedFile):
        for node in pf.walk(ast.Call):
            if dotted_name(node.func) != "json.dumps":
                continue
            fn = pf.enclosing_function(node)
            name = getattr(fn, "name", "") if fn is not None else ""
            if not _FP_FN.search(name or ""):
                continue
            ok = any(
                kw.arg == "sort_keys"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            )
            if not ok:
                yield self.finding(
                    pf, node,
                    f"json.dumps in fingerprint function {name!r} without"
                    " sort_keys=True — dict insertion order leaks into"
                    " the hash",
                )
