"""Family 6 — rangecheck: numeric contracts on the kernel/wire boundary.

Three PRs in a row hand-fixed a numeric-contract bug no rule could see: a
hostile int64 wire priority overflowing the int32 EvPlanes store (the
ISSUE 10 decode net), a ±9 priority clamp saturating the [-10, 10]
eviction-cost contract and erasing deletion-cost tiebreaks, and
sentinel-domain confusion around gang_of_class (-1 gang-free vs -2
fallback-straddling). These rules machine-check those contracts on the
second abstract domain in tools/graftlint/dataflow.py — per-value integer
intervals, dtype width, pad provenance and sentinel-domain tags,
propagated through the same project-wide call-graph fixpoint as the PR 7
provenance lattice (branch-insensitive joins; every rule flags on
positive evidence only, so imprecision degrades to silence).

GL601 narrowing-store-unclamped — a wire-derived integer flowing into a
                                  narrower-dtype array store/cast in
                                  solver//models/ without a registered
                                  normalizer (priority_tier, _clamp_slots)
                                  or an explicit clip: the astype/element
                                  coercion WRAPS, flipping hostile values
                                  inside the exclusive device window
GL602 sentinel-domain-mixing    — comparisons/arithmetic mixing values of
                                  different registered sentinel domains;
                                  zero-boundary tests (`< 0` / `>= 0`)
                                  where a deeper sentinel (-2) is
                                  positively live; ordered or unknown-
                                  sentinel comparisons inside a domain
GL603 clamp-saturation          — a summed cost whose per-term static
                                  intervals exceed the outer clamp bound:
                                  the clamp stops being a backstop and
                                  starts erasing lower-order tiebreaks
GL604 padding-inertness         — pad-provenance content (pad_to_devices
                                  sizing, the power-of-two batch pad,
                                  np/jnp.pad) reaching a reduction/argmin
                                  inside a traced (jit) region without a
                                  masking step: inert rows vote
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from tools.graftlint import dataflow
from tools.graftlint.dataflow import (
    CLAMPED,
    MASKED,
    NARROW_INT_DTYPES,
    PAD,
    SENTINEL_DOMAINS,
    WIRE,
    _literal_number,
)
from tools.graftlint.engine import ParsedFile, Rule, dotted_name, register


def _range_file(pf: ParsedFile) -> bool:
    p = f"/{pf.relpath}"
    return "/solver/" in p or "/models/" in p


def _kernel_file(pf: ParsedFile) -> bool:
    p = f"/{pf.relpath}"
    return "/ops/" in p or "/models/" in p or "/solver/" in p


@register
class NarrowingStoreUnclamped(Rule):
    id = "GL601"
    name = "narrowing-store-unclamped"
    rationale = (
        "a wire/host-derived integer flowing into a narrower-dtype array"
        " construction without a registered normalizer or explicit clip"
        " WRAPS on overflow — a hostile int64 flips sign inside the int32"
        " device planes (the ISSUE 10 evictable-priority fix, frozen as"
        " an invariant)"
    )
    scope = "project"

    def _flaggable(self, v: dataflow.AbsVal, dtype: str) -> bool:
        """Positive evidence of an unsafe narrowing: the value is
        positively wire-derived, no contributing path clamped it, and its
        static interval cannot be shown to fit the target width."""
        return (
            WIRE in v.taints
            and CLAMPED not in v.guards
            and not v.fits_dtype(dtype)
        )

    def check_project(self, files: List[ParsedFile]) -> Iterable:
        targets = [pf for pf in files if _range_file(pf)]
        if not targets:
            return
        df = dataflow.get_ranges(files)
        for pf in targets:
            for node in pf.walk(ast.Assign):
                if len(node.targets) != 1:
                    continue
                tgt = node.targets[0]
                if not isinstance(tgt, ast.Subscript):
                    continue
                fn = pf.enclosing_function(node)
                base = df.absval(pf, tgt.value, fn)
                if base.dtype not in NARROW_INT_DTYPES:
                    continue
                v = df.absval(pf, node.value, fn)
                if self._flaggable(v, base.dtype):
                    yield self.finding(
                        pf, node,
                        f"wire-derived integer stored into a {base.dtype}"
                        " array element without a registered normalizer"
                        " (priority_tier/_clamp_slots) or an explicit clip"
                        " — the element coercion wraps on overflow; clamp"
                        " at the decode net",
                    )
            for node in pf.walk(ast.Call):
                name = dotted_name(node.func)
                tail = name.rsplit(".", 1)[-1] if name else ""
                fn = pf.enclosing_function(node)
                if tail == "astype" and isinstance(node.func, ast.Attribute):
                    dt = (
                        dataflow._dtype_name(node.args[0])
                        if node.args else None
                    )
                    if dt not in NARROW_INT_DTYPES:
                        continue
                    src = df.absval(pf, node.func.value, fn)
                    if self._flaggable(src, dt):
                        yield self.finding(
                            pf, node,
                            f"astype({dt}) on a wire-derived integer value"
                            " with no clamp on the path — astype wraps"
                            " out-of-range values; np.clip to the"
                            " contract bounds first",
                        )
                elif tail in ("array", "asarray", "full") and (
                    name.startswith(("np.", "numpy.", "jnp.", "jax.numpy."))
                ):
                    dt = None
                    for kw in node.keywords:
                        if kw.arg == "dtype":
                            dt = dataflow._dtype_name(kw.value)
                    if dt not in NARROW_INT_DTYPES:
                        continue
                    payload: Optional[ast.AST] = None
                    if tail == "full" and len(node.args) >= 2:
                        payload = node.args[1]
                    elif tail in ("array", "asarray") and node.args:
                        payload = node.args[0]
                    if payload is None:
                        continue
                    v = df.absval(pf, payload, fn)
                    if self._flaggable(v, dt):
                        yield self.finding(
                            pf, node,
                            f"{tail}(dtype={dt}) over a wire-derived"
                            " integer payload with no clamp on the path —"
                            " the construction wraps on overflow",
                        )


def _const_int(node: ast.AST) -> Optional[int]:
    v = _literal_number(node)
    return v if isinstance(v, int) else None


_ORDERED_OPS = (ast.Lt, ast.LtE, ast.Gt, ast.GtE)


@register
class SentinelDomainMixing(Rule):
    id = "GL602"
    name = "sentinel-domain-mixing"
    rationale = (
        "sentinel integers are categorical tags, not magnitudes: mixing"
        " two registered domains in one comparison, or testing `< 0`"
        " where a -2 sentinel is live, silently conflates gang-free with"
        " fallback-straddling (the ISSUE 10 preemption-gate bug class)"
    )
    scope = "project"

    @staticmethod
    def _deep_sentinels(v: dataflow.AbsVal) -> List[int]:
        """Live values of v that are NON-DEFAULT sentinels (below -1) of
        one of v's domains — the positive evidence that a zero-boundary
        test conflates two meanings."""
        out = []
        for dom in v.sentinels:
            spec = SENTINEL_DOMAINS.get(dom, {})
            svals = set(spec.get("values", {}).values())
            for val in v.live_values():
                if val in svals and val <= -2:
                    out.append(val)
        return sorted(set(out))

    @staticmethod
    def _domain_values(v: dataflow.AbsVal) -> set:
        out = set()
        for dom in v.sentinels:
            out |= set(
                SENTINEL_DOMAINS.get(dom, {}).get("values", {}).values()
            )
        return out

    def check_project(self, files: List[ParsedFile]) -> Iterable:
        targets = [
            pf for pf in files
            if _kernel_file(pf) or "gl602" in pf.relpath
        ]
        if not targets:
            return
        df = dataflow.get_ranges(files)
        for pf in targets:
            for node in pf.walk(ast.Compare):
                if len(node.ops) != 1:
                    continue
                fn = pf.enclosing_function(node)
                left = df.absval(pf, node.left, fn)
                right = df.absval(pf, node.comparators[0], fn)
                op = node.ops[0]
                # cross-domain mixing: both sides tagged, no domain shared
                if (
                    left.sentinels and right.sentinels
                    and left.sentinels.isdisjoint(right.sentinels)
                ):
                    yield self.finding(
                        pf, node,
                        "comparison mixes values from different sentinel"
                        f" domains ({'/'.join(sorted(left.sentinels))} vs"
                        f" {'/'.join(sorted(right.sentinels))}) — their"
                        " negative magic numbers are unrelated tags",
                    )
                    continue
                # orient: sentinel-tagged side vs a constant side
                for sent, const_node in (
                    (left, node.comparators[0]), (right, node.left),
                ):
                    if not sent.sentinels:
                        continue
                    c = _const_int(const_node)
                    if c is None:
                        continue
                    deep = self._deep_sentinels(sent)
                    if c == 0 and isinstance(op, (ast.Lt, ast.GtE)) and deep:
                        yield self.finding(
                            pf, node,
                            "zero-boundary test on a"
                            f" {'/'.join(sorted(sent.sentinels))}-domain"
                            f" value while sentinel(s) {deep} are live —"
                            " `< 0`/`>= 0` conflates gang-free with"
                            " fallback-straddling; compare against the"
                            " named sentinel (== GANG_FREE) instead",
                        )
                        break
                    if c < 0 and isinstance(op, _ORDERED_OPS):
                        yield self.finding(
                            pf, node,
                            f"ordered comparison against {c} on a"
                            f" {'/'.join(sorted(sent.sentinels))}-domain"
                            " value treats categorical sentinels as"
                            " magnitudes — compare with == / != against"
                            " the named constants",
                        )
                        break
                    if (
                        c < 0
                        and isinstance(op, (ast.Eq, ast.NotEq))
                        and self._domain_values(sent)
                        and c not in self._domain_values(sent)
                    ):
                        yield self.finding(
                            pf, node,
                            f"equality test against {c}, which is not a"
                            " registered sentinel of domain"
                            f" {'/'.join(sorted(sent.sentinels))} —"
                            " add it to the registry (solver/gangs) or"
                            " fix the literal",
                        )
                        break
            for node in pf.walk(ast.BinOp):
                if not isinstance(node.op, (ast.Add, ast.Sub, ast.Mult)):
                    continue
                fn = pf.enclosing_function(node)
                left = df.absval(pf, node.left, fn)
                right = df.absval(pf, node.right, fn)
                if (
                    left.sentinels and right.sentinels
                    and left.sentinels.isdisjoint(right.sentinels)
                ):
                    yield self.finding(
                        pf, node,
                        "arithmetic mixes values from different sentinel"
                        f" domains ({'/'.join(sorted(left.sentinels))} vs"
                        f" {'/'.join(sorted(right.sentinels))})",
                    )


def _clip_pattern(node: ast.AST):
    """(inner expr, lo, hi) of a `min(max(x, lo), hi)` / `max(min(x, hi),
    lo)` / np.clip(x, lo, hi) expression with literal bounds, else None."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    tail = name.rsplit(".", 1)[-1] if name else ""
    if tail == "clip" and len(node.args) >= 3:
        lo, hi = _literal_number(node.args[1]), _literal_number(node.args[2])
        if lo is not None and hi is not None:
            return node.args[0], lo, hi
        return None
    if name not in ("min", "max") or len(node.args) != 2:
        return None
    outer_bound = _literal_number(node.args[1])
    inner = node.args[0]
    if outer_bound is None or not isinstance(inner, ast.Call):
        return None
    iname = dotted_name(inner.func)
    if iname not in ("min", "max") or iname == name or len(inner.args) != 2:
        return None
    inner_bound = _literal_number(inner.args[1])
    if inner_bound is None:
        return None
    lo, hi = sorted((outer_bound, inner_bound))
    return inner.args[0], lo, hi


@register
class ClampSaturation(Rule):
    id = "GL603"
    name = "clamp-saturation"
    rationale = (
        "when a summed cost's per-term static intervals can exceed the"
        " outer clamp bound, the clamp stops being a backstop and starts"
        " collapsing distinct costs onto the bound — erasing every"
        " lower-order tiebreak term (the eviction_cost ±9 regression)"
    )
    scope = "project"

    def check_project(self, files: List[ParsedFile]) -> Iterable:
        df = dataflow.get_ranges(files)
        for pf in files:
            for node in pf.walk(ast.Return):
                if node.value is None:
                    continue
                pat = _clip_pattern(node.value)
                if pat is None:
                    continue
                inner, lo, hi = pat
                fn = pf.enclosing_function(node)
                if fn is None:
                    continue
                v = df.absval(pf, inner, fn)
                # positive evidence only: a fully-known finite hull that
                # strictly exceeds the clamp. Reaching the bound exactly
                # is fine (nothing collapses); exceeding it is not.
                if v.lo == -dataflow.INF or v.hi == dataflow.INF:
                    continue
                if v.hi > hi or v.lo < lo:
                    yield self.finding(
                        pf, node,
                        f"clamped return: the interior's static interval"
                        f" [{v.lo:g}, {v.hi:g}] exceeds the clamp bounds"
                        f" [{lo:g}, {hi:g}] — values past the bound"
                        " collapse onto it, erasing lower-order tiebreak"
                        " terms; tighten the per-term clamps so their sum"
                        " stays inside the contract",
                    )


_REDUCTIONS = {"argmin", "argmax", "min", "max", "sum", "prod", "mean",
               "any", "all"}


@register
class PaddingInertness(Rule):
    id = "GL604"
    name = "padding-inertness"
    rationale = (
        "padded rows exist to make shapes divide meshes and buckets — an"
        " unmasked reduction/argmin over pad-provenance content inside a"
        " jit region lets inert slots vote (a padded slot wins the"
        " argmin, a padded row inflates the sum); route through"
        " jnp.where with a validity mask first"
    )
    scope = "project"

    def _targets(self, files: List[ParsedFile]) -> List[ParsedFile]:
        out = []
        for pf in files:
            p = f"/{pf.relpath}"
            if "/ops/" in p or "/models/" in p or "gl604" in pf.relpath:
                out.append(pf)
        return out

    def check_project(self, files: List[ParsedFile]) -> Iterable:
        targets = self._targets(files)
        if not targets:
            return
        from tools.graftlint.rules import jaxpurity as _jp

        df = dataflow.get_ranges(files)
        for pf in targets:
            traced = _jp._index(pf).traced
            for node in pf.walk(ast.Call):
                fn = pf.enclosing_function(node)
                if fn is None or fn not in traced:
                    continue  # host-side reductions window padding freely
                name = dotted_name(node.func)
                tail = name.rsplit(".", 1)[-1] if name else ""
                if tail not in _REDUCTIONS:
                    continue
                operand: Optional[ast.AST] = None
                if name.startswith(("jnp.", "jax.numpy.")) and node.args:
                    operand = node.args[0]
                elif isinstance(node.func, ast.Attribute) and not (
                    name.startswith(("np.", "numpy."))
                ):
                    operand = node.func.value
                if operand is None:
                    continue
                v = df.absval(pf, operand, fn)
                if PAD in v.taints and MASKED not in v.guards:
                    yield self.finding(
                        pf, node,
                        f"{tail} over pad-provenance content inside a"
                        " traced region with no masking step — the inert"
                        " padded rows participate in the reduction; wrap"
                        " the operand in jnp.where(valid, x, neutral)"
                        " first",
                    )
