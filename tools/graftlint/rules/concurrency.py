"""Family 3 — lock discipline in threaded modules.

The solverd sidecar serves from a ThreadingHTTPServer: every handler runs
on its own thread against one shared ``SolverDaemon``, and the supervisor's
handshake reader runs beside the operator loop. In that world an unlocked
``self.x += 1`` is a lost update and a field guarded in one method but
bare in another is a torn read waiting for load. These rules only engage
in modules that actually create threads (``threading.Thread`` /
``ThreadingHTTPServer``), so single-threaded host code stays noise-free.

GL301 thread-daemon-explicit — every threading.Thread must pass daemon=
GL302 unlocked-rmw           — read-modify-write on self attributes
                               outside the owning lock
GL303 mixed-lock-discipline  — attribute written both under a lock and
                               bare in the same class
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.graftlint.engine import ParsedFile, Rule, dotted_name, register

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "Lock", "RLock",
    "threading.Condition", "Condition",
}
_MUTATOR_METHODS = {
    "append", "extend", "insert", "pop", "remove", "clear", "update",
    "setdefault", "popitem", "add", "discard",
}


def _creates_threads(pf: ParsedFile) -> bool:
    for node in pf.walk(ast.Call):
        name = dotted_name(node.func)
        if name in ("threading.Thread", "Thread"):
            return True
        if name.endswith("ThreadingHTTPServer") or name.endswith(
            "ThreadingTCPServer"
        ):
            return True
    for node in pf.walk(ast.Name):
        if node.id in ("ThreadingHTTPServer", "ThreadingTCPServer"):
            return True
    return False


@register
class ThreadDaemonExplicit(Rule):
    id = "GL301"
    name = "thread-daemon-explicit"
    rationale = (
        "a Thread without an explicit daemon= silently blocks interpreter"
        " shutdown (or silently dies with it) depending on the default —"
        " the operator's exit behavior must be a decision, not an accident"
    )

    def check(self, pf: ParsedFile):
        for node in pf.walk(ast.Call):
            if dotted_name(node.func) not in ("threading.Thread", "Thread"):
                continue
            if any(kw.arg == "daemon" for kw in node.keywords):
                continue
            yield self.finding(
                pf, node,
                "threading.Thread without explicit daemon= — decide whether"
                " this thread may outlive the process teardown",
            )


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """self attributes assigned a Lock/RLock/Condition anywhere in cls."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        if dotted_name(node.value.func) not in _LOCK_CTORS:
            continue
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                out.add(tgt.attr)
    return out


def _locks_held(
    pf: ParsedFile, node: ast.AST, lock_attrs: Set[str]
) -> frozenset:
    """The owning-lock attributes held at node (``with self.<lock>:`` or a
    lock-method acquire context up the parent chain). Empty = bare."""
    held = set()
    for p in pf.parents(node):
        if not isinstance(p, (ast.With, ast.AsyncWith)):
            continue
        for item in p.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                expr = expr.func  # self._lock.acquire()-style contexts
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in lock_attrs
            ):
                held.add(expr.attr)
    return frozenset(held)


def _method_of(pf: ParsedFile, node: ast.AST) -> Optional[str]:
    fn = pf.enclosing_function(node)
    return getattr(fn, "name", None) if fn is not None else None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _mentions_self_attr(expr: ast.AST, attr: str) -> bool:
    for n in ast.walk(expr):
        if _self_attr(n) == attr:
            return True
    return False


@register
class UnlockedReadModifyWrite(Rule):
    id = "GL302"
    name = "unlocked-rmw"
    rationale = (
        "self.x += 1 (or self.x = f(self.x)) outside the owning lock in a"
        " threaded module is a lost update — two handler threads read the"
        " same old value"
    )

    def check(self, pf: ParsedFile):
        if not _creates_threads(pf):
            return
        for cls in pf.walk(ast.ClassDef):
            locks = _lock_attrs(cls)
            if not locks:
                continue
            for node in ast.walk(cls):
                target_attr = None
                if isinstance(node, ast.AugAssign):
                    target_attr = _self_attr(node.target)
                elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                    attr = _self_attr(node.targets[0])
                    if attr is not None and _mentions_self_attr(node.value, attr):
                        target_attr = attr
                if target_attr is None:
                    continue
                if _method_of(pf, node) == "__init__":
                    continue  # construction happens-before publication
                if _locks_held(pf, node, locks):
                    # any owning lock counts here; GL303 catches the
                    # same attribute guarded by DIFFERENT locks
                    continue
                yield self.finding(
                    pf, node,
                    f"read-modify-write of self.{target_attr} outside"
                    f" lock(s) {sorted(locks)} in threaded class"
                    f" {cls.name!r} — lost-update race",
                )


@register
class MixedLockDiscipline(Rule):
    id = "GL303"
    name = "mixed-lock-discipline"
    rationale = (
        "an attribute written under the lock in one method and bare (or"
        " under a DIFFERENT lock) in another has no consistent owner —"
        " every reader must assume the weaker discipline"
    )

    def check(self, pf: ParsedFile):
        if not _creates_threads(pf):
            return
        for cls in pf.walk(ast.ClassDef):
            locks = _lock_attrs(cls)
            if not locks:
                continue
            # attr -> guard signature (frozenset of held locks) -> sites
            writes: Dict[str, Dict[frozenset, List[ast.AST]]] = {}
            for node in ast.walk(cls):
                attr = None
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for tgt in targets:
                        a = _self_attr(tgt)
                        if a is None and isinstance(
                            tgt, ast.Subscript
                        ):
                            a = _self_attr(tgt.value)
                        if a is not None:
                            attr = a
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ) and node.func.attr in _MUTATOR_METHODS:
                    attr = _self_attr(node.func.value)
                elif isinstance(node, ast.Delete):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Subscript):
                            a = _self_attr(tgt.value)
                            if a is not None:
                                attr = a
                if attr is None or attr in locks:
                    continue
                if _method_of(pf, node) == "__init__":
                    continue
                guard = _locks_held(pf, node, locks)
                writes.setdefault(attr, {}).setdefault(guard, []).append(node)
            for attr in sorted(writes):
                guards = writes[attr]
                if len(guards) < 2:
                    continue
                # flag every site not under the dominant guard (most
                # sites; ties prefer a locked guard over bare)
                dominant = max(
                    guards, key=lambda g: (len(guards[g]), len(g))
                )
                for guard in sorted(guards, key=sorted):
                    if guard == dominant:
                        continue
                    have = (
                        f"lock(s) {sorted(guard)}" if guard else "no lock"
                    )
                    want = (
                        f"lock(s) {sorted(dominant)}"
                        if dominant
                        else "no lock"
                    )
                    for node in guards[guard]:
                        yield self.finding(
                            pf, node,
                            f"self.{attr} is written under {want}"
                            f" elsewhere in {cls.name!r} but under"
                            f" {have} here — pick one discipline",
                        )
