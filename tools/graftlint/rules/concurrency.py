"""Family 3 — lock discipline in threaded modules.

The solverd sidecar serves from a ThreadingHTTPServer: every handler runs
on its own thread against one shared ``SolverDaemon``, and the supervisor's
handshake reader runs beside the operator loop. In that world an unlocked
``self.x += 1`` is a lost update and a field guarded in one method but
bare in another is a torn read waiting for load. These rules only engage
in modules that actually create threads (``threading.Thread`` /
``ThreadingHTTPServer``), so single-threaded host code stays noise-free.

GL301 thread-daemon-explicit — every threading.Thread must pass daemon=
GL304 blocking-io-under-grant — file/network I/O statically reachable
                               while the FleetGateway device grant or the
                               SolverDaemon ``_state_lock`` is held (the
                               lint form of the PR 8/9 review findings:
                               journal I/O off the exclusive device
                               window, disk-full begin() wedging the
                               gateway)

GL302 (unlocked-rmw) and GL303 (mixed-lock-discipline) retired: subsumed
by GL702 in the lockgraph family (tools/graftlint/rules/lockgraph.py),
which infers each attribute's guard from the majority of its write sites'
interprocedurally-propagated held-lock sets instead of per-file lexical
``with`` nesting — so a ``_locked`` helper called three frames under the
lock no longer reads as bare, and a bare write only flags when a spawned
thread can actually reach it.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.engine import ParsedFile, Rule, dotted_name, register

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "Lock", "RLock",
    "threading.Condition", "Condition",
}
_MUTATOR_METHODS = {
    "append", "extend", "insert", "pop", "remove", "clear", "update",
    "setdefault", "popitem", "add", "discard",
}


def _creates_threads(pf: ParsedFile) -> bool:
    for node in pf.walk(ast.Call):
        name = dotted_name(node.func)
        if name in ("threading.Thread", "Thread"):
            return True
        if name.endswith("ThreadingHTTPServer") or name.endswith(
            "ThreadingTCPServer"
        ):
            return True
    for node in pf.walk(ast.Name):
        if node.id in ("ThreadingHTTPServer", "ThreadingTCPServer"):
            return True
    return False


@register
class ThreadDaemonExplicit(Rule):
    id = "GL301"
    name = "thread-daemon-explicit"
    rationale = (
        "a Thread without an explicit daemon= silently blocks interpreter"
        " shutdown (or silently dies with it) depending on the default —"
        " the operator's exit behavior must be a decision, not an accident"
    )

    def check(self, pf: ParsedFile):
        for node in pf.walk(ast.Call):
            if dotted_name(node.func) not in ("threading.Thread", "Thread"):
                continue
            if any(kw.arg == "daemon" for kw in node.keywords):
                continue
            yield self.finding(
                pf, node,
                "threading.Thread without explicit daemon= — decide whether"
                " this thread may outlive the process teardown",
            )


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """self attributes assigned a Lock/RLock/Condition anywhere in cls."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        if dotted_name(node.value.func) not in _LOCK_CTORS:
            continue
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                out.add(tgt.attr)
    return out


def _locks_held(
    pf: ParsedFile, node: ast.AST, lock_attrs: Set[str]
) -> frozenset:
    """The owning-lock attributes held at node (``with self.<lock>:`` or a
    lock-method acquire context up the parent chain). Empty = bare."""
    held = set()
    for p in pf.parents(node):
        if not isinstance(p, (ast.With, ast.AsyncWith)):
            continue
        for item in p.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                expr = expr.func  # self._lock.acquire()-style contexts
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in lock_attrs
            ):
                held.add(expr.attr)
    return frozenset(held)


def _method_of(pf: ParsedFile, node: ast.AST) -> Optional[str]:
    fn = pf.enclosing_function(node)
    return getattr(fn, "name", None) if fn is not None else None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _mentions_self_attr(expr: ast.AST, attr: str) -> bool:
    for n in ast.walk(expr):
        if _self_attr(n) == attr:
            return True
    return False


# ---------------------------------------------------------------------------
# GL304: blocking I/O under the device grant / the daemon state lock.
#
# The exclusive device window is the scarcest resource in the whole tier:
# every queued tenant is waiting on it, and the watchdog kills the process
# when it runs long. File and network I/O have unbounded tails (disk-full,
# NFS stall, DNS hang), so any I/O reachable while the grant is held turns
# one slow disk into a fleet-wide stall — exactly the PR 8/9 review
# findings (quarantine journal writes moved off the window; a disk-full
# begin() after collect_batch would have wedged the gateway). This rule
# rides the project call graph (the ISSUE 11 engine growth): a per-def
# does-I/O summary is iterated to a fixpoint, then every call inside a
# grant-held or _state_lock-held region is checked against it.

# NOTE: no "requests." prefix — in this codebase `requests` is the
# ubiquitous resource-vector variable name, not the HTTP library (which
# the tree does not use); http rides httpclient/socket instead
_IO_CALL_PREFIXES = (
    "shutil.", "socket.", "urllib.", "subprocess.",
)
_IO_OS_TAILS = {
    "replace", "rename", "remove", "unlink", "fsync", "write", "makedirs",
    "mkdir", "rmdir", "truncate",
}
_IO_PATH_TAILS = {"write_text", "read_text", "write_bytes", "read_bytes"}
# ubiquitous method names the call-graph propagation must not resolve
# through: name-tail resolution would connect `cache.get` to an HTTP
# client's `get` and drown the rule in noise
_IO_PROPAGATION_STOPLIST = {
    "get", "put", "set", "update", "add", "pop", "remove", "clear",
    "close", "run", "send", "solve", "encode", "decode", "items",
    "values", "keys", "next", "check", "info", "debug", "warning",
    "error", "exception", "log", "observe", "inc", "append", "join",
    "main", "start", "step", "stop",
}
_IO_MAX_CANDIDATES = 2
_GRANT_ACQUIRE_TAILS = {"await_grant"}
_GRANT_RELEASE_TAILS = {"release", "release_batch"}


def _direct_io_call(name: str, tail: str) -> bool:
    if name in ("open", "io.open", "urlopen", "os.open"):
        return True
    if name.startswith("os.") and tail in _IO_OS_TAILS:
        return True
    if name.startswith(_IO_CALL_PREFIXES):
        return True
    if tail in _IO_PATH_TAILS:
        return True
    return False


def _io_summaries(files: List[ParsedFile]) -> Set[int]:
    """ids of every def that (transitively) performs blocking I/O.

    One AST walk per def collects its direct-I/O verdict and the compact
    set of propagatable call tails; the fixpoint then iterates over those
    precomputed edge lists (each pass only grows the set, so it
    terminates; real chains are 2-3 deep)."""
    defs: Dict[str, List[ast.AST]] = {}
    # id(fn) -> the call tails propagation may resolve through
    edges: Dict[int, Set[str]] = {}
    does_io: Set[int] = set()
    for pf in files:
        for fn in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            defs.setdefault(fn.name, []).append(fn)
            tails: Set[str] = set()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                tail = name.rsplit(".", 1)[-1] if name else ""
                if _direct_io_call(name, tail):
                    does_io.add(id(fn))
                elif tail and tail not in _IO_PROPAGATION_STOPLIST:
                    tails.add(tail)
            edges[id(fn)] = tails
    while True:
        grew = False
        for cands in defs.values():
            for fn in cands:
                if id(fn) in does_io:
                    continue
                for tail in edges[id(fn)]:
                    callees = defs.get(tail, ())
                    if not callees or len(callees) > _IO_MAX_CANDIDATES:
                        continue
                    if any(id(c) in does_io for c in callees):
                        does_io.add(id(fn))
                        grew = True
                        break
        if not grew:
            break
    return does_io


def _grant_region(fn: ast.AST) -> Optional[Tuple[int, float]]:
    """(first held line EXCLUSIVE, last held line INCLUSIVE) of the device
    grant inside one function, or None.

    Two idioms: a function that calls ``await_grant`` holds the grant from
    that call to its last ``release``/``release_batch`` call (or to the
    end when it never releases — the release happens in a callee); a
    function that releases WITHOUT acquiring (``_solve_as_leader``) was
    handed the grant by its caller and holds it from entry."""
    acquire = None
    release_end = None
    submits = False
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        tail = dotted_name(node.func).rsplit(".", 1)[-1]
        if tail in _GRANT_ACQUIRE_TAILS:
            ln = node.lineno
            acquire = ln if acquire is None else min(acquire, ln)
        elif tail in _GRANT_RELEASE_TAILS:
            ln = getattr(node, "end_lineno", node.lineno)
            release_end = (
                ln if release_end is None else max(release_end, ln)
            )
        elif tail == "submit":
            submits = True
    if acquire is not None:
        return (acquire, release_end or float("inf"))
    if release_end is not None and not submits:
        # grant-entered-from-entry: the leader path
        return (fn.lineno, release_end)
    return None


@register
class BlockingIoUnderGrant(Rule):
    id = "GL304"
    name = "blocking-io-under-grant"
    rationale = (
        "file/network I/O while the exclusive device grant (or the"
        " daemon's _state_lock) is held turns one slow disk into a"
        " fleet-wide stall: every queued tenant waits on the window and"
        " the watchdog kills the process when it runs long — do the I/O"
        " on the handler thread before the grant or after release"
    )
    scope = "project"

    def _applies(self, pf: ParsedFile) -> bool:
        return "/solver/" in f"/{pf.relpath}" or "gl304" in pf.relpath

    def check_project(self, files: List[ParsedFile]):
        targets = [pf for pf in files if self._applies(pf)]
        if not targets:
            return
        does_io = _io_summaries(files)
        defs: Dict[str, List[ast.AST]] = {}
        for pf in files:
            for node in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
                defs.setdefault(node.name, []).append(node)

        def call_does_io(node: ast.Call) -> Optional[str]:
            name = dotted_name(node.func)
            tail = name.rsplit(".", 1)[-1] if name else ""
            if _direct_io_call(name, tail):
                return name or tail
            if tail in _IO_PROPAGATION_STOPLIST:
                return None
            callees = defs.get(tail, ())
            if callees and len(callees) <= _IO_MAX_CANDIDATES and any(
                id(c) in does_io for c in callees
            ):
                return tail
            return None

        for pf in targets:
            for fn in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
                region = _grant_region(fn)
                # _state_lock-held spans inside this function
                locked_spans: List[Tuple[int, int]] = []
                for w in ast.walk(fn):
                    if not isinstance(w, (ast.With, ast.AsyncWith)):
                        continue
                    for item in w.items:
                        expr = item.context_expr
                        if isinstance(expr, ast.Call):
                            expr = expr.func
                        if (
                            isinstance(expr, ast.Attribute)
                            and expr.attr == "_state_lock"
                        ):
                            locked_spans.append(
                                (w.lineno, getattr(w, "end_lineno", w.lineno))
                            )
                if region is None and not locked_spans:
                    continue
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    tail = dotted_name(node.func).rsplit(".", 1)[-1]
                    if tail in _GRANT_RELEASE_TAILS | _GRANT_ACQUIRE_TAILS:
                        continue
                    held_by = None
                    if region is not None and (
                        region[0] < node.lineno <= region[1]
                    ):
                        held_by = "the exclusive device grant"
                    for lo, hi in locked_spans:
                        if lo < node.lineno <= hi:
                            held_by = "_state_lock"
                            break
                    if held_by is None:
                        continue
                    callees = defs.get(tail, ())
                    if callees and len(callees) <= _IO_MAX_CANDIDATES and any(
                        _grant_region(c) is not None for c in callees
                    ):
                        # the callee is itself a grant-holding function
                        # (the leader path): its interior is analyzed on
                        # its own — flagging the call site too would
                        # double-report every finding at the caller
                        continue
                    culprit = call_does_io(node)
                    if culprit is not None:
                        yield self.finding(
                            pf, node,
                            f"call to {culprit!r} reaches blocking"
                            f" file/network I/O while {held_by} is held —"
                            " move the I/O off the exclusive window"
                            " (before the grant or after release)",
                        )
