"""Rule families. Importing this package registers every rule.

One module per family; each rule documents the invariant it protects and
names the code that established it. Add a new family by creating a module
here and importing it below.
"""
from tools.graftlint.rules import (  # noqa: F401
    concurrency,
    determinism,
    jaxpurity,
    lockgraph,
    parity,
    rangecheck,
    sharding,
)
