"""Family 7 — the interprocedural lock graph over the solver tier.

The GL3xx heuristics see one file and one lexical ``with`` block at a
time. The solver tier's actual discipline is interprocedural: FleetGateway
``_locked`` helpers re-enter an RLock three frames below the handler
thread, the daemon's ``_state_lock`` must never nest under the gateway
lock (service.set_brownout documents the ordering by hand), and the
coalescer hands Ticket objects across threads through Event fields. These
rules ride the third dataflow domain (dataflow.LockDataflow): lock
identity keyed by (class, attribute), may-held sets propagated through
the call graph to a fixpoint, thread reachability closed over Thread
targets and HTTP ``do_*`` handlers, and per-attribute guard inference by
strict write-site majority.

GL701 lock-order-cycle      — cycles in the acquired-while-held graph
                              (including cross-object cycles and
                              wait/join-mediated edges), plus one-edge
                              deadlocks: re-acquiring a non-reentrant
                              Lock, waiting on an event whose setter
                              needs a held lock, joining a thread that
                              acquires one
GL702 unguarded-access      — a write/RMW of a guard-inferred attribute
                              from a thread-reachable method whose
                              may-held set misses the guard (subsumes
                              and retires GL302/GL303)
GL703 thread-escape         — a guarded mutable container escaping to
                              another thread (Thread args, handoff-field
                              stores) as the live object, not a snapshot
GL704 wait-discipline       — Condition.wait outside a predicate re-check
                              loop, notify outside the owning lock,
                              timed wait results discarded

Every rule flags on positive evidence only: a lock the may-held
over-approximation cannot prove absent, a guard inference that ties, or
a receiver the resolver cannot type all degrade to silence.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from tools.graftlint import dataflow
from tools.graftlint.engine import ParsedFile, Rule, dotted_name, register
from tools.graftlint.rules.concurrency import _direct_io_call


def _fmt_cycle(cycle: List[str]) -> str:
    return " -> ".join(cycle + [cycle[0]])


# a guarded value wrapped in one of these is a SNAPSHOT, not the live
# aliased object — the sanctioned way to hand state across threads
_SNAPSHOT_CALLS = {
    "dict", "list", "tuple", "set", "frozenset", "sorted",
    "copy.copy", "copy.deepcopy",
}


def _snapshotted(pf: ParsedFile, sub: ast.AST, expr: ast.AST) -> bool:
    """True when ``sub`` sits inside a snapshot-constructor call within
    ``expr`` (``args=(dict(self.members),)`` hands off a copy)."""
    for p in pf.parents(sub):
        if isinstance(p, ast.Call) and dotted_name(p.func) in _SNAPSHOT_CALLS:
            return True
        if p is expr:
            break
    return False


@register
class LockOrderCycle(Rule):
    id = "GL701"
    name = "lock-order-cycle"
    rationale = (
        "two threads acquiring the same locks in opposite orders deadlock"
        " the tier; the order graph (acquired-while-held, plus wait/join"
        " edges: blocking on a thread that needs a lock you hold) must"
        " stay acyclic — one cycle wedges every handler thread behind it"
    )
    scope = "project"

    def check_project(self, files: List[ParsedFile]):
        df = dataflow.get_locks(files)
        by_relpath = {pf.relpath: pf for pf in files}

        for lid, relpath, line, reason in df.self_deadlocks:
            pf = by_relpath.get(relpath)
            if pf is None:
                continue
            yield self.finding(
                pf, _at(line), f"deadlock: {reason}"
            )

        in_cycle = {lock for cyc in df.cycles() for lock in cyc}
        cycle_of = {}
        for cyc in df.cycles():
            for lock in cyc:
                cycle_of[lock] = cyc
        seen = set()
        for (src, dst), witnesses in sorted(df.order_edges.items()):
            if src not in in_cycle or dst not in cycle_of.get(src, ()):
                continue
            relpath, line, via = witnesses[0]
            pf = by_relpath.get(relpath)
            if pf is None:
                continue
            key = (src, dst)
            if key in seen:
                continue
            seen.add(key)
            cyc = cycle_of[src]
            yield self.finding(
                pf, _at(line),
                f"lock-order cycle {_fmt_cycle(cyc)}: {dst} is acquired"
                f" ({via}) while {src} is held here, and the reverse"
                " order exists elsewhere — pick one global order",
            )


@register
class UnguardedAccess(Rule):
    id = "GL702"
    name = "unguarded-access"
    rationale = (
        "an attribute written under its inferred guard at most sites but"
        " bare on a thread-reachable path is a lost update / torn read:"
        " the majority discipline IS the contract, and the odd site out"
        " breaks it exactly where another thread can interleave"
        " (subsumes the retired GL302/GL303)"
    )
    scope = "project"

    def check_project(self, files: List[ParsedFile]):
        df = dataflow.get_locks(files)
        for (cname, attr), sites in sorted(df.write_sites.items()):
            guard = df.inferred_guards.get(cname, {}).get(attr)
            if guard is None:
                continue
            for site in sites:
                if guard in site.held:
                    continue
                if not df.thread_reachable(site.pf, site.fn):
                    continue
                verb = {
                    "assign": "write to", "augassign": "read-modify-write of",
                    "mutate": "in-place mutation of", "del": "deletion from",
                }.get(site.kind, "write to")
                yield self.finding(
                    site.pf, site.node,
                    f"{verb} self.{attr} without {guard} — the other"
                    f" write sites in {cname!r} hold it (inferred guard),"
                    " and this method runs on a spawned thread",
                )


@register
class ThreadEscape(Rule):
    id = "GL703"
    name = "thread-escape"
    rationale = (
        "handing the LIVE guarded container to another thread (Thread"
        " args, a handoff field on a ticket/callback object) aliases it"
        " outside the guard: the receiver mutates or iterates it with no"
        " lock while the owner keeps writing — pass a snapshot"
        " (dict(...)/list(...)) or the owning object itself"
    )
    scope = "project"

    def check_project(self, files: List[ParsedFile]):
        df = dataflow.get_locks(files)
        for pf in files:
            for cls in pf.walk(ast.ClassDef):
                guards = df.inferred_guards.get(cls.name, {})
                guarded_mutables = {
                    attr for attr in guards
                    if (cls.name, attr) in df.mutable_attrs
                }
                if not guarded_mutables:
                    continue
                for node in ast.walk(cls):
                    esc = self._escape(df, pf, cls, node, guarded_mutables)
                    if esc is not None:
                        attr, how = esc
                        yield self.finding(
                            pf, node,
                            f"guarded mutable self.{attr} (guard"
                            f" {guards[attr]}) escapes to another thread"
                            f" {how} as the live object — hand off a"
                            " snapshot or the owning object instead",
                        )

    def _escape(self, df, pf, cls, node, guarded) -> Optional[tuple]:
        # Thread(target=..., args=(..., self.attr, ...))
        if isinstance(node, ast.Call) and dotted_name(node.func) in (
            "threading.Thread", "Thread"
        ):
            exprs = []
            for kw in node.keywords:
                if kw.arg in ("args", "kwargs"):
                    exprs.append(kw.value)
            for expr in exprs:
                for sub in ast.walk(expr):
                    attr = dataflow._self_attr_of(sub)
                    if attr in guarded and not _snapshotted(pf, sub, expr):
                        return attr, "via Thread args"
            return None
        # handoff-field store: other.field = self.attr (the live ref)
        if isinstance(node, ast.Assign):
            attr = None
            sub = node.value
            a = dataflow._self_attr_of(sub)
            if a in guarded:
                attr = a
            if attr is None:
                return None
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and dataflow._self_attr_of(tgt) is None
                    and not (
                        isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    )
                ):
                    fn = pf.enclosing_function(node)
                    if fn is not None and getattr(fn, "name", "") == "__init__":
                        return None
                    return attr, (
                        "via a handoff-field store"
                        f" ({ast.unparse(tgt) if hasattr(ast, 'unparse') else 'field'})"
                    )
        return None


@register
class WaitDiscipline(Rule):
    id = "GL704"
    name = "wait-discipline"
    rationale = (
        "Condition.wait returns on spurious wakeups and stolen notifies —"
        " only a predicate re-check loop makes it correct; notify outside"
        " the owning lock races the waiter's predicate read; a discarded"
        " wait(timeout=...) result silently treats a timeout as success"
    )
    scope = "project"

    def check_project(self, files: List[ParsedFile]):
        df = dataflow.get_locks(files)
        for pf in files:
            for node in pf.walk(ast.Call):
                func = node.func
                if not isinstance(func, ast.Attribute):
                    continue
                if func.attr in ("wait", "wait_for"):
                    yield from self._check_wait(df, pf, node, func)
                elif func.attr in ("notify", "notify_all"):
                    yield from self._check_notify(df, pf, node, func)

    def _cond_attr(self, df, pf, func) -> Optional[tuple]:
        """(class, attr) when the receiver is a Condition attribute of
        the enclosing class."""
        attr = dataflow._self_attr_of(func.value)
        if attr is None:
            return None
        cls = pf.enclosing_class(func)
        if cls is None:
            return None
        if (cls.name, attr) in df.cond_attrs:
            return cls.name, attr
        return None

    def _event_kind(self, df, pf, func) -> Optional[str]:
        """'Event'/'Condition' when the receiver is a known event-like
        attribute — the enclosing class's registry first, the project-wide
        name registry for receivers precise typing cannot reach."""
        if isinstance(func.value, ast.Attribute):
            attr = func.value.attr
            cls = pf.enclosing_class(func)
            if cls is not None:
                if (cls.name, attr) in df.event_attrs:
                    return "Event"
                if (cls.name, attr) in df.cond_attrs:
                    return "Condition"
            if dataflow._self_attr_of(func.value) is None:
                return df._event_names.get(attr)
        return None

    def _check_wait(self, df, pf, node, func):
        kind = self._event_kind(df, pf, func)
        if kind is None:
            return
        # timed wait result discarded: a timeout is indistinguishable
        # from a set/notify, so the caller just proceeds on failure
        if (node.args or node.keywords) and func.attr == "wait":
            parent = next(pf.parents(node), None)
            if isinstance(parent, ast.Expr):
                yield self.finding(
                    pf, node,
                    f"result of .wait(timeout=...) on a {kind} is"
                    " discarded — a timeout looks identical to success;"
                    " branch on the return value",
                )
        # Condition.wait needs an enclosing predicate re-check loop
        # (wait_for carries its own predicate)
        if kind == "Condition" and func.attr == "wait":
            fn = pf.enclosing_function(node)
            in_loop = any(
                isinstance(p, (ast.While, ast.For))
                for p in pf.parents(node)
                if fn is None or pf.enclosing_function(p) is fn or p is fn
            )
            if not in_loop:
                yield self.finding(
                    pf, node,
                    "Condition.wait outside a predicate re-check loop —"
                    " spurious wakeups and stolen notifies make a bare"
                    " wait return with the predicate still false; use"
                    " `while not pred: cv.wait()` or cv.wait_for(pred)",
                )

    def _check_notify(self, df, pf, node, func):
        cond = self._cond_attr(df, pf, func)
        if cond is None:
            return
        cname, attr = cond
        lid = f"{cname}.{attr}"
        if lid not in df.held_at(pf, node):
            yield self.finding(
                pf, node,
                f".{func.attr}() on Condition self.{attr} outside its"
                " own lock — the notify races the waiter's predicate"
                " write and can be lost; notify inside `with"
                f" self.{attr}:`",
            )


@register
class BlockingUnderLock(Rule):
    id = "GL705"
    name = "blocking-under-lock"
    rationale = (
        "a sleep or direct file/network call lexically inside a lock span"
        " holds every other thread on that lock for the full blocking"
        " tail (disk stall, DNS hang, the sleep itself) — do the blocking"
        " work outside the critical section (GL304's discipline,"
        " generalized from the device grant to every inferred lock)"
    )
    scope = "project"

    def check_project(self, files: List[ParsedFile]):
        df = dataflow.get_locks(files)
        for pf in files:
            for node in pf.walk(ast.Call):
                name = dotted_name(node.func)
                tail = name.rsplit(".", 1)[-1] if name else ""
                is_sleep = name in ("time.sleep", "sleep")
                if not is_sleep and not _direct_io_call(name, tail):
                    continue
                fn = pf.enclosing_function(node)
                if fn is None:
                    continue
                fid = dataflow._fn_key(pf, fn)
                if fid not in df.fn_index:
                    continue
                # lexical spans only: may-held entry sets would flag
                # helpers that ALSO run outside the lock — positive
                # evidence needs the span in this very function
                held = sorted(df._lexical_held(fid, node.lineno))
                if not held:
                    continue
                what = "time.sleep" if is_sleep else (name or tail)
                yield self.finding(
                    pf, node,
                    f"blocking call {what!r} inside the critical section"
                    f" of {held[0]} — every thread queued on the lock"
                    " waits out the blocking tail; move it outside the"
                    " with block",
                )


def _at(line: int):
    """A minimal node-shaped anchor for findings built from witness
    (relpath, line) pairs rather than live AST nodes."""
    class _Anchor:
        lineno = line
    return _Anchor()
