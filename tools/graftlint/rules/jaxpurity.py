"""Family 1 — JAX purity/perf inside traced regions.

The fused hot path (PR 3) is only fast because nothing inside the jit'd
FFD scan touches the host: a stray ``.item()`` or ``np.asarray`` forces a
device sync per scan step, and a Python ``if`` on a tracer either crashes
at trace time or — worse — bakes one branch into the compiled program.
These rules build the per-module traced-region call graph (jit roots +
``lax.scan``/``fori_loop``/``while_loop``/``cond``/``vmap`` bodies, then
everything reachable through plain-name calls) and police its interior.

GL101 jit-host-sync        — host-sync calls inside a traced region
GL102 jit-tracer-branch    — Python branching on (non-static) tracer values
GL103 jit-state-no-donate  — jit entry points that carry slot-state
                             without donate_argnums

GL104 (slotstate-unsharded-deviceput) retired: subsumed by GL503 in the
shardcheck family (tools/graftlint/rules/sharding.py), which checks the
same bare-device_put pattern plus every other host materialization of a
slot-sharded value on the interprocedural provenance lattice.
"""
from __future__ import annotations

import ast
import weakref
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.engine import ParsedFile, Rule, dotted_name, register

_TRACED_HOFS = {
    "jax.lax.scan": [0],
    "lax.scan": [0],
    "jax.lax.fori_loop": [2],
    "lax.fori_loop": [2],
    "jax.lax.while_loop": [0, 1],
    "lax.while_loop": [0, 1],
    "jax.lax.cond": [1, 2],
    "lax.cond": [1, 2],
    "jax.lax.switch": [1],
    "lax.switch": [1],
    "jax.vmap": [0],
    "jax.checkpoint": [0],
}

_SYNC_ATTRS = {"item", "tolist"}
_SYNC_CALLS = {"jax.device_get"}
_NUMPY_SYNC_FUNCS = {"asarray", "array", "copy", "save", "savez"}
_CAST_BUILTINS = {"float", "int", "bool"}


def _is_jax_jit(node: ast.AST) -> bool:
    return dotted_name(node) in ("jax.jit", "jit")


def _partial_jit_kwargs(call: ast.Call) -> Optional[Dict[str, ast.AST]]:
    """``partial(jax.jit, **kw)`` -> kw dict; None when not a jit partial."""
    if dotted_name(call.func) not in ("partial", "functools.partial"):
        return None
    if not call.args or not _is_jax_jit(call.args[0]):
        return None
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def _static_names(kw: Dict[str, ast.AST]) -> Set[str]:
    names: Set[str] = set()
    v = kw.get("static_argnames")
    if isinstance(v, ast.Constant) and isinstance(v.value, str):
        names.add(v.value)
    elif isinstance(v, (ast.Tuple, ast.List)):
        for e in v.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                names.add(e.value)
    return names


class _ModuleIndex:
    """Traced-region reachability for one module."""

    def __init__(self, pf: ParsedFile):
        # no self.pf: the index is cached under the ParsedFile as a WEAK
        # key (see _INDEX_CACHE), and a strong value->key reference would
        # keep every entry alive forever
        # name -> EVERY def carrying it (module-level and nested): two
        # same-named inner functions (the conventional `def body` of a
        # lax.scan) must both be traced, not whichever parsed last — a
        # conservative over-approximation that can only add coverage
        self.defs: Dict[str, List[ast.AST]] = {}
        for node in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            self.defs.setdefault(node.name, []).append(node)
        self.jit_sites: List[Tuple[ast.AST, ast.AST, Dict[str, ast.AST]]] = []
        roots: List[ast.AST] = []

        for node in pf.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            for dec in node.decorator_list:
                if _is_jax_jit(dec):
                    roots.append(node)
                    self.jit_sites.append((dec, node, {}))
                elif isinstance(dec, ast.Call):
                    kw = _partial_jit_kwargs(dec)
                    if kw is not None:
                        roots.append(node)
                        self.jit_sites.append((dec, node, kw))
                    elif _is_jax_jit(dec.func):
                        roots.append(node)
                        kw2 = {k.arg: k.value for k in dec.keywords if k.arg}
                        self.jit_sites.append((dec, node, kw2))

        for call in pf.walk(ast.Call):
            name = dotted_name(call.func)
            # jax.jit(f, ...) / partial(jax.jit, ...)(f)
            wrapped: Optional[ast.AST] = None
            kw: Optional[Dict[str, ast.AST]] = None
            if _is_jax_jit(call.func) and call.args:
                wrapped = call.args[0]
                kw = {k.arg: k.value for k in call.keywords if k.arg}
            elif isinstance(call.func, ast.Call):
                inner_kw = _partial_jit_kwargs(call.func)
                if inner_kw is not None and call.args:
                    wrapped = call.args[0]
                    kw = inner_kw
            if wrapped is not None:
                for target in self._resolve(wrapped):
                    roots.append(target)
                    self.jit_sites.append((call, target, kw or {}))
                continue
            # traced higher-order functions: their body args are traced
            argidx = _TRACED_HOFS.get(name)
            if argidx:
                for i in argidx:
                    if i < len(call.args):
                        roots.extend(self._resolve(call.args[i]))

        # static names are tracked PER FUNCTION: a name marked static on
        # one jit entry must not exempt a same-named non-static parameter
        # of another traced function. Roots seed from their own
        # static_argnames; a callee param becomes static when some call
        # site feeds it a constant or a caller-static name (propagated to
        # a fixpoint) — an under-approximation that favors missing a
        # mixed-static param over false-flagging a genuinely static one.
        self.static_by_fn: Dict[ast.AST, Set[str]] = {}
        for _site, target, kw in self.jit_sites:
            self.static_by_fn.setdefault(target, set()).update(
                _static_names(kw)
            )

        self.traced: Set[ast.AST] = set()
        frontier = list(roots)
        while frontier:
            fn = frontier.pop()
            self.traced.add(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Lambda):
                    self.traced.add(node)
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                ):
                    continue
                for callee in self.defs.get(node.func.id, ()):
                    grew = self._propagate_statics(node, fn, callee)
                    # re-enqueue on growth so statics reach transitive
                    # callees; static sets only grow, so this terminates
                    if callee not in self.traced or grew:
                        frontier.append(callee)

        self.numpy_aliases: Set[str] = set()
        for node in pf.walk(ast.Import):
            for alias in node.names:
                if alias.name == "numpy":
                    self.numpy_aliases.add(alias.asname or "numpy")

    def _fn_statics(self, fn: Optional[ast.AST]) -> Set[str]:
        """Static names visible inside fn — its own plus (for closures
        like scan lambdas) every enclosing function's."""
        out: Set[str] = set()
        cur = fn
        while cur is not None:
            out |= self.static_by_fn.get(cur, set())
            cur = getattr(cur, "_gl_parent", None)
            while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                cur = getattr(cur, "_gl_parent", None)
        return out

    def _propagate_statics(self, call: ast.Call, caller, callee) -> bool:
        """Mark callee params static when this call site feeds them a
        constant or a caller-static name. Returns True when the set grew."""
        caller_static = self._fn_statics(caller)

        def is_static_arg(a: ast.AST) -> bool:
            return isinstance(a, ast.Constant) or (
                isinstance(a, ast.Name) and a.id in caller_static
            )

        params = _params(callee)
        tgt = self.static_by_fn.setdefault(callee, set())
        before = len(tgt)
        for i, a in enumerate(call.args):
            if i < len(params) and is_static_arg(a):
                tgt.add(params[i])
        for kwarg in call.keywords:
            if kwarg.arg and kwarg.arg in params and is_static_arg(kwarg.value):
                tgt.add(kwarg.arg)
        return len(tgt) > before

    def _resolve(self, node: ast.AST) -> List[ast.AST]:
        """Defs a callable expression may denote (every same-named def)."""
        if isinstance(node, ast.Name):
            return list(self.defs.get(node.id, ()))
        if isinstance(node, (ast.Lambda, ast.FunctionDef)):
            return [node]
        return []

    def traced_body_nodes(self):
        """(owner fn, node) pairs for every node inside a traced function,
        skipping nodes that belong to a nested non-traced def."""
        for fn in self.traced:
            for node in ast.walk(fn):
                owner = self._owner(node, fn)
                if owner is fn:
                    yield fn, node

    def _owner(self, node: ast.AST, default):
        """Innermost enclosing function of a node (default at module top)."""
        cur = getattr(node, "_gl_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return cur
            cur = getattr(cur, "_gl_parent", None)
        return default


def _accel_file(pf: ParsedFile) -> bool:
    return pf.relpath.endswith(".py") and (
        "/ops/" in f"/{pf.relpath}" or "/models/" in f"/{pf.relpath}"
    )


# weak keys: an entry lives exactly as long as its ParsedFile — a run's
# parse (and the module tree the index pins through its def tables) frees
# when the run drops it, instead of accumulating per lint invocation
_INDEX_CACHE: "weakref.WeakKeyDictionary[ParsedFile, _ModuleIndex]" = (
    weakref.WeakKeyDictionary()
)


def _index(pf: ParsedFile) -> _ModuleIndex:
    idx = _INDEX_CACHE.get(pf)
    if idx is None:
        idx = _INDEX_CACHE[pf] = _ModuleIndex(pf)
    return idx


def _params(fn) -> List[str]:
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = fn.args
        return [
            p.arg
            for p in (a.posonlyargs + a.args + a.kwonlyargs)
        ]
    return []


@register
class JitHostSync(Rule):
    id = "GL101"
    name = "jit-host-sync"
    rationale = (
        "host syncs (.item/.tolist, numpy calls, jax.device_get, float/int"
        " on tracers, print) inside a traced region serialize the device"
        " pipeline per scan step"
    )

    def applies(self, pf: ParsedFile) -> bool:
        return _accel_file(pf)

    def check(self, pf: ParsedFile):
        idx = _index(pf)
        seen = set()
        for fn, node in idx.traced_body_nodes():
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            seen.add(id(node))
            name = dotted_name(node.func)
            msg = None
            if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_ATTRS:
                msg = f".{node.func.attr}() forces a device->host sync"
            elif name in _SYNC_CALLS:
                msg = f"{name} forces a device->host transfer"
            elif "." in name and name.split(".", 1)[0] in idx.numpy_aliases:
                func = name.split(".", 1)[1]
                if func in _NUMPY_SYNC_FUNCS:
                    msg = f"{name} materializes the tracer on host"
            elif name in _CAST_BUILTINS and node.args:
                arg = node.args[0]
                if not isinstance(arg, ast.Constant):
                    msg = (
                        f"{name}() on a traced value is a concretization"
                        " (host sync / trace error)"
                    )
            elif name == "print":
                msg = "print inside a traced region is a host callback"
            if msg:
                owner = getattr(fn, "name", "<lambda>")
                yield self.finding(
                    pf, node, f"{msg} (inside traced function {owner!r})"
                )


def _name_loads(node: ast.AST) -> Set[str]:
    """Names loaded in an expression, excluding names that appear only as
    the base of a static attribute (.shape/.ndim/.dtype/.size — those are
    trace-time constants, branching on them is fine)."""
    direct: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            parent = getattr(n, "_gl_parent", None)
            if (
                isinstance(parent, ast.Attribute)
                and parent.attr in ("shape", "ndim", "dtype", "size")
            ):
                continue
            direct.add(n.id)
    return direct


def _is_none_check(test: ast.AST) -> bool:
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        if isinstance(test.ops[0], (ast.Is, ast.IsNot)):
            return True
    return False


@register
class JitTracerBranch(Rule):
    id = "GL102"
    name = "jit-tracer-branch"
    rationale = (
        "Python if/while/assert on tracer values inside a traced region"
        " either crashes at trace time or silently bakes one branch into"
        " the compiled program; use jnp.where/lax.cond"
    )

    def applies(self, pf: ParsedFile) -> bool:
        return _accel_file(pf)

    def check(self, pf: ParsedFile):
        idx = _index(pf)
        seen = set()
        for fn, node in idx.traced_body_nodes():
            if not isinstance(node, (ast.If, ast.While, ast.Assert, ast.IfExp)):
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            test = node.test
            if _is_none_check(test):
                continue
            params = set(_params(fn)) - idx._fn_statics(fn) - {"self"}
            tainted = _name_loads(test) & params
            if tainted:
                kind = type(node).__name__.lower()
                owner = getattr(fn, "name", "<lambda>")
                yield self.finding(
                    pf, node,
                    f"python {kind} on parameter(s) {sorted(tainted)} of"
                    f" traced function {owner!r} — branch on tracers with"
                    " jnp.where/lax.cond, or mark the arg static",
                )


_STATEY_PARAMS = ("state",)
_STATEY_ANNOTATIONS = ("SlotState",)


def _carries_slot_state(fn) -> Optional[str]:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    for p in fn.args.posonlyargs + fn.args.args:
        ann = ""
        if p.annotation is not None:
            ann = dotted_name(p.annotation) or (
                p.annotation.value
                if isinstance(p.annotation, ast.Constant)
                and isinstance(p.annotation.value, str)
                else ""
            )
        if p.arg in _STATEY_PARAMS or any(
            a in str(ann) for a in _STATEY_ANNOTATIONS
        ):
            return p.arg
    return None


@register
class JitStateNoDonate(Rule):
    id = "GL103"
    name = "jit-state-no-donate"
    rationale = (
        "a jit entry point that threads SlotState without donate_argnums"
        " double-buffers the [N,K,V] requirement planes in HBM every call"
        " (see ops/ffd.ffd_solve_donated)"
    )

    def applies(self, pf: ParsedFile) -> bool:
        return _accel_file(pf)

    def check(self, pf: ParsedFile):
        idx = _index(pf)
        for site, target, kw in idx.jit_sites:
            if "donate_argnums" in kw or "donate_argnames" in kw:
                continue
            param = _carries_slot_state(target)
            if param is None:
                continue
            tname = getattr(target, "name", "<fn>")
            yield self.finding(
                pf, site,
                f"jax.jit of {tname!r} carries slot-state parameter"
                f" {param!r} without donate_argnums — the carry planes"
                " double-buffer in HBM; donate or justify why the caller"
                " reuses the input state",
            )
