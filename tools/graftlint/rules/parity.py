"""Family 4 — wire and registry parity.

The solverd wire (solver/codec.py) is a pair of hand-written codecs; a
field added on the encode side but not the decode side ships silently and
drops on the floor (the ``unavailable_offerings`` near-miss PR 2 fixed by
hand). Same shape for metrics: an instrument incremented at an emission
site but never registered renders a phantom dashboard series. Both are
exact set-equality properties over the AST — no heuristics.

GL401 codec-field-parity — every encode_X/_encode_X in solver/codec.py
                           has a decode twin, and the dict keys the
                           encoder writes equal the keys the decoder reads
GL402 metric-registered  — every ALL_CAPS instrument used via
                           .inc/.observe/.set/.time resolves to a
                           REGISTRY.counter/gauge/histogram definition
GL403 wire-schema-lock   — every encode_* payload field set in
                           solver/codec.py, keyed by the wire version
                           constant that governs it, is frozen in
                           tools/graftlint/wire_schema.lock.json; a
                           field-set change without a version bump fails
                           the lint, and `--update-wire-lock` regenerates
                           the lock with the bump enforced (the contract
                           ROADMAP item 5's delta protocol builds on)
"""
from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.engine import (
    REPO_ROOT,
    Finding,
    ParsedFile,
    Rule,
    dotted_name,
    register,
)

WIRE_LOCK_PATH = Path(__file__).resolve().parent.parent / "wire_schema.lock.json"
CODEC_PATH = REPO_ROOT / "karpenter_core_tpu" / "solver" / "codec.py"


def _fn_defs(pf: ParsedFile) -> Dict[str, ast.FunctionDef]:
    return {
        node.name: node
        for node in pf.walk(ast.FunctionDef)
    }


def _encode_keys(fn: ast.FunctionDef) -> Set[str]:
    """String keys the encoder emits: dict-literal keys plus keyword args
    of np.savez* calls (the npz member names)."""
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name.endswith("savez") or name.endswith("savez_compressed"):
                for kw in node.keywords:
                    if kw.arg:
                        keys.add(kw.arg)
    return keys


def _decode_keys(fn: ast.FunctionDef) -> Set[str]:
    """String keys the decoder consumes: constant subscripts and .get()."""
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript):
            s = node.slice
            if isinstance(s, ast.Constant) and isinstance(s.value, str):
                keys.add(s.value)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "get" and node.args:
                a = node.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    keys.add(a.value)
    return keys


def _passthrough_names(fn: ast.FunctionDef) -> Set[str]:
    """Names the decoder returns wholesale (``return h``) — every key of a
    passthrough header counts as consumed downstream."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
            out.add(node.value.id)
    return out


def _header_names(fn: ast.FunctionDef) -> Set[str]:
    """Local names bound from _json_header/json.loads — the decoded dict."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = dotted_name(node.value.func)
            if name.endswith("_json_header") or name in ("json.loads",):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
    return out


@register
class CodecFieldParity(Rule):
    id = "GL401"
    name = "codec-field-parity"
    rationale = (
        "a wire field written by encode_X but never read by decode_X (or"
        " vice versa) ships silently and drops on the floor — the"
        " unavailable_offerings near-miss, machine-checked"
    )
    scope = "project"

    def check_project(self, files: List[ParsedFile]):
        for pf in files:
            if not pf.relpath.endswith("solver/codec.py") and (
                "graftlint_fixtures" not in pf.relpath
                or "codec" not in pf.relpath
            ):
                continue
            yield from self._check_codec(pf)

    def _check_codec(self, pf: ParsedFile):
        defs = _fn_defs(pf)
        pairs = []
        for name, fn in sorted(defs.items()):
            stripped = name.lstrip("_")
            if not stripped.startswith("encode_"):
                continue
            twin = name.replace("encode_", "decode_", 1)
            if twin not in defs:
                yield self.finding(
                    pf, fn,
                    f"{name} has no {twin} twin — a one-sided wire codec",
                )
                continue
            pairs.append((fn, defs[twin]))
        for name, fn in sorted(defs.items()):
            stripped = name.lstrip("_")
            if stripped.startswith("decode_"):
                twin = name.replace("decode_", "encode_", 1)
                if twin not in defs:
                    yield self.finding(
                        pf, fn,
                        f"{name} has no {twin} twin — a one-sided wire codec",
                    )
        for enc, dec in pairs:
            ekeys = _encode_keys(enc)
            dkeys = _decode_keys(dec)
            if not ekeys and not dkeys:
                continue
            passthrough = _passthrough_names(dec) & _header_names(dec)
            missing_in_decode = sorted(ekeys - dkeys) if not passthrough else []
            missing_in_encode = sorted(dkeys - ekeys)
            if missing_in_decode:
                yield self.finding(
                    pf, dec,
                    f"{dec.name} never reads wire field(s)"
                    f" {missing_in_decode} that {enc.name} writes —"
                    " the field drops on the floor",
                )
            if missing_in_encode:
                yield self.finding(
                    pf, enc,
                    f"{enc.name} never writes wire field(s)"
                    f" {missing_in_encode} that {dec.name} reads —"
                    " decode sees an absent key",
                )


_EMIT_METHODS = {"inc", "observe", "set", "time"}
_DEF_FACTORIES = {"counter", "gauge", "histogram"}


def collect_defined_instruments(
    files: List[ParsedFile],
) -> Dict[str, List[str]]:
    """instrument variable name -> EVERY metric string bound to it, from
    ``NAME = REGISTRY.counter|gauge|histogram("metric", ...)`` bindings.
    All definitions are kept (no last-wins overwrite) so the metrics audit
    can see a metric string registered twice under a shared variable name.
    Known limitation: resolution is by bare variable name across the whole
    scanned set, not per-module import graph."""
    defined: Dict[str, List[str]] = {}
    for pf in files:
        for node in pf.walk(ast.Assign):
            if not isinstance(node.value, ast.Call):
                continue
            func = node.value.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr in _DEF_FACTORIES
                and dotted_name(func.value).endswith("REGISTRY")
            ):
                continue
            metric = ""
            if node.value.args and isinstance(node.value.args[0], ast.Constant):
                metric = str(node.value.args[0].value)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    defined.setdefault(tgt.id, []).append(metric)
    return defined


def collect_used_instruments(
    files: List[ParsedFile],
) -> Dict[str, List[Finding]]:
    """instrument variable name -> usage sites (as GL402 findings)."""
    used: Dict[str, List[Finding]] = {}
    for pf in files:
        if pf.relpath.endswith("metrics/registry.py"):
            continue  # the instrument classes themselves
        for node in pf.walk(ast.Call):
            func = node.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr in _EMIT_METHODS
            ):
                continue
            base = func.value
            name: Optional[str] = None
            if isinstance(base, ast.Attribute) and base.attr.isupper():
                name = base.attr
            elif isinstance(base, ast.Name) and base.id.isupper():
                name = base.id
            if name is None:
                continue
            used.setdefault(name, []).append(Finding(
                "GL402", pf.relpath, node.lineno,
                f"instrument {name} emitted via .{func.attr}() but never"
                " registered with REGISTRY.counter/gauge/histogram —"
                " a phantom dashboard series",
            ))
    return used


_PKG_DEFS: Optional[Dict[str, List[str]]] = None


def _package_definitions() -> Dict[str, List[str]]:
    """Tree-wide instrument definitions, parsed once per process — the
    GL402 fallback for partial-path runs that don't scan wiring.py."""
    global _PKG_DEFS
    if _PKG_DEFS is None:
        from tools.graftlint.engine import REPO_ROOT, _collect_files

        pkg = REPO_ROOT / "karpenter_core_tpu"
        _PKG_DEFS = (
            collect_defined_instruments(_collect_files([str(pkg)]))
            if pkg.is_dir()
            else {}
        )
    return _PKG_DEFS


@register
class MetricRegistered(Rule):
    id = "GL402"
    name = "metric-registered"
    rationale = (
        "an instrument incremented in source but absent from the registry"
        " renders a dashboard series that never exists"
    )
    scope = "project"

    def check_project(self, files: List[ParsedFile]):
        defined = collect_defined_instruments(files)
        # partial-path runs (`python -m tools.graftlint karpenter_core_tpu/
        # solver`) must still see definitions living outside the scanned
        # subtree (metrics/wiring.py), or every emission site there reads
        # as a phantom series
        if not any(
            f.relpath.endswith("metrics/wiring.py") for f in files
        ):
            for name, metrics in _package_definitions().items():
                defined.setdefault(name, []).extend(metrics)
        used = collect_used_instruments(files)
        for name in sorted(used):
            if name in defined:
                continue
            yield from used[name]


# ---------------------------------------------------------------------------
# GL403: the wire-schema lock.
#
# GL401 pins encode<->decode symmetry *within one revision*; nothing pins
# the field set *across revisions*. A PR that adds a wire field and its
# decode twin sails through GL401, ships, and a mixed deployment (old
# sidecar, new client) silently drops the field — exactly the
# unavailable_offerings near-miss, one axis over. The lock freezes every
# encoder's statically-extracted field set keyed by the wire version
# constant that governs it; changing the set without bumping the version
# fails the lint, and the committed lockfile makes the bump reviewable.
# ---------------------------------------------------------------------------


def _const_str_args(call: ast.Call) -> Dict[int, str]:
    return {
        i: a.value
        for i, a in enumerate(call.args)
        if isinstance(a, ast.Constant) and isinstance(a.value, str)
    }


def _fstring_template(node: ast.JoinedStr) -> Optional[List[Tuple[str, str]]]:
    """f-string as [(kind, text)] parts, kind 'const' | 'param'; None when
    a formatted value is not a plain name (unresolvable statically)."""
    parts: List[Tuple[str, str]] = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(("const", v.value))
        elif isinstance(v, ast.FormattedValue) and isinstance(
            v.value, ast.Name
        ):
            parts.append(("param", v.value.id))
        else:
            return None
    return parts


def extract_wire_schema(pf: ParsedFile) -> dict:
    """Statically extract the wire schema of a codec module.

    Returns ``{"versions": {const_name: int}, "encoders": {fn_name:
    {"versioned_by": [const_name...], "fields": [key...]}}}``.

    Field keys per function: constant dict-literal keys, ``np.savez*``
    keyword names, and constant subscript-store keys. Helpers that write
    f-string keys parameterized on an argument (``out[f"{prefix}_mask"]``,
    the _masks_to_arrays shape) contribute their *instantiated* keys to
    each call site that binds the parameter to a string constant — the
    one-level interprocedural expansion the snapshot codec needs.

    Version attribution: an encoder writing ``"version": SOME_CONST``
    is governed by that constant; private helpers inherit the union of
    their (transitive) callers' constants through the codec-internal call
    graph; anything still unattributed is governed by every version
    constant (any bump permits its change).
    """
    defs: Dict[str, ast.FunctionDef] = {
        n.name: n
        for n in pf.tree.body
        if isinstance(n, ast.FunctionDef)
    }
    versions: Dict[str, int] = {}
    for node in pf.tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Name)
                    and tgt.id.endswith("_WIRE_VERSION")
                    and isinstance(node.value.value, int)
                ):
                    versions[tgt.id] = node.value.value

    fields: Dict[str, Set[str]] = {n: set() for n in defs}
    templates: Dict[str, List[List[Tuple[str, str]]]] = {n: [] for n in defs}
    version_keys: Dict[str, Set[str]] = {n: set() for n in defs}
    calls: Dict[str, List[ast.Call]] = {n: [] for n in defs}

    for name, fn in defs.items():
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        for node in ast.walk(fn):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        fields[name].add(k.value)
                        if (
                            k.value == "version"
                            and isinstance(v, ast.Name)
                            and v.id in versions
                        ):
                            version_keys[name].add(v.id)
            elif isinstance(node, ast.Call):
                cname = dotted_name(node.func)
                if cname.endswith("savez") or cname.endswith("savez_compressed"):
                    for kw in node.keywords:
                        if kw.arg:
                            fields[name].add(kw.arg)
                tail = cname.rsplit(".", 1)[-1] if cname else ""
                if tail in defs and tail != name:
                    calls[name].append(node)
            elif isinstance(node, ast.Assign) and isinstance(
                node.targets[0], ast.Subscript
            ):
                s = node.targets[0].slice
                if isinstance(s, ast.Constant) and isinstance(s.value, str):
                    fields[name].add(s.value)
                elif isinstance(s, ast.JoinedStr):
                    tpl = _fstring_template(s)
                    if tpl is not None and all(
                        kind != "param" or text in params for kind, text in tpl
                    ):
                        templates[name].append(tpl)

    # one-level template expansion at call sites binding constants
    for caller, sites in calls.items():
        for call in sites:
            callee = dotted_name(call.func).rsplit(".", 1)[-1]
            tpls = templates.get(callee)
            if not tpls:
                continue
            callee_params = [
                a.arg
                for a in defs[callee].args.posonlyargs + defs[callee].args.args
            ]
            bindings = {
                callee_params[i]: v
                for i, v in _const_str_args(call).items()
                if i < len(callee_params)
            }
            for kw in call.keywords:
                if (
                    kw.arg
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)
                ):
                    bindings[kw.arg] = kw.value.value
            for tpl in tpls:
                if all(kind == "const" or text in bindings for kind, text in tpl):
                    fields[caller].add(
                        "".join(
                            text if kind == "const" else bindings[text]
                            for kind, text in tpl
                        )
                    )

    # propagate version constants caller -> callee to a fixpoint
    changed = True
    while changed:
        changed = False
        for caller, sites in calls.items():
            for call in sites:
                callee = dotted_name(call.func).rsplit(".", 1)[-1]
                before = len(version_keys[callee])
                version_keys[callee] |= version_keys[caller]
                if len(version_keys[callee]) > before:
                    changed = True

    encoders = {}
    for name in sorted(defs):
        if not name.lstrip("_").startswith("encode_") or not fields[name]:
            continue
        governed = sorted(version_keys[name]) or sorted(versions)
        encoders[name] = {
            "versioned_by": governed,
            "fields": sorted(fields[name]),
        }
    return {"versions": versions, "encoders": encoders}


def _load_lock(path: Path) -> Optional[dict]:
    if not path.exists():
        return None
    try:
        return json.loads(path.read_text())
    except (ValueError, OSError):
        return None


@register
class WireSchemaLock(Rule):
    id = "GL403"
    name = "wire-schema-lock"
    rationale = (
        "a wire field-set change without a version bump ships a silent"
        " mixed-deployment incompatibility (the field drops on the floor"
        " between revisions) — the committed lock makes every schema"
        " change an explicit, reviewed version bump"
    )
    scope = "project"

    def check_project(self, files: List[ParsedFile]):
        for pf in files:
            lock_path = self._lock_for(pf)
            if lock_path is None:
                continue
            yield from self._check(pf, lock_path)

    def _lock_for(self, pf: ParsedFile) -> Optional[Path]:
        if pf.relpath.endswith("solver/codec.py"):
            return WIRE_LOCK_PATH
        if "graftlint_fixtures" in pf.relpath and "gl403" in pf.path.name:
            # fixtures carry a sidecar lock: <fixture stem>.lock.json
            return pf.path.with_name(pf.path.stem + ".lock.json")
        return None

    def _check(self, pf: ParsedFile, lock_path: Path):
        schema = extract_wire_schema(pf)
        lock = _load_lock(lock_path)
        if lock is None:
            yield self.finding(
                pf, pf.tree,
                f"no wire-schema lock at {lock_path.name} — run"
                " `python -m tools.graftlint --update-wire-lock` to freeze"
                " the current field sets",
            )
            return
        locked_versions = dict(lock.get("versions", {}))
        locked_encoders = dict(lock.get("encoders", {}))
        defs = {
            n.name: n for n in pf.tree.body if isinstance(n, ast.FunctionDef)
        }

        def bumped(governed: List[str]) -> bool:
            return any(
                schema["versions"].get(k) != locked_versions.get(k)
                for k in governed
            )

        stale_lock = False
        for name, cur in schema["encoders"].items():
            node = defs.get(name, pf.tree)
            ent = locked_encoders.get(name)
            if ent is None:
                yield self.finding(
                    pf, node,
                    f"{name} is not in the wire-schema lock — new wire"
                    " payloads need a version bump and"
                    " `--update-wire-lock`",
                )
                continue
            if cur["fields"] != ent.get("fields"):
                if bumped(cur["versioned_by"]):
                    stale_lock = True  # bumped but lock not regenerated
                else:
                    added = sorted(set(cur["fields"]) - set(ent.get("fields", [])))
                    removed = sorted(set(ent.get("fields", [])) - set(cur["fields"]))
                    gov = "/".join(cur["versioned_by"])
                    yield self.finding(
                        pf, node,
                        f"{name} wire field set changed without a {gov}"
                        f" bump (added {added}, removed {removed}) — an"
                        " old peer on the same version number silently"
                        " drops the difference; bump the version, then"
                        " `--update-wire-lock`",
                    )
        for name in sorted(set(locked_encoders) - set(schema["encoders"])):
            yield self.finding(
                pf, pf.tree,
                f"locked encoder {name} no longer exists in the codec —"
                " removing a wire payload is a schema change: bump and"
                " `--update-wire-lock`",
            )
        for k in sorted(set(schema["versions"]) | set(locked_versions)):
            if schema["versions"].get(k) != locked_versions.get(k):
                stale_lock = True
        if stale_lock:
            yield self.finding(
                pf, pf.tree,
                f"{lock_path.name} is stale against the codec (version"
                " constants or bumped field sets differ) — run"
                " `python -m tools.graftlint --update-wire-lock`",
            )


def update_wire_lock(
    codec_path: Optional[Path] = None, lock_path: Optional[Path] = None
) -> int:
    """Regenerate the wire-schema lock from the codec source, with the
    bump enforced: an encoder whose field set differs from the existing
    lock while every version constant governing it is unchanged aborts
    the regeneration — the lock must never absorb an unversioned schema
    change. Returns the number of locked encoders."""
    codec_path = codec_path or CODEC_PATH
    lock_path = lock_path or WIRE_LOCK_PATH
    source = codec_path.read_text()
    pf = ParsedFile(codec_path, codec_path.name, source)
    schema = extract_wire_schema(pf)
    old = _load_lock(lock_path)
    if old is not None:
        old_versions = dict(old.get("versions", {}))
        old_encoders = dict(old.get("encoders", {}))

        def bumped(governed: List[str]) -> bool:
            return any(
                schema["versions"].get(k) != old_versions.get(k)
                for k in governed
            )

        offenders = []
        for name, cur in schema["encoders"].items():
            ent = old_encoders.get(name)
            gov = "/".join(cur["versioned_by"])
            if ent is None:
                # a NEW payload is a schema change too: an old peer on the
                # same version number cannot decode it
                if not bumped(cur["versioned_by"]):
                    offenders.append(f"{name} (new encoder, governed by {gov})")
            elif cur["fields"] != ent.get("fields") and not bumped(
                cur["versioned_by"]
            ):
                offenders.append(f"{name} (governed by {gov})")
        for name, ent in old_encoders.items():
            if name in schema["encoders"]:
                continue
            governed = ent.get("versioned_by") or sorted(old_versions)
            if not bumped(governed):
                offenders.append(
                    f"{name} (removed encoder, governed by"
                    f" {'/'.join(governed)})"
                )
        if offenders:
            raise SystemExit(
                "graftlint: refusing to update the wire lock — schema"
                " changed without a version bump: "
                + ", ".join(sorted(offenders))
            )
    lock_path.write_text(
        json.dumps(schema, indent=2, sort_keys=True) + "\n"
    )
    return len(schema["encoders"])
