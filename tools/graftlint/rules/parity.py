"""Family 4 — wire and registry parity.

The solverd wire (solver/codec.py) is a pair of hand-written codecs; a
field added on the encode side but not the decode side ships silently and
drops on the floor (the ``unavailable_offerings`` near-miss PR 2 fixed by
hand). Same shape for metrics: an instrument incremented at an emission
site but never registered renders a phantom dashboard series. Both are
exact set-equality properties over the AST — no heuristics.

GL401 codec-field-parity — every encode_X/_encode_X in solver/codec.py
                           has a decode twin, and the dict keys the
                           encoder writes equal the keys the decoder reads
GL402 metric-registered  — every ALL_CAPS instrument used via
                           .inc/.observe/.set/.time resolves to a
                           REGISTRY.counter/gauge/histogram definition
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.graftlint.engine import Finding, ParsedFile, Rule, dotted_name, register


def _fn_defs(pf: ParsedFile) -> Dict[str, ast.FunctionDef]:
    return {
        node.name: node
        for node in pf.walk(ast.FunctionDef)
    }


def _encode_keys(fn: ast.FunctionDef) -> Set[str]:
    """String keys the encoder emits: dict-literal keys plus keyword args
    of np.savez* calls (the npz member names)."""
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name.endswith("savez") or name.endswith("savez_compressed"):
                for kw in node.keywords:
                    if kw.arg:
                        keys.add(kw.arg)
    return keys


def _decode_keys(fn: ast.FunctionDef) -> Set[str]:
    """String keys the decoder consumes: constant subscripts and .get()."""
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript):
            s = node.slice
            if isinstance(s, ast.Constant) and isinstance(s.value, str):
                keys.add(s.value)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "get" and node.args:
                a = node.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    keys.add(a.value)
    return keys


def _passthrough_names(fn: ast.FunctionDef) -> Set[str]:
    """Names the decoder returns wholesale (``return h``) — every key of a
    passthrough header counts as consumed downstream."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
            out.add(node.value.id)
    return out


def _header_names(fn: ast.FunctionDef) -> Set[str]:
    """Local names bound from _json_header/json.loads — the decoded dict."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = dotted_name(node.value.func)
            if name.endswith("_json_header") or name in ("json.loads",):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
    return out


@register
class CodecFieldParity(Rule):
    id = "GL401"
    name = "codec-field-parity"
    rationale = (
        "a wire field written by encode_X but never read by decode_X (or"
        " vice versa) ships silently and drops on the floor — the"
        " unavailable_offerings near-miss, machine-checked"
    )
    scope = "project"

    def check_project(self, files: List[ParsedFile]):
        for pf in files:
            if not pf.relpath.endswith("solver/codec.py") and (
                "graftlint_fixtures" not in pf.relpath
                or "codec" not in pf.relpath
            ):
                continue
            yield from self._check_codec(pf)

    def _check_codec(self, pf: ParsedFile):
        defs = _fn_defs(pf)
        pairs = []
        for name, fn in sorted(defs.items()):
            stripped = name.lstrip("_")
            if not stripped.startswith("encode_"):
                continue
            twin = name.replace("encode_", "decode_", 1)
            if twin not in defs:
                yield self.finding(
                    pf, fn,
                    f"{name} has no {twin} twin — a one-sided wire codec",
                )
                continue
            pairs.append((fn, defs[twin]))
        for name, fn in sorted(defs.items()):
            stripped = name.lstrip("_")
            if stripped.startswith("decode_"):
                twin = name.replace("decode_", "encode_", 1)
                if twin not in defs:
                    yield self.finding(
                        pf, fn,
                        f"{name} has no {twin} twin — a one-sided wire codec",
                    )
        for enc, dec in pairs:
            ekeys = _encode_keys(enc)
            dkeys = _decode_keys(dec)
            if not ekeys and not dkeys:
                continue
            passthrough = _passthrough_names(dec) & _header_names(dec)
            missing_in_decode = sorted(ekeys - dkeys) if not passthrough else []
            missing_in_encode = sorted(dkeys - ekeys)
            if missing_in_decode:
                yield self.finding(
                    pf, dec,
                    f"{dec.name} never reads wire field(s)"
                    f" {missing_in_decode} that {enc.name} writes —"
                    " the field drops on the floor",
                )
            if missing_in_encode:
                yield self.finding(
                    pf, enc,
                    f"{enc.name} never writes wire field(s)"
                    f" {missing_in_encode} that {dec.name} reads —"
                    " decode sees an absent key",
                )


_EMIT_METHODS = {"inc", "observe", "set", "time"}
_DEF_FACTORIES = {"counter", "gauge", "histogram"}


def collect_defined_instruments(
    files: List[ParsedFile],
) -> Dict[str, List[str]]:
    """instrument variable name -> EVERY metric string bound to it, from
    ``NAME = REGISTRY.counter|gauge|histogram("metric", ...)`` bindings.
    All definitions are kept (no last-wins overwrite) so the metrics audit
    can see a metric string registered twice under a shared variable name.
    Known limitation: resolution is by bare variable name across the whole
    scanned set, not per-module import graph."""
    defined: Dict[str, List[str]] = {}
    for pf in files:
        for node in pf.walk(ast.Assign):
            if not isinstance(node.value, ast.Call):
                continue
            func = node.value.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr in _DEF_FACTORIES
                and dotted_name(func.value).endswith("REGISTRY")
            ):
                continue
            metric = ""
            if node.value.args and isinstance(node.value.args[0], ast.Constant):
                metric = str(node.value.args[0].value)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    defined.setdefault(tgt.id, []).append(metric)
    return defined


def collect_used_instruments(
    files: List[ParsedFile],
) -> Dict[str, List[Finding]]:
    """instrument variable name -> usage sites (as GL402 findings)."""
    used: Dict[str, List[Finding]] = {}
    for pf in files:
        if pf.relpath.endswith("metrics/registry.py"):
            continue  # the instrument classes themselves
        for node in pf.walk(ast.Call):
            func = node.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr in _EMIT_METHODS
            ):
                continue
            base = func.value
            name: Optional[str] = None
            if isinstance(base, ast.Attribute) and base.attr.isupper():
                name = base.attr
            elif isinstance(base, ast.Name) and base.id.isupper():
                name = base.id
            if name is None:
                continue
            used.setdefault(name, []).append(Finding(
                "GL402", pf.relpath, node.lineno,
                f"instrument {name} emitted via .{func.attr}() but never"
                " registered with REGISTRY.counter/gauge/histogram —"
                " a phantom dashboard series",
            ))
    return used


_PKG_DEFS: Optional[Dict[str, List[str]]] = None


def _package_definitions() -> Dict[str, List[str]]:
    """Tree-wide instrument definitions, parsed once per process — the
    GL402 fallback for partial-path runs that don't scan wiring.py."""
    global _PKG_DEFS
    if _PKG_DEFS is None:
        from tools.graftlint.engine import REPO_ROOT, _collect_files

        pkg = REPO_ROOT / "karpenter_core_tpu"
        _PKG_DEFS = (
            collect_defined_instruments(_collect_files([str(pkg)]))
            if pkg.is_dir()
            else {}
        )
    return _PKG_DEFS


@register
class MetricRegistered(Rule):
    id = "GL402"
    name = "metric-registered"
    rationale = (
        "an instrument incremented in source but absent from the registry"
        " renders a dashboard series that never exists"
    )
    scope = "project"

    def check_project(self, files: List[ParsedFile]):
        defined = collect_defined_instruments(files)
        # partial-path runs (`python -m tools.graftlint karpenter_core_tpu/
        # solver`) must still see definitions living outside the scanned
        # subtree (metrics/wiring.py), or every emission site there reads
        # as a phantom series
        if not any(
            f.relpath.endswith("metrics/wiring.py") for f in files
        ):
            for name, metrics in _package_definitions().items():
                defined.setdefault(name, []).extend(metrics)
        used = collect_used_instruments(files)
        for name in sorted(used):
            if name in defined:
                continue
            yield from used[name]
