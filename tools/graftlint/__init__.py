"""graftlint: project-native static analysis for karpenter-core-tpu.

Run: ``python -m tools.graftlint [--baseline] [--timing] [paths...]``

Public API: ``run``, ``Rule``, ``register``, ``Finding``, ``RULES``
(tools/graftlint/engine.py documents the rule-author contract).
"""
from tools.graftlint.engine import (  # noqa: F401
    Finding,
    ParsedFile,
    Rule,
    RULES,
    register,
    run,
)
