"""Which cfg3 pod kind drives the device-vs-greedy node delta?
Runs sub-mixes of the cfg3 kinds and reports node counts for both solvers.
JAX_PLATFORMS=cpu python tools/diag_cfg3_kinds.py
"""
from __future__ import annotations

import copy
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from karpenter_core_tpu.cloudprovider.kwok import bench_catalog  # noqa: E402

KIND_NAMES = ["generic", "zonal-aff", "selector", "spread-z", "spread-h", "anti-h"]


def run(kinds, n=5000):
    pods = [
        p
        for p in bench._topology_pods(n)
        if int(p.metadata.name[1:]) % 6 in kinds
    ]
    pools = [bench._pool()]
    catalog = bench_catalog(400)
    its = {p.name: list(catalog) for p in pools}

    from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
        Scheduler,
    )
    from karpenter_core_tpu.models.provisioner import DeviceScheduler

    g = Scheduler(copy.deepcopy(pools), its)
    gres = g.solve(copy.deepcopy(pods))
    assert gres.all_pods_scheduled(), list(gres.pod_errors.items())[:3]

    d = DeviceScheduler(pools, its, max_slots=2048)
    dres = d.solve(pods)
    assert dres.all_pods_scheduled(), list(dres.pod_errors.items())[:3]

    lbl = "+".join(KIND_NAMES[k] for k in kinds)
    print(
        f"{lbl:45s} pods={len(pods):5d} greedy={gres.node_count():4d} "
        f"device={dres.node_count():4d} delta={dres.node_count() - gres.node_count():+d}",
        flush=True,
    )


if __name__ == "__main__":
    for kinds in (
        (0,),
        (3,),
        (4,),
        (5,),
        (0, 1, 2),
        (3, 4),
        (4, 5),
        (3, 4, 5),
        (0, 3),
        (0, 4),
        (0, 5),
        (0, 1, 2, 3, 4, 5),
    ):
        run(kinds)
