"""Trace WHICH class/pod opens each fresh node in device vs greedy on cfg3.
JAX_PLATFORMS=cpu python tools/diag_cfg3_trace.py [n]
"""
from __future__ import annotations

import collections
import copy
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import bench  # noqa: E402
from karpenter_core_tpu.cloudprovider.kwok import bench_catalog  # noqa: E402

KIND_NAMES = ["generic", "zonal-aff", "selector", "spread-z", "spread-h", "anti-h"]


def kind_of(name):
    return int(name[1:]) % 6


def device_trace(pods, pools, catalog):
    from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
        Topology,
    )
    from karpenter_core_tpu.models.provisioner import DeviceScheduler
    from karpenter_core_tpu.ops import topoplan
    from karpenter_core_tpu.ops.ffd import ffd_solve
    import jax

    its = {p.name: list(catalog) for p in pools}
    d = DeviceScheduler(pools, its, max_slots=2048)
    d._round_remaining = {}
    topo = Topology(domains={k: set(v) for k, v in d.domains_universe.items()})
    topo.ensure_inverse_initialized()
    for p in pods:
        if p.topology_spread_constraints or p.affinity is not None:
            topo.update(p)
    classes = d._sorted_classes(pods, topo)
    plan = topoplan.plan_topology(classes, topo)
    d._final_filter_cache = {}
    prep = d._prepare_with_vocab(plan, 2048, topo)
    state, takes, unplaced = ffd_solve(
        prep.init_state, d._class_steps(prep), prep.statics,
        level_iters=prep.level_iters,
    )
    takes = np.asarray(jax.device_get(takes))
    kindarr = np.asarray(jax.device_get(state.kind))
    J = len(plan.steps)
    takes = takes[:J]
    print(f"device: {J} class steps, unplaced total "
          f"{int(np.asarray(jax.device_get(unplaced))[:J].sum())}")

    # first class to take on each NEW slot = the opener
    new_slots = np.where(kindarr == 2)[0]
    openers = collections.Counter()
    per_class_opened = collections.Counter()
    for n in new_slots:
        col = takes[:, n]
        jj = np.where(col > 0)[0]
        if len(jj) == 0:
            continue
        j0 = int(jj[0])
        ci = plan.steps[j0].class_idx
        k = kind_of(plan.device_classes[ci].pods[0].metadata.name)
        openers[KIND_NAMES[k]] += 1
        per_class_opened[ci] += 1
    print("device fresh nodes opened, by opener kind:", dict(openers))
    multi = {j: c for j, c in per_class_opened.items() if c > 1}
    print(f"device classes opening >1 node: {len(multi)} "
          f"(total extra {sum(c - 1 for c in multi.values())})")
    # biggest multi-openers
    for j, c in sorted(multi.items(), key=lambda kv: -kv[1])[:10]:
        cl = plan.device_classes[j]
        # j is a class index here
        k = kind_of(cl.pods[0].metadata.name)
        print(f"  step {j}: opened {c} nodes, class kind={KIND_NAMES[k]} "
              f"npods={len(cl.pods)} cpu={cl.requests.get('cpu', 0):.2f} "
              f"mem={cl.requests.get('memory', 0) / 2**30:.2f}")
    return openers


def greedy_trace(pods, pools, catalog):
    from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
        Scheduler,
    )

    its = {p.name: list(catalog) for p in pools}
    s = Scheduler(copy.deepcopy(pools), its)
    openers = collections.Counter()
    orig_add = s._add

    def traced_add(pod):
        before = len(s.new_node_claims)
        err = orig_add(pod)
        if len(s.new_node_claims) > before:
            openers[KIND_NAMES[kind_of(pod.metadata.name)]] += 1
        return err

    s._add = traced_add
    res = s.solve(copy.deepcopy(pods))
    assert res.all_pods_scheduled()
    print(f"greedy: {res.node_count()} nodes")
    print("greedy fresh nodes opened, by opener kind:", dict(openers))
    return openers


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 5000
    pods = bench._topology_pods(n)
    pools = [bench._pool()]
    catalog = bench_catalog(400)
    g = greedy_trace(pods, pools, catalog)
    d = device_trace(pods, pools, catalog)
    print("\nopener-kind delta (device - greedy):")
    for k in KIND_NAMES:
        if d.get(k, 0) or g.get(k, 0):
            print(f"  {k:10s} {d.get(k, 0) - g.get(k, 0):+d} "
                  f"(device {d.get(k, 0)}, greedy {g.get(k, 0)})")


if __name__ == "__main__":
    main()
