"""Diagnose the cfg3 topology parity gap: device vs greedy node contents.

Runs the bench's cfg3 workload (deterministic), solves with both solvers,
then buckets the resulting nodes by (instance type, pod-kind histogram) and
prints the diff so the extra device nodes are attributable to a pod family.
"""
from __future__ import annotations

import copy
import os
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

N = int(sys.argv[1]) if len(sys.argv) > 1 else 5000


def kind_of(pod_name: str) -> str:
    i = int(pod_name[1:])
    return ["generic", "zonal", "selector", "spread-z", "spread-h", "anti-h"][i % 6]


def describe(res):
    nodes = []
    for claim in res.new_node_claims:
        opts = claim.instance_type_options
        it = opts[0].name if opts else "?"
        kinds = Counter(kind_of(p.name) for p in claim.pods)
        cpu = sum(p.resource_requests.get("cpu", 0) for p in claim.pods)
        nodes.append((it, tuple(sorted(kinds.items())), len(claim.pods), round(cpu, 1)))
    return nodes


def main():
    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog

    pods = bench._topology_pods(N)
    pools = [bench._pool()]
    catalog = bench_catalog(400)

    from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
        Scheduler,
    )
    from karpenter_core_tpu.models.provisioner import DeviceScheduler

    its = {p.name: list(catalog) for p in pools}
    g = Scheduler(copy.deepcopy(pools), {k: list(v) for k, v in its.items()})
    gres = g.solve(copy.deepcopy(pods))
    assert gres.all_pods_scheduled()

    d = DeviceScheduler(pools, its, max_slots=2048)
    dres = d.solve(pods)
    assert dres.all_pods_scheduled()

    gn = describe(gres)
    dn = describe(dres)
    print(f"greedy nodes: {len(gn)}   device nodes: {len(dn)}  delta {len(dn)-len(gn)}")

    # histogram by instance type
    git = Counter(n[0] for n in gn)
    dit = Counter(n[0] for n in dn)
    print("\nby instance type (device - greedy):")
    for it in sorted(set(git) | set(dit)):
        diff = dit[it] - git[it]
        if diff:
            print(f"  {it:30s} greedy={git[it]:3d} device={dit[it]:3d} diff={diff:+d}")

    # histogram by dominant pod kind on the node
    def dom(n):
        return max(n[1], key=lambda kv: kv[1])[0] if n[1] else "?"

    gk = Counter(dom(n) for n in gn)
    dk = Counter(dom(n) for n in dn)
    print("\nby dominant pod kind (device - greedy):")
    for k in sorted(set(gk) | set(dk)):
        print(f"  {k:10s} greedy={gk[k]:3d} device={dk[k]:3d} diff={dk[k]-gk[k]:+d}")

    # pods-per-node distribution
    print("\npods/node: greedy total pods", sum(n[2] for n in gn),
          "device", sum(n[2] for n in dn))
    gpp = sorted((n[2] for n in gn))
    dpp = sorted((n[2] for n in dn))
    print("greedy pods/node min/p50/max:", gpp[0], gpp[len(gpp)//2], gpp[-1])
    print("device pods/node min/p50/max:", dpp[0], dpp[len(dpp)//2], dpp[-1])

    # cpu utilization per node
    print("\nnodes sorted by pod count (device):")
    for n in sorted(dn, key=lambda x: x[2])[:15]:
        print("  ", n)
    print("\nnodes sorted by pod count (greedy):")
    for n in sorted(gn, key=lambda x: x[2])[:15]:
        print("  ", n)


if __name__ == "__main__":
    main()
