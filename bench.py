"""Round bench: device-solver performance on the kwok catalog.

Primary metric = BASELINE.json north star: Scheduler.Solve() throughput at
**50k pending pods x 800 instance types** (reference harness:
scheduling_benchmark_test.go:75-95 grid, 100 pods/sec CI floor at :53).
Secondary lines (reported in `detail`):

  cfg1_5k400      the reference benchmark grid's largest point (5k x 400)
  cfg2_masked     + nodeSelector / taints+tolerations / pool requirements
  cfg4_consol     MultiNodeConsolidation sweep: 2k-node cluster, the
                  100-candidate cap evaluated as ONE vmapped device call
                  (vs log2(100) full host simulations upstream)
  cfg7_fleet      8 tenants hammering ONE sidecar through the fleet
                  gateway: per-tenant queue-wait p50/p99 solo vs
                  concurrent, shed rate + greedy-fallback parity, cache
                  evictions under a deliberately undersized bound, and
                  aggregate pods/sec across the fleet
  cfg10_batch     continuous cross-tenant batching: 32 tenants of SMALL
                  problems (the many-small-solves traffic shape) through
                  ONE sidecar, serialized (max_batch=1, the cfg7-shaped
                  baseline) vs coalesced (the gateway dispatches
                  compatible queued problems as one vmapped device
                  batch); records aggregate pods/sec both ways, the
                  speedup (target >=2x), mean batch size, batch-axis
                  padding ratio, and per-tenant p99 queue-wait (must be
                  no worse batched). A tiny version runs under
                  BENCH_FAST=1 so tier-1 smokes the batched path
  cfg11_gangs     mixed-priority churn with gangs (ISSUE 10): 20k pods —
                  10% system-critical sized past the largest fresh
                  instance (admit only via preemption on the existing
                  fleet), 15% in 8-pod all-or-nothing gangs, the rest
                  plain — recording preemption count, the
                  evicted-per-admitted-cpu minimality proxy, gang
                  atomicity violations (MUST be 0), and the p50 ratio vs
                  the plain cfg1 shape. A tiny version runs under
                  BENCH_FAST=1 so tier-1 smokes the gangsched path
  cfg12_relax     the relaxsolve backend (ISSUE 13) vs FFD on cfg3- and
                  cfg11-shaped problems over a two-pool catalog where
                  first-template-wins is suboptimal: node-count and
                  $-cost deltas at both modes' p50s (gate: relax strictly
                  fewer nodes AND dollars at equal-or-better p50). A tiny
                  version runs under BENCH_FAST=1 so tier-1 smokes the
                  relax path. `--configs cfgA,cfgB` runs a subset of the
                  secondary configs (the primary always runs)
  cfg13_delta     the delta wire + solver fleet (ISSUE 14): an
                  operator-shaped snapshot (existing nodes + topology
                  context + catalog) re-solved across 1%-churn rounds
                  through BOTH wire forms — full vs segment-manifest —
                  recording bytes shipped per re-solve (gate: delta
                  ships <=10% of full-wire bytes at scale) with
                  node-count and result-wire byte parity; then aggregate
                  pods/sec serving N tenants at 1 vs 2 vs 4 sidecars
                  through the client-side fleet router, affinity on vs
                  off (scheduler-cache hit rate must stay hot under
                  affinity). A tiny version runs under BENCH_FAST=1 so
                  tier-1 smokes the manifest path and the router
  cfg14_twin      the closed-loop digital twin (ISSUE 15): N simulated
                  clusters run the FULL operator loop — provisioning,
                  binding, consolidation, ICE routing — over
                  Tesserae-shaped workload waves on a virtual clock,
                  judged on END-TO-END outcomes per scenario: fleet
                  $-cost over virtual time, time-to-bind SLO percentiles
                  per workload class, preemption budget burn, solver-tier
                  utilization, and the virtual:wall compression ratio.
                  Scenarios: clean (gate: zero invariant violations, zero
                  fallbacks), fault_storm (ICE storm + kube/cloud chaos;
                  gate: zero invariant violations), and — full runs — a
                  fleet scenario through real in-thread solverd members
                  with murder/partition/amnesia faults. A tiny version
                  runs under BENCH_FAST=1 so tier-1 smokes the twin
  cfg15_incremental  the churn-proportional incremental re-solve engine
                  (ISSUE 16): a 600-node snapshot's standing pod set
                  re-solved over 1%-churn rounds (one class drains, one
                  fills per round) with prev_fingerprint chaining vs an
                  always-fresh daemon — p50 both ways, the speedup
                  (gate: incremental >=5x below fresh), per-round
                  node-count delta (gate: within 2% of fresh), the
                  engine outcome mix, and the zero-rejections gates. A
                  tiny version runs under BENCH_FAST=1 so tier-1 smokes
                  the warm-replay path
  cfg16_elastic   the closed-loop elastic solver tier (ISSUE 17): an
                  autoscaled tier (TierAutoscaler over real spawn/drain)
                  vs a max-fixed-size control on an identical
                  surge-then-quiet trace — member-seconds on a virtual
                  tick clock (gate: >=30% below the control), post-ramp
                  per-tenant queue-wait p99 (gate at full scale:
                  equal-or-better), the resize-cost audit (zero miss
                  rounds / fallbacks / open breakers across remaps), and
                  the brownout ladder firing 1->2->3 and clearing
                  3->2->1->0 in order under forced max-scale overload
                  with the verifier counter unmoved. A tiny version runs
                  under BENCH_FAST=1 so tier-1 smokes the elastic path
  cfg17_pallas    the hand-fused Pallas FFD hot core vs the classic XLA
                  lowering (ISSUE 18, --kernel=xla|pallas) on the
                  primary and cfg3-topology shapes: per-backend p50 +
                  phase split, speedup (accelerator gates: pallas
                  primary p50 < 0.3s, topology p50 halved), result-wire
                  byte parity and fetch-window device-byte parity
                  asserted inside the round. CPU runs exercise interpret
                  mode: parity gates judged, latency verdicts null with
                  a speedup_note (the cfg8 precedent). A tiny version
                  runs under BENCH_FAST=1 so tier-1 smokes both backends
  cfg18_topoaware rank/topology-aware gang placement (ISSUE 20): the
                  identical comms-sensitive gang problem solved twice on
                  a racked 2-zone fleet — once with rack/superpod labels
                  visible (topo catalog engaged) and once stripped (the
                  distance-blind control) — then both judged against the
                  TRUE labels. Gates: strictly fewer max intra-gang hops
                  at equal-or-better node count (+$-cost recorded), the
                  hard pod-group-max-hops bound never provably exceeded
                  on an accepted placement, every gang placed; p50_ratio
                  records the topo steering's latency price. A tiny
                  version runs under BENCH_FAST=1 so tier-1 smokes the
                  aware-vs-blind pair
  cfg9_verified   the verification trust anchor's cost: the primary
                  config runs with the ResultVerifier ON (the production
                  default — every config above already pays it), and this
                  summary pins the verify phase against the <5% of solve
                  p50 budget; `--no-verify` is the escape hatch and its
                  use is recorded in the JSON
  cfg8_multidev   the primary config sharded over the local device slice
                  (DeviceScheduler(devices=all), pjit over the slot
                  axis; target >=4x single-device pods/sec on >=8
                  devices). Without a real multi-device slice the
                  throughput half records throughput_skipped and a child
                  process runs the sharded-vs-single parity battery on a
                  forced 8-device virtual CPU mesh instead

  cfg3_topology   the reference's diverse benchmark mix (1/6 each generic,
                  zonal, selector, zone-spread, hostname-spread, hostname
                  anti-affinity; scheduling_benchmark_test.go:233-247) at
                  5k pods, through the device topology kernel. The
                  host-floor-first class ordering (models/provisioner
                  _sorted_classes) packs MATERIALLY DENSER than the greedy
                  oracle here (negative parity_nodes_delta): ~91 vs 121
                  nodes at 5k, ~235 vs 315 at 50k (cfg3_topology_50k),
                  while solving ~10-90x faster

Every config reports `parity_nodes_delta` = device nodes − greedy nodes
on the identical pod set (the north star demands node-count parity, not
just all-scheduled), plus a `phases` breakdown of the final warm solve
(host plan / prepare / device kernel / decode / verify seconds,
device<->host bytes total and per device, adaptive slot usage,
prepared-cache hits, the `solver_mode` that produced the numbers, and —
relax solves — the won/lost/cached verdict block) so regressions
localize to a phase and attribute to a backend without re-profiling. Prints ONE JSON line; vs_baseline is
pods/sec over the reference's enforced 100 pods/sec floor. Runs on
whatever backend JAX selects (real TPU chip under the driver). Env knobs:
BENCH_PODS / BENCH_TYPES (primary config), BENCH_FAST=1 (primary only,
skips parity).
"""
from __future__ import annotations

import json
import os
import sys
import time

N_PODS = int(os.environ.get("BENCH_PODS", "50000"))
N_TYPES = int(os.environ.get("BENCH_TYPES", "800"))
FAST = os.environ.get("BENCH_FAST", "") == "1"
# --no-verify: the escape hatch for isolating verification cost — the
# production default is verification ON, and the flag's use is RECORDED in
# the bench JSON so a suspiciously fast run can't hide that it skipped the
# trust anchor
NO_VERIFY = "--no-verify" in sys.argv
GIB = 2.0**30


def _pool(name="default", taints=None, requirements=None):
    from karpenter_core_tpu.api.nodepool import NodePool, NodePoolSpec
    from karpenter_core_tpu.api.objects import ObjectMeta

    pool = NodePool(metadata=ObjectMeta(name=name))
    pool.spec = NodePoolSpec()
    if taints:
        pool.spec.template.taints = list(taints)
    if requirements:
        pool.spec.template.requirements = list(requirements)
    return pool


def _plain_pods(n, shapes=(16, 12)):
    """Diverse cpu/mem shapes -> many pod equivalence classes (the FFD scan
    length); mirrors the benchmark's diverse pod mix minus topology."""
    from karpenter_core_tpu.api.objects import ObjectMeta, Pod

    a, b = shapes
    return [
        Pod(
            metadata=ObjectMeta(name=f"p{i}"),
            resource_requests={
                "cpu": 0.1 * (1 + i % a),
                "memory": 0.25 * GIB * (1 + (i // a) % b),
            },
        )
        for i in range(n)
    ]


def _masked_pods(n):
    """BASELINE config 2: 1/3 plain, 1/3 nodeSelector+zone-affinity, 1/3
    toleration-gated onto a tainted pool (requirement/taint mask paths)."""
    from karpenter_core_tpu.api import labels as L
    from karpenter_core_tpu.api.objects import (
        Affinity,
        NodeAffinity,
        NodeSelectorRequirement,
        NodeSelectorTerm,
        ObjectMeta,
        Pod,
        Toleration,
    )

    pods = []
    for i in range(n):
        kind = i % 3
        requests = {
            "cpu": 0.1 * (1 + i % 8),
            "memory": 0.25 * GIB * (1 + (i // 8) % 6),
        }
        if kind == 0:
            pods.append(
                Pod(metadata=ObjectMeta(name=f"m{i}"), resource_requests=requests)
            )
        elif kind == 1:
            pods.append(
                Pod(
                    metadata=ObjectMeta(name=f"m{i}"),
                    resource_requests=requests,
                    node_selector={L.LABEL_OS: "linux"},
                    affinity=Affinity(
                        node_affinity=NodeAffinity(
                            required=[
                                NodeSelectorTerm(
                                    match_expressions=(
                                        NodeSelectorRequirement(
                                            L.LABEL_TOPOLOGY_ZONE,
                                            "In",
                                            ("zone-a", "zone-b"),
                                        ),
                                    )
                                )
                            ]
                        )
                    ),
                )
            )
        else:
            pods.append(
                Pod(
                    metadata=ObjectMeta(name=f"m{i}"),
                    resource_requests=requests,
                    node_selector={"pool": "batch"},
                    tolerations=[
                        Toleration(key="batch", operator="Exists", effect="NoSchedule")
                    ],
                )
            )
    return pods


def _topology_pods(n, n_deploys=10):
    """BASELINE cfg3: the reference benchmark's diverse mix
    (scheduling_benchmark_test.go:233-247) — 1/6 each generic, zonal
    node-affinity, nodeSelector, zone spread, hostname spread, hostname
    anti-affinity — in deployment-style cohorts (shared labels/selectors)
    so classes collapse the way real workloads do."""
    from karpenter_core_tpu.api import labels as L
    from karpenter_core_tpu.api.objects import (
        Affinity,
        LabelSelector,
        NodeAffinity,
        NodeSelectorRequirement,
        NodeSelectorTerm,
        ObjectMeta,
        Pod,
        PodAffinity,
        PodAffinityTerm,
        TopologySpreadConstraint,
    )

    def selector(labels):
        return LabelSelector(match_labels=tuple(sorted(labels.items())))

    pods = []
    for i in range(n):
        kind = i % 6
        dep = (i // 6) % n_deploys
        requests = {
            "cpu": 0.1 * (1 + i % 8),
            "memory": 0.25 * GIB * (1 + (i // 8) % 6),
        }
        name = f"t{i}"
        if kind == 0:
            pods.append(Pod(metadata=ObjectMeta(name=name),
                            resource_requests=requests))
        elif kind == 1:
            pods.append(Pod(
                metadata=ObjectMeta(name=name),
                resource_requests=requests,
                affinity=Affinity(node_affinity=NodeAffinity(required=[
                    NodeSelectorTerm(match_expressions=(
                        NodeSelectorRequirement(
                            L.LABEL_TOPOLOGY_ZONE, "In",
                            ("zone-a", "zone-b")),
                    ))
                ])),
            ))
        elif kind == 2:
            pods.append(Pod(
                metadata=ObjectMeta(name=name),
                resource_requests=requests,
                node_selector={L.LABEL_OS: "linux"},
            ))
        elif kind == 3:
            labels = {"app": f"spread-z-{dep}"}
            pods.append(Pod(
                metadata=ObjectMeta(name=name, labels=labels),
                resource_requests=requests,
                topology_spread_constraints=[TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=L.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=selector(labels),
                )],
            ))
        elif kind == 4:
            labels = {"app": f"spread-h-{dep}"}
            pods.append(Pod(
                metadata=ObjectMeta(name=name, labels=labels),
                resource_requests=requests,
                topology_spread_constraints=[TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=L.LABEL_HOSTNAME,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=selector(labels),
                )],
            ))
        else:
            labels = {"app": f"anti-{dep}"}
            pods.append(Pod(
                metadata=ObjectMeta(name=name, labels=labels),
                resource_requests=requests,
                affinity=Affinity(pod_anti_affinity=PodAffinity(required=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME,
                        label_selector=selector(labels),
                    )
                ])),
            ))
    return pods


def _greedy_nodes(pods, nodepools, catalog):
    """One greedy-oracle solve on the identical inputs; returns (nodes, s)."""
    import copy

    from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
        Scheduler,
    )

    its = {p.name: list(catalog) for p in nodepools}
    s = Scheduler(copy.deepcopy(nodepools), its)
    pods = copy.deepcopy(pods)  # outside the timed window
    t0 = time.perf_counter()
    res = s.solve(pods)
    dt = time.perf_counter() - t0
    assert res.all_pods_scheduled(), list(res.pod_errors.items())[:3]
    return res.node_count(), dt


def _spread(times):
    """p50/p99/IQR over warm solves — a single p50 can't distinguish a real
    regression from chip contention (VERDICT r4 weak #2)."""
    ts = sorted(times)
    n = len(ts)

    def q(p):
        return ts[min(int(round(p * (n - 1))), n - 1)]

    return {
        "p50_solve_s": round(q(0.50), 3),
        "p99_solve_s": round(q(0.99), 3),
        "iqr_s": round(q(0.75) - q(0.25), 3),
        "warm_times_s": [round(t, 3) for t in ts],
    }


def _phase_breakdown(sched) -> dict:
    """Per-phase split of the LAST solve (DeviceScheduler.last_phase_stats):
    host plan (topology groups + class sort), host prepare (tensor
    build/cache), device dispatch incl. the result fetch, host decode, and
    the result-verification pass — plus the device<->host bytes actually
    moved, so the next round can see where the remaining time lives
    without re-profiling."""
    st = sched.last_phase_stats or {}
    out = {}
    for k in ("plan_s", "prepare_s", "kernel_s", "decode_s", "verify_s"):
        if k in st:
            out[k] = round(st[k], 4)
    # n_devices + per-device h2d/fetch bytes ride every config so single-
    # vs multi-device runs compare like for like: sharded planes cost each
    # device ~1/n of their bytes, replicated ones the full bytes
    for k in ("fetch_bytes", "h2d_bytes", "rounds", "slots", "used_slots",
              "prep_cache_hits", "prep_cache_misses",
              "n_devices", "h2d_dev_bytes", "fetch_dev_bytes"):
        if k in st:
            out[k] = int(st[k])
    # which solve backend produced these numbers (relaxsolve, ISSUE 13):
    # every config records it so past/future rounds are attributable to
    # a backend, and relax solves carry their won/lost/cached verdict
    out["solver_mode"] = st.get(
        "solver_mode", getattr(sched, "solver_mode", "ffd")
    )
    # ... and which kernel implementation answered its FFD-scan
    # dispatches (ISSUE 18, --kernel=xla|pallas): every config records it
    # so past/future rounds attribute their numbers to a kernel backend
    out["kernel_backend"] = st.get(
        "kernel_backend", getattr(sched, "kernel_backend", "xla")
    )
    if "relax" in st:
        out["relax"] = dict(st["relax"])
    return out


def _solve_bench(pods, nodepools, catalog, max_slots=1024, repeats=5,
                 parity=True, devices=1, verify=None, kernel="xla"):
    from karpenter_core_tpu.models.provisioner import DeviceScheduler

    # verify defaults to the RUN-WIDE flag: --no-verify must govern every
    # config, or the recorded "verification": false would lie about which
    # numbers still paid the trust anchor
    if verify is None:
        verify = not NO_VERIFY
    its = {p.name: list(catalog) for p in nodepools}
    sched = DeviceScheduler(
        nodepools, its, max_slots=max_slots, devices=devices, verify=verify,
        kernel_backend=kernel,
    )

    t0 = time.perf_counter()
    res = sched.solve(pods)
    cold = time.perf_counter() - t0
    assert res.all_pods_scheduled(), list(res.pod_errors.items())[:3]

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = sched.solve(pods)
        times.append(time.perf_counter() - t0)
    out = _spread(times)
    p50_raw = sorted(times)[len(times) // 2]  # unrounded for the ratio
    out.update({
        "cold_solve_s": round(cold, 3),
        "pods_per_sec": round(len(pods) / p50_raw, 1),
        "nodes": res.node_count(),
        # phase split of the final warm solve (steady-state: prepared-state
        # caches hot, adaptive slot axis settled)
        "phases": _phase_breakdown(sched),
    })
    if parity:
        greedy_nodes, greedy_s = _greedy_nodes(pods, nodepools, catalog)
        out["greedy_nodes"] = greedy_nodes
        out["greedy_solve_s"] = round(greedy_s, 1)
        out["parity_nodes_delta"] = res.node_count() - greedy_nodes
    return out


def _verified_summary(primary: dict, cfg1: dict) -> dict:
    """cfg9_verified: the verification trust anchor's cost, pinned.

    Verification is ON in the primary config (the production default), so
    its per-solve cost already rides every measurement above as the
    ``verify_s`` phase; this summary judges it against the <5% budget —
    relative to cfg1's solve p50 (the acceptance reference) and to the
    primary's own p50 — and records whether the --no-verify escape hatch
    was pulled for this run."""
    verify_s = (primary.get("phases") or {}).get("verify_s")
    out = {
        "verification_on": not NO_VERIFY,
        "verify_s": verify_s,
        "pods": N_PODS,
    }
    if verify_s is None:
        out["skipped"] = "--no-verify: no verification phase measured"
        return out
    p50 = primary["p50_solve_s"]
    out["pct_of_primary_p50"] = round(100.0 * verify_s / p50, 2) if p50 else None
    if cfg1:
        ref = cfg1["p50_solve_s"]
        # the verify phase scales with pod count; cfg1's own verify cost
        # is the like-for-like comparison at the 5k point
        cfg1_verify = (cfg1.get("phases") or {}).get("verify_s")
        out["cfg1_p50_s"] = ref
        out["cfg1_verify_s"] = cfg1_verify
        if cfg1_verify is not None and ref:
            out["cfg1_pct_of_p50"] = round(100.0 * cfg1_verify / ref, 2)
            out["budget_ok"] = cfg1_verify <= 0.05 * ref
    return out


def _ice_storm_bench(n_pods=5000, n_types=400, fractions=(0.0, 0.25, 0.5),
                     repeats=3):
    """Solve latency under an ICE storm: a growing fraction of the
    catalog's offerings — CHEAPEST first, exactly the rows the packer
    wants — marked unavailable through the same snapshot the provisioner
    passes (the UnavailableOfferings cache populated by lifecycle on
    InsufficientCapacityError). Measures the stockout-masking overhead
    (apply_unavailable catalog projection + the off_avail tensor mask) and
    the repack cost of routing around dead capacity."""
    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.cloudprovider.types import OfferingKey
    from karpenter_core_tpu.models.provisioner import DeviceScheduler

    catalog = bench_catalog(n_types)
    pools = [_pool()]
    by_price = sorted(
        (off.price, OfferingKey(it.name, off.zone, off.capacity_type))
        for it in catalog
        for off in it.offerings
    )
    out = {}
    for frac in fractions:
        k = int(len(by_price) * frac)
        unavail = frozenset(key for _, key in by_price[:k])
        sched = DeviceScheduler(
            pools,
            {p.name: list(catalog) for p in pools},
            max_slots=1024,
            unavailable_offerings=unavail,
        )
        pods = _plain_pods(n_pods)
        sched.solve(pods)  # warm the jit cache at this masking shape
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            res = sched.solve(pods)
            times.append(time.perf_counter() - t0)
        entry = _spread(times)
        entry["unavailable_offerings"] = k
        entry["nodes"] = res.node_count()
        entry["all_scheduled"] = res.all_pods_scheduled()
        out[f"storm_{int(frac * 100)}pct"] = entry
    return out


def _shape_churn_bench(n=20000, types=800, rounds=6):
    """Every solve mutates the pod mix — different pod counts AND a
    different shape grid, so class counts drift round to round. Bucketed
    device shapes (models/provisioner._bucket) must keep hitting the jit
    cache: p50 over the churn rounds should sit near the static-shape p50
    rather than paying a multi-second recompile per round."""
    from karpenter_core_tpu.models.provisioner import DeviceScheduler
    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog

    catalog = bench_catalog(types)
    sched = DeviceScheduler(
        [_pool()], {"default": list(catalog)}, max_slots=1024
    )
    times = []
    for r in range(rounds):
        pods = _plain_pods(n + 53 * r, shapes=(14 + r % 3, 11 + r % 2))
        t0 = time.perf_counter()
        res = sched.solve(pods)
        times.append(time.perf_counter() - t0)
        assert res.all_pods_scheduled(), list(res.pod_errors.items())[:3]
    churn = sorted(times[1:])[len(times[1:]) // 2]
    return {
        "p50_churn_s": round(churn, 3),
        "cold_s": round(times[0], 3),
        "rounds": rounds,
        "round_times_s": [round(t, 3) for t in times],
    }


def _consolidation_bench(n_nodes=2000, n_candidates=100, repeats=3):
    """BASELINE config 4: the multi-node consolidation frontier over a
    2k-node cluster — all `n_candidates` prefixes in one vmapped call
    (models/consolidation.py) instead of the reference's binary search of
    full scheduling simulations (multinodeconsolidation.go:110-162)."""
    import numpy as np
    import jax.numpy as jnp

    from karpenter_core_tpu.api import labels as L
    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
        SimNode,
    )
    from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
        Topology,
    )
    from karpenter_core_tpu.models.consolidation import (
        _it_price_vector,
        _prefix_scan,
        prefix_batches,
    )
    from karpenter_core_tpu.models.provisioner import DeviceScheduler

    catalog = bench_catalog(400)
    nodes = [
        SimNode(
            name=f"n{i}",
            labels={
                L.LABEL_ARCH: "amd64",
                L.LABEL_OS: "linux",
                L.LABEL_TOPOLOGY_ZONE: f"zone-{'abcd'[i % 4]}",
                L.NODEPOOL_LABEL_KEY: "default",
                L.LABEL_INSTANCE_TYPE: "s-8x-amd64-linux",
            },
            taints=[],
            # candidates (the first n_candidates) are under-utilized
            available={"cpu": 7.0 if i < n_candidates else 1.0,
                       "memory": 14 * GIB if i < n_candidates else 2 * GIB,
                       "pods": 200.0},
            capacity={"cpu": 8.0, "memory": 16 * GIB, "pods": 210.0},
        )
        for i in range(n_nodes)
    ]
    # each candidate carries 2 small reschedulable pods
    resched = _plain_pods(2 * n_candidates, shapes=(4, 3))

    sched = DeviceScheduler(
        [_pool()], {"default": catalog}, existing_nodes=nodes,
        max_slots=2560,
    )
    sched.existing_nodes = nodes  # candidate-first order
    prep = sched._prepare(resched, 2560, Topology())
    classes = sched._class_steps(prep)

    kind_batch, count_batch = prefix_batches(
        prep,
        base_pods=[],
        candidate_pods=[resched[2 * i : 2 * i + 2] for i in range(n_candidates)],
    )
    Jp = int(classes.count.shape[0])
    if count_batch.shape[1] < Jp:  # steps pad to a bucketed count
        count_batch = np.pad(
            count_batch, ((0, 0), (0, Jp - count_batch.shape[1]))
        )

    args = (
        prep.init_state,
        classes,
        prep.statics,
        jnp.asarray(kind_batch),
        jnp.asarray(count_batch),
        jnp.asarray(_it_price_vector(prep)),
        jnp.int32(len(sched.existing_nodes)),
    )
    import jax

    t0 = time.perf_counter()
    out = _prefix_scan(*args)
    jax.block_until_ready(out)
    cold = time.perf_counter() - t0

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = _prefix_scan(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    p50 = sorted(times)[len(times) // 2]
    unplaced = np.asarray(out[1])
    return {
        "p50_sweep_s": round(p50, 3),
        "cold_sweep_s": round(cold, 3),
        "prefixes": n_candidates,
        "cluster_nodes": n_nodes,
        "schedulable_prefixes": int((unplaced == 0).sum()),
    }


def _sidecar_bench(n_pods=5000, n_types=400, repeats=5):
    """solverd RPC overhead: the same solve through the in-proc
    DeviceScheduler and through a sidecar (in-thread server — the codec,
    HTTP framing, and result rematerialization are the costs under test;
    process hop adds scheduler noise, not work). Reported per phase from
    the client's RPC histograms so encode/transit/kernel/decode drift is
    visible across rounds."""
    from karpenter_core_tpu.metrics import wiring as m
    from karpenter_core_tpu.models.provisioner import DeviceScheduler
    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.solver import remote, service

    pods = _plain_pods(n_pods)
    catalog = bench_catalog(n_types)
    pools = [_pool()]
    its = {"default": list(catalog)}

    sched = DeviceScheduler(pools, dict(its), max_slots=1024)
    inproc_times = []
    sched.solve(pods)  # shared warm-up (jit cache is process-global)
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = sched.solve(pods)
        inproc_times.append(time.perf_counter() - t0)
    assert res.all_pods_scheduled()
    inproc_nodes = res.node_count()

    srv = service.serve(0)
    try:
        client = remote.SolverClient(
            f"127.0.0.1:{srv.server_address[1]}", timeout=600
        )
        rs = remote.RemoteScheduler(
            client, pools, dict(its),
            device_scheduler_opts={"max_slots": 1024},
            verify=not NO_VERIFY,
        )
        rpc_times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            res = rs.solve(pods)
            rpc_times.append(time.perf_counter() - t0)
        assert res.all_pods_scheduled()
        # mode parity: the sidecar is the SAME solver behind a wire — any
        # node-count delta vs in-proc means the codec/rebind leaked
        assert res.node_count() == inproc_nodes, (
            res.node_count(), inproc_nodes,
        )
    finally:
        srv.shutdown()
        srv.server_close()

    p50_in = sorted(inproc_times)[len(inproc_times) // 2]
    p50_rpc = sorted(rpc_times)[len(rpc_times) // 2]
    phases = {}
    h = m.SOLVER_RPC_PHASE_DURATION
    for phase in ("encode", "transit", "kernel", "decode"):
        k = (("phase", phase),)
        total, n = h.sums.get(k, 0.0), h.totals.get(k, 0)
        phases[f"mean_{phase}_s"] = round(total / n, 3) if n else None
    return {
        "pods": n_pods,
        "p50_inproc_s": round(p50_in, 3),
        "p50_sidecar_s": round(p50_rpc, 3),
        "rpc_overhead_s": round(p50_rpc - p50_in, 3),
        "nodes": inproc_nodes,
        "mode_parity_nodes_delta": 0,  # asserted equal above
        **phases,
    }


def _fleet_bench(n_tenants=8, n_pods=1000, n_types=200, repeats=3):
    """cfg7_fleet: N synthetic tenants hammering ONE sidecar through the
    fleet gateway (solver/fleet.py). Every tenant owns a distinct problem
    fingerprint (tenant-named pool; identical catalog shapes so the jit
    cache is shared and only ONE compile cliff is paid) and the scheduler
    cache is deliberately smaller than the tenant count, so the
    heterogeneous mix churns it — the eviction counter must move.

    Phases: (1) solo — each tenant alone, for its baseline queue-wait and
    e2e percentiles; (2) concurrent — all tenants hammer at once through
    their own RemoteSchedulers with a queue bound low enough that bursts
    shed (the shed requests degrade to the client's greedy path, counted
    as fallbacks); (3) a forced-shed parity probe — one solve against a
    saturated gateway must produce node-for-node the greedy oracle's
    placement.

    ``fairness_ok`` is the no-starvation bound: no tenant's concurrent
    p99 queue wait exceeds 3x its fair-share round latency (n_tenants x
    the observed p50 device time) — a starved tenant blows that by an
    order of magnitude, a fair queue sits under it."""
    import copy
    import threading

    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
        Scheduler,
    )
    from karpenter_core_tpu.metrics import wiring as m
    from karpenter_core_tpu.solver import fleet, remote, service

    catalog = bench_catalog(n_types)
    tenants = [f"tenant{i}" for i in range(n_tenants)]
    problems = {}
    for i, tenant in enumerate(tenants):
        # the pod mix drifts per tenant (pods are fingerprint-exempt, but
        # the distinct pool name makes each tenant its own problem half)
        problems[tenant] = {
            "pools": [_pool(tenant)],
            "its": {tenant: list(catalog)},
            "pods": _plain_pods(n_pods, shapes=(8 + i % 3, 6)),
        }

    gateway = fleet.FleetGateway(max_depth=max(n_tenants - 2, 2))
    cache = fleet.BoundedSchedulerCache(max_entries=max(n_tenants // 2, 2))
    daemon = service.SolverDaemon(gateway=gateway, sched_cache=cache)
    srv = service.serve(0, daemon=daemon)
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"

        def scheduler_for(tenant):
            p = problems[tenant]
            client = remote.SolverClient(addr, timeout=600, tenant=tenant)
            return remote.RemoteScheduler(
                client, p["pools"], p["its"],
                device_scheduler_opts={"max_slots": 1024},
                verify=not NO_VERIFY,
            )

        # -- solo baselines (also the shared compile warm-up) -------------
        solo = {}
        for tenant in tenants:
            rs = scheduler_for(tenant)
            rs.solve(problems[tenant]["pods"])  # warm
            times = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                res = rs.solve(problems[tenant]["pods"])
                times.append(time.perf_counter() - t0)
            assert res.all_pods_scheduled()
            solo[tenant] = {
                "e2e": _spread(times), "nodes": res.node_count(),
            }
        solo_waits = gateway.snapshot(reset=True)["tenants"]

        # -- concurrent hammer --------------------------------------------
        fallbacks_before = m.SOLVER_RPC_FALLBACKS.value(
            {"endpoint": "solve"}
        )
        conc_times = {tenant: [] for tenant in tenants}
        errors = []

        def hammer(tenant):
            try:
                rs = scheduler_for(tenant)
                for _ in range(repeats):
                    t0 = time.perf_counter()
                    res = rs.solve(problems[tenant]["pods"])
                    conc_times[tenant].append(time.perf_counter() - t0)
                    assert res.all_pods_scheduled()
            except Exception as e:  # surfaced after join
                errors.append((tenant, repr(e)))

        threads = [
            threading.Thread(target=hammer, args=(t,), daemon=True)
            for t in tenants
        ]
        wall0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - wall0
        assert not errors, errors
        snap = gateway.snapshot()
        shed_total = sum(snap["sheds"].values())
        fallbacks = m.SOLVER_RPC_FALLBACKS.value(
            {"endpoint": "solve"}
        ) - fallbacks_before

        # -- forced-shed parity probe -------------------------------------
        parked = [
            gateway.submit("parked", fleet.LANE_SOLVE)
            for _ in range(gateway.max_depth - gateway.depth())
        ]
        probe = problems[tenants[0]]
        rs = scheduler_for(tenants[0])
        shed_res = rs.solve(probe["pods"])  # 429 -> client greedy path
        for ticket in parked:
            gateway.abandon(ticket)
        greedy = Scheduler(
            copy.deepcopy(probe["pools"]),
            {tenants[0]: list(catalog)},
        ).solve(copy.deepcopy(probe["pods"]))
        parity_ok = (
            shed_res.all_pods_scheduled()
            and shed_res.node_count() == greedy.node_count()
        )

        fair_bound = 3.0 * n_tenants * snap["device_p50_s"]
        per_tenant = {}
        for tenant in tenants:
            waits = snap["tenants"].get(tenant, {})
            per_tenant[tenant] = {
                "solo_wait_p99_s": solo_waits.get(tenant, {}).get(
                    "wait_p99_s", 0.0
                ),
                "wait_p50_s": waits.get("wait_p50_s", 0.0),
                "wait_p99_s": waits.get("wait_p99_s", 0.0),
                "solo_p50_e2e_s": solo[tenant]["e2e"]["p50_solve_s"],
                "p50_e2e_s": round(
                    sorted(conc_times[tenant])[len(conc_times[tenant]) // 2],
                    3,
                ) if conc_times[tenant] else None,
                "nodes": solo[tenant]["nodes"],
            }
        return {
            "tenants": n_tenants,
            "pods_per_tenant": n_pods,
            "aggregate_pods_per_sec": round(
                sum(len(ts) for ts in conc_times.values()) * n_pods / wall, 1
            ),
            "device_p50_s": snap["device_p50_s"],
            "shed_total": shed_total,
            "sheds_by_reason": snap["sheds"],
            "greedy_fallbacks": fallbacks,
            "cache_evictions": dict(cache.evictions),
            "cache_entries": len(cache),
            "cache_entry_bound": cache.max_entries,
            "shed_parity_ok": parity_ok,
            "fair_bound_s": round(fair_bound, 3),
            "fairness_ok": all(
                pt["wait_p99_s"] <= fair_bound for pt in per_tenant.values()
            ),
            "per_tenant": per_tenant,
        }
    finally:
        srv.shutdown()
        srv.server_close()


def _batch_bench(n_tenants=32, n_pods=120, n_types=60, repeats=3):
    """cfg10_batch: continuous cross-tenant solve batching (ISSUE 9).

    The many-small-solves traffic shape: N tenants, each with a SMALL
    problem (distinct fingerprint — tenant-named pool — but identical
    catalog/pod SHAPES, so every tenant lands in the same compile-shape
    bucket), hammering one sidecar concurrently. Two phases over the same
    problems:

    * serialized — max_batch=1: the cfg7-shaped baseline, one exclusive
      device grant per request;
    * batched — the production defaults (max_batch=8, a few-ms window):
      a granted leader coalesces compatible queued problems into one
      vmapped multi-problem device dispatch.

    Records aggregate pods/sec both ways (speedup target >=2x), the mean
    batch size and batch-axis padding ratio actually achieved, and
    per-tenant p99 queue wait (batched must be no worse than serialized:
    coalescing must AMORTIZE device time, not starve anyone)."""
    import threading

    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.metrics import wiring as m
    from karpenter_core_tpu.solver import fleet, remote, service

    catalog = bench_catalog(n_types)
    tenants = [f"bt{i:02d}" for i in range(n_tenants)]
    problems = {
        tenant: {
            "pools": [_pool(tenant)],
            "its": {tenant: list(catalog)},
            # identical shape grid for every tenant: same pod-count bucket
            # and catalog cardinality -> same problem_bucket, which is
            # exactly the production fleet shape batching targets
            "pods": _plain_pods(n_pods, shapes=(6, 4)),
        }
        for tenant in tenants
    }

    def run_phase(max_batch, window_s):
        gateway = fleet.FleetGateway(
            # deep enough that nothing sheds: this config measures
            # throughput and wait, cfg7 owns overload behavior
            max_depth=2 * n_tenants + 4,
            max_batch=max_batch,
            batch_window=window_s,
        )
        cache = fleet.BoundedSchedulerCache(max_entries=n_tenants + 2)
        daemon = service.SolverDaemon(gateway=gateway, sched_cache=cache)
        srv = service.serve(0, daemon=daemon)
        try:
            addr = f"127.0.0.1:{srv.server_address[1]}"

            def scheduler_for(tenant):
                p = problems[tenant]
                client = remote.SolverClient(addr, timeout=600, tenant=tenant)
                return remote.RemoteScheduler(
                    client, p["pools"], p["its"],
                    device_scheduler_opts={"max_slots": 256},
                    verify=not NO_VERIFY,
                )

            errors = []
            counts = {t: 0 for t in tenants}

            def hammer(tenant, rounds, count=False):
                try:
                    rs = scheduler_for(tenant)
                    for _ in range(rounds):
                        res = rs.solve(problems[tenant]["pods"])
                        assert res.all_pods_scheduled(), res.pod_errors
                        if count:
                            counts[tenant] += 1
                except Exception as e:  # surfaced after join
                    errors.append((tenant, repr(e)))

            # warm-up 1: the batched jit entries compile per padded batch
            # size (1, 2, 4, ... — the power-of-two batch-axis pad), so
            # warm each size DETERMINISTICALLY with in-process
            # solve_batch calls at the exact problem shapes the timed
            # phase produces (the jit cache is process-global; the
            # concurrent warm rounds below cannot guarantee which batch
            # sizes they hit)
            if max_batch > 1:
                import copy as _copy

                from karpenter_core_tpu.models.provisioner import (
                    DeviceScheduler,
                    solve_batch,
                )

                size = 2
                while size <= max_batch:
                    entries = []
                    for j in range(size):
                        p = problems[tenants[j % n_tenants]]
                        entries.append((
                            DeviceScheduler(
                                p["pools"], p["its"], max_slots=256,
                                verify=False,
                            ),
                            _copy.deepcopy(p["pods"]),
                        ))
                    outcomes, _stats = solve_batch(entries)
                    assert all(st == "ok" for st, _ in outcomes)
                    size *= 2
            # warm-up 2: two untimed concurrent rounds through the real
            # transport warm the scheduler cache and the remaining cliffs
            for _ in range(2):
                ws = [
                    threading.Thread(
                        target=hammer, args=(t, 1), daemon=True
                    )
                    for t in tenants
                ]
                for w in ws:
                    w.start()
                for w in ws:
                    w.join()
            assert not errors, errors[:3]

            gateway.snapshot(reset=True)
            pad_sum0 = sum(m.SOLVERD_BATCH_PADDING.sums.values())
            pad_n0 = sum(m.SOLVERD_BATCH_PADDING.totals.values())
            threads = [
                threading.Thread(
                    target=hammer, args=(t, repeats, True), daemon=True
                )
                for t in tenants
            ]
            wall0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - wall0
            assert not errors, errors[:3]
            snap = gateway.snapshot()
            solves = sum(counts.values())
            pad_n = sum(m.SOLVERD_BATCH_PADDING.totals.values()) - pad_n0
            pad_sum = sum(m.SOLVERD_BATCH_PADDING.sums.values()) - pad_sum0
            waits = {
                t: snap["tenants"].get(t, {}).get("wait_p99_s", 0.0)
                for t in tenants
            }
            return {
                "aggregate_pods_per_sec": round(solves * n_pods / wall, 1),
                "wall_s": round(wall, 3),
                "solves": solves,
                "device_p50_s": snap["device_p50_s"],
                "grants": snap["grants"],
                "mean_batch_size": snap["batch"]["mean_size"],
                "coalesced": snap["batch"]["coalesced"],
                "padding_ratio": round(pad_sum / pad_n, 4) if pad_n else 0.0,
                "wait_p99_max_s": round(max(waits.values()), 6),
                "wait_p99_mean_s": round(
                    sum(waits.values()) / len(waits), 6
                ),
            }
        finally:
            srv.shutdown()
            srv.server_close()

    serialized = run_phase(1, 0.0)
    batched = run_phase(
        fleet.DEFAULT_MAX_BATCH, fleet.DEFAULT_BATCH_WINDOW_MS / 1000.0
    )
    speedup = batched["aggregate_pods_per_sec"] / max(
        serialized["aggregate_pods_per_sec"], 1e-9
    )
    import jax

    backend = jax.default_backend()
    out = {
        "tenants": n_tenants,
        "pods_per_tenant": n_pods,
        "repeats": repeats,
        "backend": backend,
        "serialized": serialized,
        "batched": batched,
        "speedup": round(speedup, 2),
        "speedup_ok": speedup >= 2.0,
        # the coalescer itself must demonstrably engage regardless of
        # backend: grants served >1 problem on average under contention
        "coalesce_ok": batched["mean_batch_size"] >= 1.5,
        # no-worse bound on the per-tenant tail: coalescing must not buy
        # throughput by starving someone (small epsilon absorbs timer
        # noise on near-zero waits)
        "queue_wait_ok": (
            batched["wait_p99_max_s"]
            <= serialized["wait_p99_max_s"] + 0.010
        ),
        "mean_batch_size": batched["mean_batch_size"],
        "padding_ratio": batched["padding_ratio"],
    }
    if backend == "cpu":
        # cfg8_multidev precedent: the amortization target is an
        # ACCELERATOR property — a vmapped batch on the CPU backend
        # competes with the sequential kernels for the same cores, so
        # the >=2x judgment belongs to the TPU bench box; the CPU run
        # still proves coalescing, fairness shares, and wait behavior
        out["speedup_note"] = (
            "cpu backend: batched kernels share the serial cores the"
            " solo kernels used; >=2x aggregate pods/sec is judged on"
            " the accelerator bench run"
        )
    return out


def _multidev_bench(repeats=3) -> dict:
    """cfg8_multidev: the primary config sharded over the local slice
    (DeviceScheduler(devices=all) — the pjit-over-ICI production path,
    ROADMAP item 1; target >=4x the single-device pods/sec on >=8
    devices). On a box without a real multi-device accelerator slice the
    throughput half is meaningless, so it records `throughput_skipped`
    and runs the sharded-vs-single parity battery in a CHILD process on a
    forced 8-device virtual CPU mesh instead (the same contract the
    MULTICHIP artifact checks)."""
    import jax

    n_avail = len(jax.devices())
    if jax.default_backend() == "cpu" or n_avail < 2:
        out = _run_multidev_probe()
        out.setdefault("throughput_skipped", True)
        out["reason"] = (
            f"{jax.default_backend()} backend with {n_avail} device(s);"
            " multi-device throughput needs a real >=2-device slice"
        )
        return out

    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog

    catalog = bench_catalog(N_TYPES)
    pods = _plain_pods(N_PODS)
    single = _solve_bench(
        pods, [_pool()], catalog, parity=False, repeats=repeats, devices=1
    )
    multi = _solve_bench(
        pods, [_pool()], catalog, parity=False, repeats=repeats,
        devices=n_avail,
    )
    speedup = multi["pods_per_sec"] / single["pods_per_sec"]
    return {
        "n_devices": n_avail,
        "throughput_skipped": False,
        "single": single,
        "multi": multi,
        "speedup_vs_single": round(speedup, 2),
        # the ISSUE 6 acceptance bar is defined on >=8 devices; on a
        # smaller slice report null rather than a vacuous pass
        "target_4x_ok": (speedup >= 4.0) if n_avail >= 8 else None,
        "parity_nodes_delta_multi_vs_single": (
            multi["nodes"] - single["nodes"]
        ),
    }


def _multidev_probe() -> None:
    """Child mode: a forced 8-device virtual CPU mesh runs the
    sharded-vs-single-device parity battery at small sizes — identical
    node counts and identical result wire bytes across an even split, a
    slot axis that needs padding (n_slots % n_devices != 0), and a
    3-device mesh. Throughput is NOT measured here (virtual devices share
    one CPU); prints one JSON line for the parent."""
    from karpenter_core_tpu.utils.jaxenv import force_virtual_cpu_mesh

    force_virtual_cpu_mesh(8)
    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.models.provisioner import DeviceScheduler
    from karpenter_core_tpu.solver import codec

    catalog = bench_catalog(100)
    parity = {}
    ok = True
    cases = (
        ("even_8dev", 256, 8),
        ("padded_slots_8dev", 100, 8),  # 100 -> 104 on the mesh
        ("uneven_3dev", 64, 3),
    )
    for name, max_slots, devices in cases:
        pods = _plain_pods(1000)
        its = {"default": list(catalog)}
        r1 = DeviceScheduler(
            [_pool()], dict(its), max_slots=max_slots, devices=1
        ).solve(pods)
        rn = DeviceScheduler(
            [_pool()], dict(its), max_slots=max_slots, devices=devices
        ).solve(pods)
        wire_ok = codec.encode_solve_results(
            rn, 0.0
        ) == codec.encode_solve_results(r1, 0.0)
        case_ok = (
            r1.all_pods_scheduled()
            and rn.all_pods_scheduled()
            and r1.node_count() == rn.node_count()
            and wire_ok
        )
        parity[name] = {
            "devices": devices,
            "max_slots": max_slots,
            "nodes_single": r1.node_count(),
            "nodes_sharded": rn.node_count(),
            "wire_parity": wire_ok,
            "ok": case_ok,
        }
        ok = ok and case_ok
    print(json.dumps({
        "n_devices": 8,
        "throughput_skipped": True,
        "parity_ok": ok,
        "parity": parity,
    }))


def _run_multidev_probe() -> dict:
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--multidev-probe"],
            capture_output=True, text=True, timeout=600,
            env=dict(os.environ),
        )
    except subprocess.TimeoutExpired:
        return {"error": "multidev probe exceeded 600s"}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except (ValueError, TypeError):
            continue
    return {"error": proc.stderr.strip()[-300:] or "no output"}


def _pallas_bench(n_pods=None, n_types=None, topo_pods=None,
                  topo_types=None, max_slots=1024, topo_slots=2048,
                  repeats=5) -> dict:
    """cfg17_pallas: the hand-fused Pallas FFD hot core vs the classic
    XLA lowering (ISSUE 18, ``--kernel=xla|pallas``) on the two shapes
    the acceptance names — the primary config (pallas target: p50 <
    0.3s) and the cfg3 topology mix (pallas target: p50 halved vs xla).

    Byte parity is asserted INSIDE the round, not just in the test
    battery: a speedup that moved a placement would be a bug wearing a
    win's clothes, so each shape solves once more under both backends
    through fresh schedulers and compares the encoded result wire.  The
    used-slot fetch window (aggregate_takes) is host-side post-kernel
    windowing, so on these single-device shapes ``fetch_dev_bytes``
    must be byte-identical across backends too — asserted here (on a
    multi-device mesh the pallas path commits replicated planes and the
    per-device fetch bytes legitimately differ; that comparison belongs
    to cfg8's sharded battery, not this gate).

    On the CPU backend pallas runs in interpret mode (pure-Python refs
    executed per class step), so the latency targets are an ACCELERATOR
    judgment — the cfg8 precedent: a CPU run records parity plus a
    ``speedup_note`` and leaves the target verdicts null."""
    import copy

    import jax

    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.models.provisioner import DeviceScheduler
    from karpenter_core_tpu.solver import codec

    backend = jax.default_backend()
    n_pods = N_PODS if n_pods is None else n_pods
    n_types = N_TYPES if n_types is None else n_types
    # topology shape rides the round's pod knob on sub-accelerator runs
    # (the cfg12 pattern): a default 50k-pod accelerator round keeps the
    # classic cfg3 5k x 400 point
    topo_pods = min(5000, max(n_pods // 4, 400)) if topo_pods is None \
        else topo_pods
    topo_types = min(400, n_types) if topo_types is None else topo_types

    def wire_parity(pods, pools, catalog, slots):
        # one fresh solve per backend, outside the timed loops: byte
        # compare the decision content (solve_seconds pinned — timing is
        # not packing)
        its = {p.name: list(catalog) for p in pools}
        wires = []
        for kb in ("xla", "pallas"):
            sched = DeviceScheduler(
                copy.deepcopy(pools), its, max_slots=slots,
                kernel_backend=kb,
            )
            wires.append(
                codec.encode_solve_results(
                    sched.solve(copy.deepcopy(pods)), 0.0
                )
            )
        return wires[0] == wires[1]

    def shape(pods, pools, catalog, slots, reps):
        xla = _solve_bench(
            pods, pools, catalog, max_slots=slots, repeats=reps,
            parity=False, kernel="xla",
        )
        pal = _solve_bench(
            pods, pools, catalog, max_slots=slots, repeats=reps,
            parity=False, kernel="pallas",
        )
        speedup = xla["p50_solve_s"] / max(pal["p50_solve_s"], 1e-9)
        return {
            "xla": xla,
            "pallas": pal,
            "speedup_vs_xla": round(speedup, 2),
            "wire_parity_ok": wire_parity(pods, pools, catalog, slots),
            # the satellite-4 gate: identical device fetch bytes — the
            # used-slot window is backend-agnostic host logic
            "fetch_dev_bytes_parity_ok": (
                xla["phases"].get("fetch_dev_bytes")
                == pal["phases"].get("fetch_dev_bytes")
            ),
            "nodes_delta_pallas_vs_xla": pal["nodes"] - xla["nodes"],
        }

    catalog = bench_catalog(n_types)
    primary = shape(
        _plain_pods(n_pods), [_pool()], catalog, max_slots, repeats
    )
    topology = shape(
        _topology_pods(topo_pods), [_pool()], bench_catalog(topo_types),
        topo_slots, max(repeats - 2, 2),
    )
    on_accel = backend != "cpu"
    out = {
        "backend": backend,
        "pods": n_pods,
        "topo_pods": topo_pods,
        "primary": primary,
        "topology": topology,
        # the acceptance verdicts are accelerator properties; null on a
        # CPU (interpret-mode) run rather than a vacuous fail
        "primary_p50_target_ok": (
            primary["pallas"]["p50_solve_s"] < 0.3 if on_accel else None
        ),
        "topology_halved_ok": (
            topology["speedup_vs_xla"] >= 2.0 if on_accel else None
        ),
        "parity_ok": (
            primary["wire_parity_ok"] and topology["wire_parity_ok"]
            and primary["fetch_dev_bytes_parity_ok"]
            and topology["fetch_dev_bytes_parity_ok"]
        ),
    }
    if not on_accel:
        out["speedup_note"] = (
            "cpu backend: the pallas kernel runs in interpret mode"
            " (pure-Python refs per class step), so latency targets are"
            " judged on the accelerator bench box; this run proves byte"
            " parity and the fetch-window byte parity"
        )
    return out


def _gangs_bench(n_pods=20000, n_existing=None, repeats=3,
                 cfg1_p50=None) -> dict:
    """cfg11_gangs: mixed-priority churn with gangs (ISSUE 10).

    The gangsched workload shape at scale: ~75% default-tier plain pods,
    10% system-critical pods SIZED PAST the largest fresh instance (the
    preemption traffic — they admit only by evicting strictly-lower-tier
    bound pods on the existing fleet), and 15% of pods in 8-pod gangs
    (all-or-nothing placement). Records:

    * preemption_count — victims named by the final solve's eviction
      claims (the drain-before-bind work the operator would execute);
    * eviction_minimality — evicted-cpu per admitted-cpu on preempted
      nodes, the minimality proxy: the kernel claims the cheapest
      sufficient PREFIX per node, so the ratio must stay near 1 (bounded
      by one victim's worth of overshoot per node, never a whole node's
      population for one pod);
    * gang_atomicity_violations — gangs left partially materialized
      (placed count in (0, min)); MUST be 0, and verification is ON so a
      forged packing would already have degraded;
    * p50_vs_cfg1 — the priority/gang machinery's price over the plain
      cfg1-shaped solve at the same scale (the off-by-default contract
      says plain problems pay nothing; THIS config pays the gang scan +
      preemption pass and records how much).
    """
    from karpenter_core_tpu.api.objects import ObjectMeta, Pod
    from karpenter_core_tpu.cloudprovider.kwok import build_catalog
    from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
        EvictablePod,
        SimNode,
    )
    from karpenter_core_tpu.models.provisioner import DeviceScheduler
    from karpenter_core_tpu.solver.gangs import (
        GANG_ANNOTATION,
        gang_min_count,
        pod_gang_sig,
    )
    from karpenter_core_tpu.utils.disruption import priority_tier

    catalog = build_catalog(cpu_grid=[1, 2, 4])  # fresh tops out at 4 cpu
    if n_existing is None:
        n_existing = max(4, n_pods // 250)
    existing = [
        SimNode(
            name=f"exist-{i}",
            labels={
                "topology.kubernetes.io/zone": "zone-a",
                "kubernetes.io/hostname": f"exist-{i}",
                "kubernetes.io/os": "linux",
                "kubernetes.io/arch": "amd64",
                "karpenter.sh/capacity-type": "on-demand",
                "karpenter.sh/nodepool": "default",
            },
            taints=[],
            available={"cpu": 0.5, "memory": 8 * GIB, "pods": 100.0},
            capacity={"cpu": 16.0, "memory": 16 * GIB, "pods": 110.0},
            initialized=True,
            evictable=tuple(
                EvictablePod(
                    uid=f"victim-{i}-{j}", priority=0,
                    requests={"cpu": 3.0, "memory": 0.5 * GIB},
                    cost=1.0 + 0.01 * j,
                )
                for j in range(4)
            ),
        )
        for i in range(n_existing)
    ]

    n_gang = int(n_pods * 0.15) // 8 * 8
    n_crit = int(n_pods * 0.10)
    pods = []
    for i in range(n_gang):
        p = Pod(
            metadata=ObjectMeta(
                name=f"g{i}",
                annotations={GANG_ANNOTATION: f"gang-{i // 8}"},
            ),
            resource_requests={
                "cpu": 0.5 * (1 + (i // 8) % 3),
                "memory": 0.25 * GIB * (1 + (i // 8) % 4),
            },
        )
        pods.append(p)
    for i in range(n_crit):
        # past the 4-cpu fresh ceiling: admits only via preemption; 16
        # memory shapes split the demand into classes so the bounded
        # per-class node fan-out (ops/gangsched.NODE_ROUNDS) spreads over
        # the fleet instead of serializing on one class
        p = Pod(
            metadata=ObjectMeta(name=f"c{i}"),
            resource_requests={
                "cpu": 6.0,
                "memory": 0.25 * GIB * (1 + i % 16),
            },
            priority=2_000_000_000,
        )
        pods.append(p)
    plain = _plain_pods(n_pods - len(pods))
    for p in plain:
        p.metadata.name = f"pl-{p.metadata.name}"
    pods.extend(plain)

    sched = DeviceScheduler(
        [_pool()], {"default": list(catalog)},
        existing_nodes=existing, max_slots=4096, verify=not NO_VERIFY,
    )
    t0 = time.perf_counter()
    res = sched.solve(pods)
    cold = time.perf_counter() - t0
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = sched.solve(pods)
        times.append(time.perf_counter() - t0)

    preemption_count = sum(len(uids) for uids in res.evictions.values())
    # minimality proxy: evicted cpu per admitted cpu on preempted nodes,
    # resolved from the claimed uids' actual requests so re-sizing the
    # synthetic victims keeps the gate honest
    victim_cpu = {
        e.uid: e.requests.get("cpu", 0.0)
        for n in existing
        for e in n.evictable
    }
    evicted_cpu = sum(
        victim_cpu.get(uid, 0.0)
        for uids in res.evictions.values()
        for uid in uids
    )
    # denominator: preemption-ADMITTED cpu only. The preempt pass serves
    # positive tiers exclusively, so tier-0 plain pods that the main scan
    # packed into a claimed node's ordinary free capacity must not
    # inflate the ratio and mask an over-evicting regression.
    admitted_cpu = 0.0
    for sim in res.existing_nodes:
        if sim.name in res.evictions:
            admitted_cpu += sum(
                p.resource_requests.get("cpu", 0.0)
                for p in sim.pods
                if priority_tier(p.priority) > 0
            )
    minimality = (
        round(evicted_cpu / admitted_cpu, 3) if admitted_cpu else None
    )
    # gang atomicity over the final results: placed in (0, min) = violation
    placed_uids = {
        p.uid
        for c in res.new_node_claims
        for p in c.pods
    } | {p.uid for s in res.existing_nodes for p in s.pods}
    by_gang = {}
    for p in pods:
        g = pod_gang_sig(p)
        if g is not None:
            by_gang.setdefault(g[0], []).append(p)
    violations = 0
    gangs_placed = 0
    for name, mpods in by_gang.items():
        n_placed = sum(1 for p in mpods if p.uid in placed_uids)
        if n_placed >= gang_min_count(mpods):
            gangs_placed += 1
        elif n_placed > 0:
            violations += 1

    out = _spread(times)
    p50_raw = sorted(times)[len(times) // 2]
    out.update({
        "cold_solve_s": round(cold, 3),
        "pods": len(pods),
        "pods_per_sec": round(len(pods) / p50_raw, 1),
        "preemption_count": preemption_count,
        "eviction_minimality": minimality,
        # one 6-cpu admit needs 5.5 freed = 2 victims (6.0): per-node
        # overshoot is bounded by one victim, so the fleet-wide ratio must
        # stay under ~1.2 when anything preempted at all
        "eviction_minimality_ok": minimality is None or minimality <= 1.2,
        "gangs": len(by_gang),
        "gangs_placed": gangs_placed,
        "gang_atomicity_violations": violations,
        "gang_atomicity_ok": violations == 0,
        "unschedulable": len(res.pod_errors),
        "phases": _phase_breakdown(sched),
    })
    if cfg1_p50:
        out["p50_vs_cfg1"] = round(p50_raw / cfg1_p50, 2)
    return out


def _topoaware_bench(n_gangs=40, n_plain=2000, repeats=3) -> dict:
    """cfg18_topoaware: rank/topology-aware gang placement (ISSUE 20).

    A racked 2-zone fleet (racks of two nodes, superpods of two racks,
    zones interleaved in slot order — the adversarial order for a
    distance-blind first-fit) hosting comms-sensitive 8-pod gangs, each
    declaring a hard ``pod-group-max-hops: 2`` (same zone) bound and
    per-member collective ranks, plus plain filler pods that land on
    fresh capacity. Two runs of the IDENTICAL problem:

    * **aware** — nodes carry their rack/superpod labels, so the
      topology catalog engages: per-gang anchor planes steer the FFD
      level fill and the relax objective toward network-near slots;
    * **blind** — the same nodes with topology labels STRIPPED (the
      pre-topoaware catalog): the solver first-fits across the
      interleaved zones; hops are then measured against the TRUE racked
      labels the run couldn't see.

    Gates: ``topo_hops_ok`` — the aware run's worst intra-gang hop
    distance is STRICTLY below the blind control's at equal-or-better
    node count; ``hard_bound_ok`` — no accepted aware placement provably
    exceeds its declared bound (the verifier's sound re-derivation);
    ``gangs_placed_ok`` — every gang actually bound (the comparison is
    not vacuous). ``p50_ratio`` records the topo machinery's latency
    price over the blind solve of the same problem.
    """
    from karpenter_core_tpu.api import labels as apilabels
    from karpenter_core_tpu.api.objects import ObjectMeta, Pod
    from karpenter_core_tpu.cloudprovider.kwok import build_catalog
    from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
        SimNode,
    )
    from karpenter_core_tpu.models.provisioner import DeviceScheduler
    from karpenter_core_tpu.solver.gangs import (
        GANG_ANNOTATION,
        GANG_MAX_HOPS_ANNOTATION,
        GANG_MIN_SIZE_ANNOTATION,
        GANG_RANK_ANNOTATION,
        hop_distance,
        placement_hop_bound,
    )

    catalog = build_catalog(cpu_grid=[1, 2])  # fresh tops out at 2 cpu
    max_hops = 2  # hard bound: same zone
    gang_size = 8
    member_cpu = 3.0  # past the fresh ceiling: gangs live on the fleet
    # 2 members per node -> 4 nodes per gang, plus slack
    n_existing = 4 * n_gangs + 8

    def racked_nodes(with_topo_labels: bool):
        nodes = []
        for i in range(n_existing):
            zone = "zone-a" if i % 2 == 0 else "zone-b"
            zi = i // 2  # creation order within the zone
            labels = {
                "topology.kubernetes.io/zone": zone,
                "kubernetes.io/hostname": f"exist-{i}",
                "kubernetes.io/os": "linux",
                "kubernetes.io/arch": "amd64",
                "karpenter.sh/capacity-type": "on-demand",
                "karpenter.sh/nodepool": "default",
            }
            if with_topo_labels:
                labels[apilabels.LABEL_TOPOLOGY_RACK] = f"{zone}-r{zi // 2}"
                labels[apilabels.LABEL_TOPOLOGY_SUPERPOD] = (
                    f"{zone}-s{zi // 4}"
                )
            nodes.append(SimNode(
                name=f"exist-{i}",
                labels=labels,
                taints=[],
                available={
                    "cpu": 2 * member_cpu + 0.5,
                    "memory": 8 * GIB,
                    "pods": 100.0,
                },
                capacity={"cpu": 16.0, "memory": 16 * GIB, "pods": 110.0},
                initialized=True,
            ))
        return nodes

    # the TRUE topology, for judging both runs (the blind run never saw it)
    truth = {
        n.name: dict(n.labels) for n in racked_nodes(with_topo_labels=True)
    }

    pods = []
    for g in range(n_gangs):
        for i in range(gang_size):
            pods.append(Pod(
                metadata=ObjectMeta(
                    name=f"tg{g}-{i}",
                    annotations={
                        GANG_ANNOTATION: f"tgang-{g}",
                        GANG_MIN_SIZE_ANNOTATION: str(gang_size),
                        GANG_MAX_HOPS_ANNOTATION: str(max_hops),
                        GANG_RANK_ANNOTATION: str(i),
                    },
                ),
                resource_requests={
                    "cpu": member_cpu, "memory": 0.25 * GIB,
                },
            ))
    plain = _plain_pods(n_plain)
    for p in plain:
        p.metadata.name = f"pl-{p.metadata.name}"
    pods.extend(plain)

    def result_cost(res):
        total = 0.0
        for c in res.new_node_claims:
            total += min(
                off.price
                for it_ in c.instance_type_options
                for off in it_.offerings
                if off.available
            )
        return total

    out = {"pods": len(pods), "gangs": n_gangs, "max_hops_bound": max_hops}
    measured = {}
    for mode in ("aware", "blind"):
        existing = racked_nodes(with_topo_labels=(mode == "aware"))
        sched = DeviceScheduler(
            [_pool()], {"default": list(catalog)},
            existing_nodes=existing, max_slots=4096, verify=not NO_VERIFY,
        )
        t0 = time.perf_counter()
        res = sched.solve(pods)
        cold = time.perf_counter() - t0
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            res = sched.solve(pods)
            times.append(time.perf_counter() - t0)
        # judge each gang's placement against the TRUE racked labels
        node_of = {}
        for sim in res.existing_nodes:
            for p in sim.pods:
                node_of[p.metadata.name] = sim.name
        worst_hops = 0
        worst_bound = 0
        gangs_placed = 0
        for g in range(n_gangs):
            placed = [
                truth[node_of[f"tg{g}-{i}"]]
                for i in range(gang_size)
                if f"tg{g}-{i}" in node_of
            ]
            if len(placed) < gang_size:
                continue
            gangs_placed += 1
            worst_hops = max(worst_hops, max(
                hop_distance(a, b)
                for i, a in enumerate(placed)
                for b in placed[i + 1:]
            ))
            worst_bound = max(worst_bound, placement_hop_bound(placed))
        p50_raw = sorted(times)[len(times) // 2]
        measured[mode] = {
            "p50": p50_raw,
            "hops": worst_hops,
            "nodes": len(res.new_node_claims) + sum(
                1 for s in res.existing_nodes if s.pods
            ),
        }
        out[mode] = {
            **_spread(times),
            "cold_solve_s": round(cold, 3),
            "max_intra_gang_hops": worst_hops,
            "provable_hop_bound": worst_bound,
            "gangs_placed": gangs_placed,
            "node_count": measured[mode]["nodes"],
            "new_claims": len(res.new_node_claims),
            "cost_dollars_per_hour": round(result_cost(res), 3),
            "unschedulable": len(res.pod_errors),
        }
    aware, blind = out["aware"], out["blind"]
    out.update({
        "p50_ratio": round(
            measured["aware"]["p50"] / measured["blind"]["p50"], 2
        ),
        "gangs_placed_ok": (
            aware["gangs_placed"] == n_gangs
            and blind["gangs_placed"] == n_gangs
        ),
        # strictly fewer hops at equal-or-better node count: the topo
        # steering pays in placement order, never in nodes
        "topo_hops_ok": (
            aware["max_intra_gang_hops"] < blind["max_intra_gang_hops"]
            and aware["node_count"] <= blind["node_count"]
        ),
        # the hard annotation bound holds on every ACCEPTED aware
        # placement, by the verifier's own sound re-derivation
        "hard_bound_ok": aware["provable_hop_bound"] <= max_hops,
    })
    return out


def _relax_bench(n_pods=5000, repeats=3):
    """cfg12_relax: the relaxsolve backend (ISSUE 13) vs FFD on the two
    marquee shapes — cfg3-shaped (the diverse topology mix) and
    cfg11-shaped (gang/tier mix) problems — over a two-pool catalog where
    first-template-wins is provably suboptimal (pool A, first by name,
    offers only small nodes; pool B dense nodes at a lower per-cpu
    price: the heuristic packs A, the optimizer B). Both modes solve the
    IDENTICAL pod sets; the record is the node-count and $-cost delta at
    the two p50s — the acceptance gate is relax strictly fewer nodes AND
    dollars at equal-or-better p50 (the verdict cache makes warm relax
    solves single-dispatch, so warm p50 parity is by construction, not
    luck). Verification stays ON (--no-verify governs here too), so a
    relax packing that tripped the verifier would show up as a silent
    greedy degradation in the node counts."""
    from karpenter_core_tpu.api.objects import ObjectMeta, Pod
    from karpenter_core_tpu.cloudprovider.kwok import build_catalog
    from karpenter_core_tpu.models.provisioner import DeviceScheduler
    from karpenter_core_tpu.solver.gangs import GANG_ANNOTATION

    cat_a = build_catalog(cpu_grid=[4], mem_factors=[4], oses=["linux"],
                          arches=["amd64"])
    cat_b = build_catalog(cpu_grid=[16], mem_factors=[4], oses=["linux"],
                          arches=["amd64"])
    # the dense pool's committed-use/spot-shaped discount: 25% under the
    # linear kwok price curve, so its per-pod $ is structurally lower for
    # any class that can actually fill it — the cost surface the
    # relaxation optimizes and first-template-wins is blind to
    for it in cat_b:
        for off in it.offerings:
            off.price *= 0.75
    pools = [_pool("a-first"), _pool("b-dense")]
    its = {"a-first": list(cat_a), "b-dense": list(cat_b)}

    def gang_tier_pods(n):
        # the cfg11 traffic shape sans preemption fleet: 15% in 8-pod
        # all-or-nothing gangs, 10% high-priority, the rest plain — the
        # relaxation must compose gang atomicity and tier ordering, not
        # merely survive them
        n_gang = int(n * 0.15) // 8 * 8
        n_crit = int(n * 0.10)
        pods = []
        for i in range(n_gang):
            pods.append(Pod(
                metadata=ObjectMeta(
                    name=f"g{i}",
                    annotations={GANG_ANNOTATION: f"gang-{i // 8}"},
                ),
                resource_requests={
                    "cpu": 0.5 * (1 + (i // 8) % 3),
                    "memory": 0.25 * GIB * (1 + (i // 8) % 4),
                },
            ))
        for i in range(n_crit):
            pods.append(Pod(
                metadata=ObjectMeta(name=f"c{i}"),
                resource_requests={
                    "cpu": 1.0, "memory": 0.25 * GIB * (1 + i % 4),
                },
                priority=1_000_000,
            ))
        plain = _plain_pods(n - len(pods), shapes=(4, 3))
        for p in plain:
            p.metadata.name = f"pl-{p.metadata.name}"
        return pods + plain

    def result_cost(res):
        total = 0.0
        for c in res.new_node_claims:
            total += min(
                off.price
                for it_ in c.instance_type_options
                for off in it_.offerings
                if off.available
            )
        return total

    problems = {
        "cfg3_shape": _topology_pods(n_pods, n_deploys=max(n_pods // 500, 2)),
        "cfg11_shape": gang_tier_pods(n_pods),
    }
    out = {"pods": n_pods, "pools": 2}
    for pname, pods in problems.items():
        entry = {}
        for mode in ("ffd", "relax"):
            sched = DeviceScheduler(
                pools, its, max_slots=4096, verify=not NO_VERIFY,
                solver_mode=mode,
            )
            t0 = time.perf_counter()
            res = sched.solve(pods)
            cold = time.perf_counter() - t0
            # settle solve (untimed): the adaptive slot axis shrinks after
            # the cold solve, which re-keys the class batch — this run
            # pays the re-evaluation/compiles at the settled shape so the
            # timed repeats below measure steady state for BOTH modes
            # (relax's steady state is the verdict-cached single dispatch)
            sched.solve(pods)
            times = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                res = sched.solve(pods)
                times.append(time.perf_counter() - t0)
            m = _spread(times)
            m.update({
                "cold_solve_s": round(cold, 3),
                "nodes": res.node_count(),
                "cost": round(result_cost(res), 3),
                "unschedulable": len(res.pod_errors),
                "phases": _phase_breakdown(sched),
            })
            entry[mode] = m
        f, r = entry["ffd"], entry["relax"]
        entry["nodes_delta"] = r["nodes"] - f["nodes"]  # negative = win
        entry["cost_delta"] = round(r["cost"] - f["cost"], 3)
        entry["p50_ratio"] = (
            round(r["p50_solve_s"] / f["p50_solve_s"], 3)
            if f["p50_solve_s"] else None
        )
        entry["node_improved"] = r["nodes"] < f["nodes"]
        entry["cost_improved"] = r["cost"] < f["cost"]
        # warm p50 parity: the verdict cache must make relax's steady
        # state cost what ffd's does (10% jitter headroom, or 50ms
        # absolute at smoke scale where both p50s are a few ms)
        entry["p50_ok"] = (
            entry["p50_ratio"] is None
            or entry["p50_ratio"] <= 1.10
            or r["p50_solve_s"] - f["p50_solve_s"] <= 0.05
        )
        out[pname] = entry
    out["relax_ok"] = all(
        out[p]["node_improved"] and out[p]["cost_improved"]
        and out[p]["p50_ok"]
        for p in problems
    )
    return out


def _delta_bench(
    n_pods=2000,
    n_nodes=600,
    n_types=300,
    churn=0.01,
    rounds=5,
    fleet_tenants=6,
    fleet_rounds=3,
    fleet_sizes=(1, 2, 4),
):
    """cfg13_delta: the delta wire + solver fleet (ISSUE 14).

    Phase 1 (wire): an operator-shaped problem — existing nodes carrying
    a topology context, a real catalog, a pending-pod batch sized at the
    churn fraction — re-solved across `rounds` snapshots that each
    replace ``churn`` of the nodes and mint a fresh pending batch.
    Both wire forms are driven against their own daemon (transport-free,
    so the bytes ARE the payloads): the full path re-encodes and ships
    everything; the delta path ships a digest manifest plus exactly the
    segments the far side has not seen (the client-side sent-set the
    real SolverClient keeps). Records per-re-solve bytes and latency on
    both paths, the delta/full byte ratio (acceptance: <= 0.10 at
    scale), and node-count + result-wire parity per round (the manifest
    path may never change a packing).

    Phase 2 (fleet): N tenants with distinct catalogs (distinct problem
    fingerprints — warm scheduler caches are the prize) hammer 1 / 2 / 4
    in-thread sidecars through the client-side FleetRouter; at the
    largest size, affinity on vs off. Records aggregate pods/sec and the
    scheduler-cache hit rate per topology (affinity must keep re-solves
    hitting the member whose caches are warm)."""
    import copy
    import threading

    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.metrics import wiring as m
    from karpenter_core_tpu.solver import codec, remote, segments, service

    catalog = bench_catalog(n_types)
    pools = [_pool()]
    its = {"default": list(catalog)}
    from karpenter_core_tpu.api import labels as L
    from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
        SimNode,
    )
    from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
        Topology,
    )

    def make_node(name, i):
        return SimNode(
            name=name,
            labels={
                L.LABEL_ARCH: "amd64",
                L.LABEL_OS: "linux",
                L.LABEL_TOPOLOGY_ZONE: f"zone-{'abcd'[i % 4]}",
                L.LABEL_HOSTNAME: name,
                L.NODEPOOL_LABEL_KEY: "default",
            },
            taints=[],
            available={"cpu": 2.0, "memory": 4 * GIB, "pods": 200.0},
            capacity={"cpu": 8.0, "memory": 16 * GIB, "pods": 210.0},
            initialized=True,
        )

    nodes = [make_node(f"node-{i:05d}", i) for i in range(n_nodes)]
    # a topology context shaped like the provisioner's: a few bound pods
    # per node ride the wire as (pod, labels, node) triples
    ctx_pods = _plain_pods(2 * n_nodes, shapes=(4, 3))
    existing_pods = [
        (p, {"app": f"ctx-{i % 7}"}, nodes[i // 2].name)
        for i, p in enumerate(ctx_pods)
    ]
    domains = {
        L.LABEL_TOPOLOGY_ZONE: {f"zone-{z}" for z in "abcd"},
        L.LABEL_HOSTNAME: {n.name for n in nodes},
    }
    batch = max(int(n_pods * churn), 4)

    def snapshot(round_no):
        """Round r's churned snapshot: `churn` of the nodes replaced,
        a fresh pending batch (new pods ALWAYS ship — they are new)."""
        ns = list(nodes)
        k = max(int(n_nodes * churn), 1)
        for j in range(k):
            i = (round_no * 31 + j * 97) % n_nodes
            ns[i] = make_node(f"node-r{round_no}-{i:05d}", i)
        pending = _plain_pods(batch)
        for p in pending:
            p.metadata.name = f"r{round_no}-{p.metadata.name}"
        topo = Topology(
            domains={k_: set(v) for k_, v in domains.items()},
            existing_pods=[
                t for t in existing_pods
                if any(n.name == t[2] for n in ns)
            ],
            excluded_pod_uids={p.uid for p in pending},
        )
        return ns, pending, topo

    def result_view(out):
        h = codec._json_header(out)
        h.pop("solve_seconds", None)
        return h

    d_full = service.SolverDaemon()
    d_delta = service.SolverDaemon()
    # the client-side ledger (SolverClient.segcache shape): sent digests
    # + the last confirmed listing, so steady-state manifests ship
    # base+edits instead of the full digest listing
    sent = set()
    base = None
    full_bytes, delta_bytes = [], []
    full_times, delta_times = [], []
    parity_ok = True
    for r in range(rounds + 1):  # round 0 is the cold start
        ns, pending, topo = snapshot(r)
        header = codec._encode_solve_header(
            pools, its, ns, [], pending, topology=topo, max_slots=1024,
        )
        # symmetric timing: each path's timer covers ITS encode (the
        # container dump here, split+manifest-encode below) plus the
        # daemon round — the p50 comparison must not hide the full
        # wire's encode cost
        t0 = time.perf_counter()
        body_full = codec._json_payload(header)
        out_full, _ = d_full.solve(body_full)
        t_full = time.perf_counter() - t0

        t0 = time.perf_counter()
        plan = segments.split_solve_header(header)
        include = [dg for dg in plan.segments if dg not in sent]
        body_delta = codec.encode_manifest_request(plan, include, base=base)
        out_delta, _ = d_delta.solve(body_delta)
        t_delta = time.perf_counter() - t0
        sent |= set(plan.segments)
        base = (plan.listing_digest, plan.listing)

        parity_ok = parity_ok and (
            result_view(out_full) == result_view(out_delta)
        )
        if r > 0:  # the cold round is the catalog upload, not the regime
            full_bytes.append(len(body_full))
            delta_bytes.append(len(body_delta))
            full_times.append(t_full)
            delta_times.append(t_delta)

    ratio = (
        sum(delta_bytes) / sum(full_bytes) if sum(full_bytes) else 1.0
    )
    nodes_full = len(codec._json_header(out_full)["claims"])
    nodes_delta = len(codec._json_header(out_delta)["claims"])

    wire = {
        "nodes": n_nodes,
        "ctx_pods": len(existing_pods),
        "pending_per_round": batch,
        "churn": churn,
        "rounds": rounds,
        "full_wire_bytes_per_resolve": int(
            sum(full_bytes) / max(len(full_bytes), 1)
        ),
        "delta_wire_bytes_per_resolve": int(
            sum(delta_bytes) / max(len(delta_bytes), 1)
        ),
        "delta_ratio": round(ratio, 4),
        # the acceptance gate: a 1%-churn re-solve ships <=10% of the
        # full wire (judged at the full-scale round; a BENCH_FAST run
        # has too little stable snapshot for 10% and records the ratio)
        "delta_ok": bool(ratio <= 0.10),
        "p50_full_resolve_s": round(
            sorted(full_times)[len(full_times) // 2], 4
        ) if full_times else None,
        "p50_delta_resolve_s": round(
            sorted(delta_times)[len(delta_times) // 2], 4
        ) if delta_times else None,
        "parity_ok": bool(parity_ok),
        "result_nodes_delta": nodes_delta - nodes_full,
    }

    # -- phase 2: 1 vs 2 vs 4 sidecars through the fleet router ------------

    tenant_problems = []
    for t in range(fleet_tenants):
        tcat = bench_catalog(max(n_types // 2 + 7 * t, 20))
        tenant_problems.append((
            f"tenant{t}",
            [_pool()],
            {"default": list(tcat)},
            _plain_pods(max(batch, 24)),
        ))

    def run_fleet(n_sidecars, affinity):
        srvs = [service.serve(0) for _ in range(n_sidecars)]
        try:
            members = [
                remote.SolverClient(
                    f"127.0.0.1:{s.server_address[1]}",
                    timeout=600, member=str(i),
                )
                for i, s in enumerate(srvs)
            ]
            router = remote.FleetRouter(members, affinity=affinity)
            scheds = {
                tenant: remote.RemoteScheduler(
                    router, tpools, tits,
                    device_scheduler_opts={"max_slots": 256},
                    verify=not NO_VERIFY,
                )
                for tenant, tpools, tits, _ in tenant_problems
            }
            hits0 = m.SOLVERD_SCHED_CACHE.value({"outcome": "hit"})
            miss0 = m.SOLVERD_SCHED_CACHE.value({"outcome": "miss"})
            solved = [0]
            lock = threading.Lock()

            def hammer(tenant, tpods):
                for _ in range(fleet_rounds):
                    res = scheds[tenant].solve(copy.deepcopy(tpods))
                    assert res.all_pods_scheduled()
                    with lock:
                        solved[0] += len(tpods)

            t0 = time.perf_counter()
            threads = [
                threading.Thread(
                    target=hammer, args=(tenant, tpods), daemon=True
                )
                for tenant, _tp, _ti, tpods in tenant_problems
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            wall = time.perf_counter() - t0
            hits = m.SOLVERD_SCHED_CACHE.value({"outcome": "hit"}) - hits0
            misses = (
                m.SOLVERD_SCHED_CACHE.value({"outcome": "miss"}) - miss0
            )
            return {
                "sidecars": n_sidecars,
                "affinity": affinity,
                "aggregate_pods_per_sec": round(solved[0] / wall, 1),
                "wall_s": round(wall, 3),
                "sched_cache_hit_rate": round(
                    hits / max(hits + misses, 1), 3
                ),
                "routed": router.snapshot()["routed"],
            }
        finally:
            for s in srvs:
                s.shutdown()
                s.server_close()

    fleet = {}
    for k in fleet_sizes:
        fleet[f"x{k}"] = run_fleet(k, affinity=True)
    fleet["x%d_no_affinity" % fleet_sizes[-1]] = run_fleet(
        fleet_sizes[-1], affinity=False
    )
    on = fleet[f"x{fleet_sizes[-1]}"]["sched_cache_hit_rate"]
    off = fleet[
        "x%d_no_affinity" % fleet_sizes[-1]
    ]["sched_cache_hit_rate"]
    return {
        "wire": wire,
        "fleet": fleet,
        "tenants": fleet_tenants,
        "rounds_per_tenant": fleet_rounds,
        # affinity's whole point: re-solves keep hitting the member whose
        # caches are warm, so the hit rate must not degrade vs no-affinity
        "affinity_hit_rate": on,
        "no_affinity_hit_rate": off,
        "affinity_cache_ok": bool(on >= off),
    }


def _incremental_bench(
    n_pods=2000,
    n_nodes=600,
    n_types=300,
    churn=0.01,
    rounds=8,
):
    """cfg15_incremental: the churn-proportional incremental re-solve
    engine (ISSUE 16).

    A 600-node operator snapshot with a standing pod set, re-solved over
    1%-churn rounds: each round one small-pod class shrinks by the churn
    fraction while another grows by the same amount (pods replaced, net
    demand steady — the regime the PackingLedger exists for). The mix is
    operator-shaped: an anchor class of node-sized pods that can only
    land on fresh claims (the stable packing the ledger pins), plus
    small classes that fit the existing nodes' headroom (where real
    churn lands). Two daemons see the identical round sequence: one
    driven with prev_fingerprint chaining (the engine's path — round r
    names round r-1's fingerprint, as the real SolverClient does), one
    always fresh.
    Records the p50 re-solve both ways, the speedup, the per-round
    node-count delta vs fresh (node quality must not rot as replays
    compound), and the engine's outcome mix (warm/partial/drift_reset).

    Gates (`incremental_ok`, judged at full scale — a BENCH_FAST run is
    too small for the fresh solve to cost anything, and records the
    numbers): incremental p50 >= 5x below fresh, node count within 2%
    of fresh every round, zero self-verify rejections, and the
    client-facing solver_result_rejected_total unmoved."""
    from karpenter_core_tpu.api import labels as L
    from karpenter_core_tpu.api.objects import ObjectMeta, Pod
    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (  # noqa: E501
        SimNode,
    )
    from karpenter_core_tpu.metrics import wiring as m
    from karpenter_core_tpu.solver import codec, service

    catalog = bench_catalog(n_types)
    pools = [_pool()]
    its = {"default": list(catalog)}
    nodes = [
        SimNode(
            name=f"node-{i:05d}",
            labels={
                L.LABEL_ARCH: "amd64",
                L.LABEL_OS: "linux",
                L.LABEL_TOPOLOGY_ZONE: f"zone-{'abcd'[i % 4]}",
                L.LABEL_HOSTNAME: f"node-{i:05d}",
                L.NODEPOOL_LABEL_KEY: "default",
            },
            taints=[],
            available={"cpu": 2.0, "memory": 4 * GIB, "pods": 200.0},
            capacity={"cpu": 8.0, "memory": 16 * GIB, "pods": 210.0},
            initialized=True,
        )
        for i in range(n_nodes)
    ]

    # explicit per-class counts so one round's churn is attributable to
    # exactly two equivalence classes (one drains, one fills). Anchors
    # are node-sized (cpu 4.0 > the existing nodes' 2.0 headroom) so
    # they always mint claims; the small classes stay well inside the
    # snapshot's aggregate headroom so churn re-packs onto existing
    # capacity instead of fragmenting the pinned claims
    n_anchor = max(n_pods // 10, 4)
    n_classes = max(min(36, (n_pods - n_anchor) // 8), 2)
    counts = {
        c: (n_pods - n_anchor) // n_classes for c in range(n_classes)
    }

    def make_pods():
        out = [
            Pod(
                metadata=ObjectMeta(name=f"anchor-{i:04d}"),
                resource_requests={"cpu": 4.0, "memory": 2 * GIB},
            )
            for i in range(n_anchor)
        ]
        for c in range(n_classes):
            for i in range(counts[c]):
                out.append(Pod(
                    metadata=ObjectMeta(name=f"c{c:02d}-{i:04d}"),
                    resource_requests={
                        "cpu": 0.1 * (1 + c % 4),
                        # per-class-unique memory: each counts-class IS
                        # one pod equivalence class (group_pods keys on
                        # the request shape), so one round's churn
                        # dirties exactly two classes, not a merged blob
                        "memory": 0.05 * GIB * (1 + c),
                    },
                ))
        return out

    def body_for(pods, prev=""):
        return codec.encode_solve_request(
            pools, its, nodes, [], pods, max_slots=1024,
            prev_fingerprint=prev,
        )

    d_inc = service.SolverDaemon()
    d_fresh = service.SolverDaemon()
    out_base = dict(m.SOLVER_INCREMENTAL.values)
    rej_base = sum(m.SOLVER_RESULT_REJECTED.values.values())

    def claims_of(out):
        return len(codec._json_header(out)["claims"])

    # round 0: the cold start, twice on the incremental daemon — the
    # first request names no predecessor (bypasses the engine), the
    # second names it and records the packing (outcome full/miss). The
    # steady-state regime starts at round 1.
    pods0 = make_pods()
    base_body = body_for(pods0)
    prev = codec.problem_fingerprint(codec._json_header(base_body))
    d_fresh.solve(base_body)
    d_inc.solve(base_body)
    d_inc.solve(body_for(pods0, prev=prev))

    k = max(int(n_pods * churn), 2)
    inc_times, fresh_times = [], []
    node_delta_pct = 0.0
    for r in range(1, rounds + 1):
        # 1% of the fleet's pods replaced: small class A drains k,
        # small class B fills k (distinct classes each round)
        a, b = (2 * r) % n_classes, (2 * r + 1) % n_classes
        if a == b:
            b = (a + 1) % n_classes
        counts[a] = max(counts[a] - k, 0)
        counts[b] += k
        pods = make_pods()
        body = body_for(pods)

        t0 = time.perf_counter()
        out_f, _ = d_fresh.solve(body)
        fresh_times.append(time.perf_counter() - t0)

        inc_body = body_for(pods, prev=prev)
        t0 = time.perf_counter()
        out_i, _ = d_inc.solve(inc_body)
        inc_times.append(time.perf_counter() - t0)
        prev = codec.problem_fingerprint(codec._json_header(body))

        nf, ni = claims_of(out_f), claims_of(out_i)
        node_delta_pct = max(
            node_delta_pct, abs(ni - nf) / max(nf, 1)
        )

    outcomes = {
        key[0][1]: int(
            m.SOLVER_INCREMENTAL.values[key] - out_base.get(key, 0)
        )
        for key in m.SOLVER_INCREMENTAL.values
        if m.SOLVER_INCREMENTAL.values[key] != out_base.get(key, 0)
    }
    rejections = int(
        sum(m.SOLVER_RESULT_REJECTED.values.values()) - rej_base
    )
    p50_inc = sorted(inc_times)[len(inc_times) // 2]
    p50_fresh = sorted(fresh_times)[len(fresh_times) // 2]
    speedup = p50_fresh / max(p50_inc, 1e-9)
    replayed = outcomes.get("warm", 0) + outcomes.get("partial", 0)
    return {
        "pods": n_anchor + sum(counts.values()),
        "nodes": n_nodes,
        "types": n_types,
        "churn": churn,
        "rounds": rounds,
        "p50_fresh_resolve_s": round(p50_fresh, 4),
        "p50_incremental_resolve_s": round(p50_inc, 4),
        "speedup_x": round(speedup, 1),
        "node_delta_pct_max": round(100.0 * node_delta_pct, 3),
        "outcomes": outcomes,
        "replayed_rounds": replayed,
        # the self-verify gate is structural: ANY rejected outcome means
        # the replay machinery built a packing the trust anchor refused
        "incremental_rejected": outcomes.get("rejected", 0),
        # ... and the client-facing counter must never move for replays
        "verifier_rejections": rejections,
        "ledger": d_inc.incremental.ledger.stats(),
        "incremental_ok": bool(
            speedup >= 5.0
            and node_delta_pct <= 0.02
            and replayed > 0
            and outcomes.get("rejected", 0) == 0
            and rejections == 0
        ),
    }


def _elastic_bench(
    n_tenants=6,
    n_types=48,
    n_pods=36,
    surge_ticks=6,
    quiet_ticks=8,
    tick_s=30.0,
    max_members=4,
):
    """cfg16_elastic: the closed-loop elastic solver tier (ISSUE 17).

    Phase 1 (economics): N tenants with distinct catalogs drive a
    surge-then-quiet load trace against two tiers serving the identical
    workload — one autoscaled (starts at 1 member, TierAutoscaler grows
    it through the real spawn path and retires through the faultless
    drain path), one pinned at max size (the control). Member-seconds
    are charged on a virtual tick clock (live size x tick), so the
    economics are deterministic; queue waits are measured from the real
    gateways AFTER the autoscaler's ramp window, when both tiers serve
    at full size. Resize cost is audited the way the contract states it:
    rendezvous re-keys only the retired/granted member's digests, so a
    resize costs at most one upload round per remapped lineage and
    NOTHING else — zero segment-miss repair rounds, zero greedy
    fallbacks, every surviving breaker closed.

    Phase 2 (ladder): a tier pinned at max size is driven over budget;
    the brownout rungs must fire 1 -> 2 -> 3 strictly in order (relax
    served as FFD, batch window widened, admission halved), then clear
    3 -> 2 -> 1 -> 0 restoring the gateway shape, with the verifier
    rejection counter unmoved throughout.

    Gates: `saving_ok` (autoscaled member-seconds >= 30% below the
    fixed-size control — structural, the sizes ride the deterministic
    policy), `resize_cost_ok` (miss rounds 0, fallbacks 0, breakers
    closed), `brownout_order_ok` (rungs fire and clear in order, shape
    restored, rejections unmoved); `p99_ok` and the headline
    `elastic_ok` are judged at the full-scale round."""
    import copy
    import threading

    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.metrics import wiring as m
    from karpenter_core_tpu.solver import fleet as fleetmod
    from karpenter_core_tpu.solver import remote, service
    from karpenter_core_tpu.solver.autoscale import (
        MemberSignal,
        TierAutoscaler,
        TierSignals,
    )

    tenant_problems = []
    for t in range(n_tenants):
        # floor 20: below that bench_catalog lacks the shapes
        # _plain_pods needs (the cfg13 fleet-phase floor)
        tcat = bench_catalog(max(n_types // 2 + 5 * t, 20))
        tenant_problems.append((
            f"tenant{t}",
            [_pool()],
            {"default": list(tcat)},
            _plain_pods(n_pods),
        ))
    vnow = [0.0]

    # per-member capacity (solves per tick) chosen so the surge at full
    # tenant fan-in is under budget ONLY at max size — the autoscaled
    # tier must ramp all the way — while a single quiet tenant sits in
    # the scale-down band even at max size
    member_capacity = n_tenants / (max_members - 0.5)

    class BenchTier:
        """The autoscaler's tier surface over in-thread daemons: the
        pressure signal is offered load per live member (deterministic —
        the resize trace must not ride CPU timing), everything else is
        the production path (real spawn, real drain, real routers)."""

        def __init__(self, start):
            self.daemons, self.servers = [], []
            self.addrs, self.ids = [], []
            self.routers, self.tenants = [], []
            self._next = 0
            self.offered = 0.0
            self.remapped = 0
            for _ in range(start):
                self._spawn()

        def _spawn(self):
            daemon = service.SolverDaemon(gateway=fleetmod.FleetGateway(
                max_depth=8, max_batch=4, batch_window=0.002,
            ))
            srv = service.serve(0, daemon=daemon)
            self.daemons.append(daemon)
            self.servers.append(srv)
            self.addrs.append(f"127.0.0.1:{srv.server_address[1]}")
            self.ids.append(str(self._next))
            self._next += 1
            return len(self.ids) - 1

        def client(self, addr, mid, tenant):
            return remote.SolverClient(
                addr, timeout=600, member=mid, tenant=tenant,
                wire_mode="delta",
            )

        def observe(self):
            members = [MemberSignal(member=mid) for mid in self.ids]
            pressure = self.offered / (len(self.ids) * member_capacity)
            return TierSignals(
                members=members, pressure=pressure, storm=False
            )

        def _winners(self):
            out = {}
            for router in self.routers:
                with router._lock:
                    if router._lineage_key is not None:
                        out[router] = router._lineage_winner_locked()
            return out

        def _count_remaps(self, before):
            for router, winner in before.items():
                with router._lock:
                    if router._lineage_winner_locked() != winner:
                        self.remapped += 1

        def scale_up(self):
            before = self._winners()
            idx = self._spawn()
            for tenant, router in zip(self.tenants, self.routers):
                router.add_member(
                    self.client(self.addrs[idx], self.ids[idx], tenant),
                    member_id=self.ids[idx],
                )
            self._count_remaps(before)

        def scale_down(self, index):
            before = self._winners()
            for router in self.routers:
                router.remove_member(index)
            daemon = self.daemons.pop(index)
            srv = self.servers.pop(index)
            self.addrs.pop(index)
            self.ids.pop(index)
            # the faultless retirement path: flush queued tickets (503,
            # degrade-without-charge on the client), then the socket
            daemon.drain()
            srv.shutdown()
            srv.server_close()
            self._count_remaps(before)

        def set_rung(self, rung):
            for daemon in self.daemons:
                daemon.set_brownout(rung)

        def stop(self):
            for srv in self.servers:
                srv.shutdown()
                srv.server_close()

    def counter_total(counter):
        return sum(counter.values.values())

    def run_tier(autoscale):
        fall0 = counter_total(m.SOLVER_RPC_FALLBACKS)
        miss0 = m.SOLVER_RPC_FAILURES.value({"cause": "segment_miss"})
        tier = BenchTier(1 if autoscale else max_members)
        scheds = {}
        try:
            for tenant, tpools, tits, _tp in tenant_problems:
                members = [
                    tier.client(addr, mid, tenant)
                    for addr, mid in zip(tier.addrs, tier.ids)
                ]
                router = remote.FleetRouter(members, tenant=tenant)
                tier.routers.append(router)
                tier.tenants.append(tenant)
                scheds[tenant] = remote.RemoteScheduler(
                    router, tpools, tits,
                    device_scheduler_opts={"max_slots": 256},
                    verify=not NO_VERIFY,
                )
            autoscaler = TierAutoscaler(
                tier, 1, max_members,
                up_stable=1, down_stable=2,
                # 0.45: a lone quiet tenant must sit in the scale-down
                # band at EVERY size down to 2 members (1/(2*capacity)),
                # or the descent stalls halfway
                down_pressure=0.45,
                up_cooldown_s=0.0, down_cooldown_s=0.0,
                time_fn=lambda: vnow[0],
            ) if autoscale else None
            # both runs judge queue waits only AFTER this many ticks —
            # the window the autoscaled tier needs to reach max size
            ramp = max_members - 1
            member_seconds = 0.0
            sizes = []
            for tick in range(surge_ticks + quiet_ticks):
                surge = tick < surge_ticks
                active = (
                    tenant_problems if surge
                    else tenant_problems[tick % n_tenants:][:1]
                )
                tier.offered = float(len(active))
                vnow[0] += tick_s
                if autoscaler is not None:
                    autoscaler.step()
                threads = [
                    threading.Thread(
                        target=lambda te=tenant, tp=tpods: scheds[te]
                        .solve(copy.deepcopy(tp)),
                        daemon=True,
                    )
                    for tenant, _tp_, _ti, tpods in active
                ]
                for th in threads:
                    th.start()
                for th in threads:
                    th.join()
                member_seconds += len(tier.ids) * tick_s
                sizes.append(len(tier.ids))
                if tick == ramp - 1:
                    for daemon in tier.daemons:
                        daemon.gateway.snapshot(reset=True)
            p99 = {}
            for daemon in tier.daemons:
                snap = daemon.gateway.snapshot()
                for tenant, row in snap["tenants"].items():
                    p99[tenant] = max(
                        p99.get(tenant, 0.0), row["wait_p99_s"]
                    )
            open_breakers = sum(
                1 for router in tier.routers for c in router.members
                if c.breaker.state != remote.STATE_CLOSED
            )
            return {
                "sizes": sizes,
                "member_seconds": member_seconds,
                "p99_by_tenant": {
                    t: round(v, 4) for t, v in sorted(p99.items())
                },
                "p99_max_s": round(max(p99.values() or [0.0]), 4),
                "remapped_lineages": tier.remapped,
                "miss_rounds": int(
                    m.SOLVER_RPC_FAILURES.value(
                        {"cause": "segment_miss"}
                    ) - miss0
                ),
                "fallbacks": int(
                    counter_total(m.SOLVER_RPC_FALLBACKS) - fall0
                ),
                "open_breakers": open_breakers,
                "decisions": (
                    [list(d) for d in autoscaler.decisions]
                    if autoscaler else None
                ),
            }
        finally:
            tier.stop()

    auto = run_tier(autoscale=True)
    fixed = run_tier(autoscale=False)

    # -- phase 2: the brownout ladder at forced max-scale overload ---------

    def brownout_ladder():
        tier = BenchTier(1)
        tenant, tpools, tits, tpods = tenant_problems[0]
        try:
            tier.routers.append(remote.FleetRouter(
                [tier.client(tier.addrs[0], tier.ids[0], tenant)],
                tenant=tenant,
            ))
            tier.tenants.append(tenant)
            sched_relax = remote.RemoteScheduler(
                tier.routers[0], tpools, tits,
                device_scheduler_opts={
                    "max_slots": 256, "solver_mode": "relax",
                },
                verify=not NO_VERIFY,
            )
            autoscaler = TierAutoscaler(
                tier, 1, 1,
                up_stable=1, down_stable=10 ** 6,
                rung_up_stable=1, rung_down_stable=1,
                time_fn=lambda: vnow[0],
            )
            daemon = tier.daemons[0]
            base_window = daemon.gateway.batch_window
            base_depth = daemon.gateway.max_depth
            rej0 = counter_total(m.SOLVER_RESULT_REJECTED)
            served0 = counter_total(m.SOLVERD_BROWNOUT_SERVED)
            rungs = []
            tier.offered = 100.0  # over budget, nowhere left to scale
            for _ in range(3):
                vnow[0] += tick_s
                autoscaler.step()
                rungs.append(daemon.brownout_rung)
            at_max = {
                "window_s": daemon.gateway.batch_window,
                "depth": daemon.gateway.max_depth,
            }
            # rung >= 1: a relax request is served in FFD mode (anytime
            # answer, verification still on)
            res = sched_relax.solve(copy.deepcopy(tpods))
            served = int(
                counter_total(m.SOLVERD_BROWNOUT_SERVED) - served0
            )
            tier.offered = 0.0
            for _ in range(3):
                vnow[0] += tick_s
                autoscaler.step()
                rungs.append(daemon.brownout_rung)
            order = [
                int(arg) for _ts, action, arg in autoscaler.decisions
                if action in ("rung_up", "rung_down")
            ]
            restored = (
                daemon.gateway.batch_window == base_window
                and daemon.gateway.max_depth == base_depth
            )
            rejections = int(
                counter_total(m.SOLVER_RESULT_REJECTED) - rej0
            )
            return {
                "rungs": rungs,
                "rung_order": order,
                "relax_served_as_ffd": served,
                "relax_scheduled": bool(res.all_pods_scheduled()),
                "window_at_max_s": round(at_max["window_s"], 4),
                "depth_at_max": at_max["depth"],
                "base_window_s": round(base_window, 4),
                "base_depth": base_depth,
                "restored": bool(restored),
                "verifier_rejections": rejections,
                "brownout_order_ok": bool(
                    order == [1, 2, 3, 2, 1, 0]
                    and served > 0
                    and res.all_pods_scheduled()
                    and at_max["window_s"] > base_window
                    and at_max["depth"] < base_depth
                    and restored
                    and rejections == 0
                ),
            }
        finally:
            tier.stop()

    ladder = brownout_ladder()

    saving = 1.0 - auto["member_seconds"] / max(
        fixed["member_seconds"], 1e-9
    )
    p99_ok = auto["p99_max_s"] <= fixed["p99_max_s"] + 0.05
    resize_cost_ok = bool(
        auto["miss_rounds"] == 0
        and auto["fallbacks"] == 0
        and auto["open_breakers"] == 0
        and fixed["fallbacks"] == 0
    )
    return {
        "tenants": n_tenants,
        "pods_per_tenant": n_pods,
        "surge_ticks": surge_ticks,
        "quiet_ticks": quiet_ticks,
        "tick_s": tick_s,
        "max_members": max_members,
        "autoscaled": auto,
        "fixed": fixed,
        "member_seconds_saving_pct": round(100.0 * saving, 1),
        # structural: the size trace rides the deterministic policy
        "saving_ok": bool(saving >= 0.30),
        "p99_ok": bool(p99_ok),
        "resize_cost_ok": resize_cost_ok,
        "brownout": ladder,
        "elastic_ok": bool(
            saving >= 0.30
            and p99_ok
            and resize_cost_ok
            and ladder["brownout_order_ok"]
        ),
    }


def _restart_probe() -> None:
    """Child mode: a FRESH process (persistent compile cache on disk warm
    from the parent's solves) boots a DeviceScheduler, pre-warms the shape
    buckets, and times its first real 50k solve — the restart path
    (VERDICT r4 item 4). Prints one JSON line for the parent."""
    from karpenter_core_tpu.utils.jaxenv import enable_persistent_compile_cache

    enable_persistent_compile_cache()
    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.models.provisioner import DeviceScheduler

    pods = _plain_pods(N_PODS)
    catalog = bench_catalog(N_TYPES)
    t0 = time.perf_counter()
    sched = DeviceScheduler(
        [_pool()], {"default": list(catalog)}, max_slots=1024
    )
    sched.prewarm()
    prewarm_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    res = sched.solve(pods)
    first = time.perf_counter() - t0
    assert res.all_pods_scheduled()
    print(json.dumps({
        "prewarm_s": round(prewarm_s, 3),
        "restart_cold_s": round(first, 3),
    }))


def _run_restart_probe() -> dict:
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--restart-probe"],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "BENCH_PODS": str(N_PODS),
                 "BENCH_TYPES": str(N_TYPES)},
        )
    except subprocess.TimeoutExpired:
        # degrade like other child failures — the already-measured configs
        # must still reach the JSON line
        return {"error": "restart probe exceeded 600s"}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except (ValueError, TypeError):
            continue
    return {"error": proc.stderr.strip()[-300:] or "no output"}


def _twin_bench(scale: str = "full"):
    """cfg14_twin: closed-loop macro outcomes over virtual time (ISSUE
    15). The twin IS the judge here — per scenario it reports the ledger
    ($-cost integral, SLO percentiles per workload class, preemption
    burn, tier utilization) plus the wall<->virtual compression, and the
    gates are outcome gates: no invariant violations anywhere, no greedy
    fallbacks on the clean run."""
    from karpenter_core_tpu.twin import (
        FleetFault,
        Scenario,
        Storm,
        WorkloadWave,
    )
    from karpenter_core_tpu.twin.harness import run_scenario

    if scale == "fast":
        counts = dict(serving=40, training=32, batch=60)
        duration, tick = 300.0, 30.0
    else:
        counts = dict(serving=1200, training=800, batch=2400)
        duration, tick = 7200.0, 300.0

    def waves():
        return (
            WorkloadWave(at=0.0, cluster=0, kind="serving",
                         count=counts["serving"], min_available=4),
            WorkloadWave(at=0.0, cluster=1, kind="training",
                         count=counts["training"], gang_size=8,
                         priority=100),
            WorkloadWave(at=tick, cluster=0, kind="batch",
                         count=counts["batch"], lifetime=duration / 2),
            WorkloadWave(at=tick * 2, cluster=1, kind="serving",
                         count=counts["serving"] // 2, min_available=2),
        )

    storm = Storm(start=tick, duration=tick * 3, cluster=0, head=6)
    rates = {
        "kube.create.conflict": 0.05,
        "kube.update.conflict": 0.04,
        "kube.bind.conflict": 0.04,
        "cloud.create.insufficient_capacity": 0.03,
    }
    scenarios = {
        "clean": Scenario(
            seed=3, clusters=2, duration=duration, tick=tick,
            solver="greedy", waves=waves(),
        ),
        "fault_storm": Scenario(
            seed=5, clusters=2, duration=duration, tick=tick,
            solver="greedy", waves=waves(), rates=rates, storms=(storm,),
        ),
    }
    if scale != "fast":
        # the fleet scenario runs the REAL solve tier (in-thread solverd
        # members behind each operator's router) under fleet faults
        scenarios["fleet"] = Scenario(
            seed=7, clusters=2, duration=1800.0, tick=60.0,
            solver="tpu", fleet=2, wire="delta",
            waves=(
                WorkloadWave(at=0.0, cluster=0, kind="serving", count=16,
                             min_available=2),
                WorkloadWave(at=60.0, cluster=1, kind="batch", count=16),
                WorkloadWave(at=600.0, cluster=0, kind="batch", count=12),
            ),
            fleet_faults=(
                FleetFault(at=300.0, kind="amnesia", member=0),
                FleetFault(at=600.0, kind="murder", member=1),
                FleetFault(at=900.0, kind="partition", cluster=0,
                           duration=120.0),
            ),
        )

    out = {}
    for name in scenarios:
        t0 = time.perf_counter()
        result = run_scenario(scenarios[name])
        wall = time.perf_counter() - t0
        ledger = result.ledger.encode()
        out[name] = {
            "wall_s": round(wall, 3),
            "virtual_s": ledger["virtual_seconds"],
            "compression_x": round(ledger["virtual_seconds"] / wall, 1),
            "pods_bound": sum(c["n"] for c in ledger["slo"].values()),
            "cost_dollar_hours": round(
                sum(ledger["cost_dollar_hours"].values()), 6
            ),
            "peak_nodes": ledger["peak_nodes"],
            "slo": ledger["slo"],
            "slo_misses": ledger["slo_misses"],
            "preemption_evictions": ledger["preemption_evictions"],
            "utilization": ledger["utilization"],
            "invariant_violations": len(result.violations),
            "rpc_fallbacks": result.counters["rpc_fallbacks"],
            "verifier_rejections": result.counters["result_rejected"],
        }
    return {
        **out,
        "twin_ok": all(
            phase["invariant_violations"] == 0
            and phase["verifier_rejections"] == 0
            for phase in out.values()
        ) and out["clean"]["rpc_fallbacks"] == 0,
    }


def main():
    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.api.objects import Taint
    from karpenter_core_tpu.utils.jaxenv import enable_persistent_compile_cache

    # cold solves amortize across driver runs via the on-disk XLA cache
    enable_persistent_compile_cache()

    # --configs cfgA,cfgB: run only the named secondary configs (prefix
    # match, e.g. "cfg12" selects cfg12_relax). The primary always runs —
    # it is the headline metric every round reports. Lets a round target
    # the configs it is landing (BENCH_r06: cfg8-cfg12) without paying
    # for the whole suite.
    only = None
    if "--configs" in sys.argv:
        i = sys.argv.index("--configs")
        if i + 1 >= len(sys.argv):
            raise SystemExit("--configs needs a comma-separated value")
        only = [c.strip() for c in sys.argv[i + 1].split(",") if c.strip()]
        known = (
            "cfg1_5k400", "cfg2_masked", "cfg3_topology", "cfg4_consol",
            "cfg5_sidecar", "cfg6_ice_storm", "cfg7_fleet", "cfg8_multidev",
            "cfg9_verified", "cfg10_batch", "cfg11_gangs", "cfg12_relax",
            "cfg13_delta", "cfg14_twin", "cfg15_incremental",
            "cfg16_elastic", "cfg17_pallas", "cfg18_topoaware",
            "shape_churn", "restart",
        )
        bogus = [
            o for o in only
            if not any(k == o or k.startswith(o) for k in known)
        ]
        if bogus:
            # a typo'd name silently filtering everything out would look
            # like an intentional primary-only round
            raise SystemExit(f"--configs: unknown config name(s) {bogus}")

    def sel(name: str) -> bool:
        return only is None or any(
            name == o or name.startswith(o) for o in only
        )

    catalog = bench_catalog(N_TYPES)

    primary = _solve_bench(
        _plain_pods(N_PODS), [_pool()], catalog, parity=not FAST,
        repeats=7,  # the budget guard reads this p50; extra samples damp
        # tunnel-latency jitter on the shared chip
    )
    detail = {"primary": primary}

    if not FAST and sel("cfg1_5k400"):
        detail["cfg1_5k400"] = _solve_bench(
            _plain_pods(5000), [_pool()], bench_catalog(400)
        )
    if not FAST:
        from karpenter_core_tpu.api import labels as L
        from karpenter_core_tpu.api.objects import NodeSelectorRequirement

        masked_pools = [
            _pool("default"),
            _pool(
                "batch",
                taints=[Taint(key="batch", value="", effect="NoSchedule")],
                # pool-requirement mask path: the batch pool only offers
                # amd64/linux instance types
                requirements=[
                    NodeSelectorRequirement(L.LABEL_ARCH, "In", ("amd64",)),
                    NodeSelectorRequirement(L.LABEL_OS, "In", ("linux",)),
                ],
            ),
        ]
        masked_pools[1].spec.template.labels["pool"] = "batch"
        if sel("cfg2_masked"):
            detail["cfg2_masked"] = _solve_bench(
                _masked_pods(N_PODS), masked_pools, catalog
            )
        if sel("cfg3_topology"):
            detail["cfg3_topology"] = _solve_bench(
                _topology_pods(5000),
                [_pool()],
                bench_catalog(400),
                max_slots=2048,
                repeats=5,
            )
            # 50k-scale topology (VERDICT r5 item 1): the full diverse
            # mix at the north-star pod count, parity vs the greedy oracle
            detail["cfg3_topology_50k"] = _solve_bench(
                _topology_pods(50000, n_deploys=40),
                [_pool()],
                bench_catalog(N_TYPES),
                max_slots=4096,
                repeats=3,
            )
        # cfg9_verified: the primary config WITH verification (the
        # production default) — the verifier pass is a phase of every
        # solve above; here its cost is pinned against the solve p50 and
        # judged against the <5% budget (vs cfg1's p50, the reference
        # point the acceptance names, and vs the primary's own p50)
        if sel("cfg9_verified"):
            detail["cfg9_verified"] = _verified_summary(
                primary, detail.get("cfg1_5k400")
            )
        if sel("shape_churn"):
            detail["shape_churn"] = _shape_churn_bench()
        if sel("cfg4_consol"):
            detail["cfg4_consol"] = _consolidation_bench()
        if sel("cfg5_sidecar"):
            detail["cfg5_sidecar"] = _sidecar_bench()
        if sel("cfg6_ice_storm"):
            detail["cfg6_ice_storm"] = _ice_storm_bench()
        if sel("cfg7_fleet"):
            detail["cfg7_fleet"] = _fleet_bench()
        if sel("cfg8_multidev"):
            detail["cfg8_multidev"] = _multidev_bench()
        if sel("cfg10_batch"):
            detail["cfg10_batch"] = _batch_bench()
        if sel("cfg11_gangs"):
            cfg1 = detail.get("cfg1_5k400")
            detail["cfg11_gangs"] = _gangs_bench(
                # scale to the round's pod knob on sub-accelerator runs;
                # a default (50k-pod) round keeps the classic 20k shape
                n_pods=min(20000, max(N_PODS, 1000)),
                cfg1_p50=cfg1["p50_solve_s"] if cfg1 else None,
            )
        if sel("cfg12_relax"):
            detail["cfg12_relax"] = _relax_bench(
                n_pods=min(5000, max(N_PODS, 500))
            )
        if sel("cfg13_delta"):
            detail["cfg13_delta"] = _delta_bench(
                n_pods=min(2000, max(N_PODS, 400)),
                n_nodes=min(600, max(N_PODS // 3, 100)),
            )
        if sel("cfg14_twin"):
            detail["cfg14_twin"] = _twin_bench()
        if sel("cfg15_incremental"):
            detail["cfg15_incremental"] = _incremental_bench(
                n_pods=min(2000, max(N_PODS, 400)),
                n_nodes=min(600, max(N_PODS // 3, 100)),
            )
        if sel("cfg16_elastic"):
            detail["cfg16_elastic"] = _elastic_bench()
        if sel("cfg17_pallas"):
            detail["cfg17_pallas"] = _pallas_bench()
        if sel("cfg18_topoaware"):
            detail["cfg18_topoaware"] = _topoaware_bench()
        if sel("restart"):
            detail["restart"] = _run_restart_probe()
    else:
        # tier-1 fast-bench smoke: a tiny cfg10 proves the coalescer +
        # vmapped batch path end-to-end (serialized-vs-batched schema
        # included) without the full 32-tenant cost, and a tiny cfg11
        # proves the gangsched path (preemption claims + gang atomicity)
        # the same way
        detail["cfg10_batch"] = _batch_bench(
            n_tenants=4, n_pods=24, n_types=12, repeats=2
        )
        detail["cfg11_gangs"] = _gangs_bench(
            n_pods=200, n_existing=4, repeats=2,
            cfg1_p50=primary["p50_solve_s"],
        )
        # ... and a small cfg12 proves the relaxsolve backend end-to-end
        # (both modes, node/cost delta schema, verdict-cache warm path).
        # 400 pods is the smallest size where the relax win is structural
        # on BOTH shapes (below it the topology host floor dominates the
        # capacity classes and the scored fallback correctly keeps FFD)
        detail["cfg12_relax"] = _relax_bench(n_pods=400, repeats=2)
        # ... and a tiny cfg13 proves the delta wire (manifest path,
        # result parity, the byte ratio schema) + the fleet router at
        # 1-vs-2 sidecars; the 10% byte gate is judged at full scale
        # (a tiny snapshot has too little stable problem half)
        detail["cfg13_delta"] = _delta_bench(
            n_pods=96, n_nodes=48, n_types=16, rounds=2,
            fleet_tenants=3, fleet_rounds=2, fleet_sizes=(1, 2),
        )
        # ... and a tiny cfg14 proves the closed-loop digital twin end to
        # end (clean + fault-storm scenarios, ledger schema, the
        # zero-violations / zero-fallbacks gates) at smoke scale
        detail["cfg14_twin"] = _twin_bench(scale="fast")
        # ... and a tiny cfg15 proves the incremental re-solve engine
        # end to end (warm/partial replays, node parity, the rejection
        # gates); the 5x p50 gate is judged at full scale — a tiny
        # fresh solve costs ~nothing to beat
        detail["cfg15_incremental"] = _incremental_bench(
            n_pods=160, n_nodes=24, n_types=16, churn=0.05, rounds=3,
        )
        # ... and a tiny cfg16 proves the elastic tier end to end (the
        # autoscaled-vs-fixed member-seconds economics, the resize-cost
        # audit, the brownout ladder firing and clearing in order); the
        # p99 comparison is judged at full scale
        detail["cfg16_elastic"] = _elastic_bench(
            n_tenants=3, n_types=12, n_pods=12,
            surge_ticks=4, quiet_ticks=8, max_members=3,
        )
        # ... and a tiny cfg17 proves the pallas kernel seam end to end
        # (both backends on both shapes, the byte-parity and fetch-
        # window-parity gates); the <0.3s / halved-p50 latency verdicts
        # are judged on the accelerator round
        # (24 types is the floor: bench_catalog(16) tops out at 1 cpu
        # and can't host the largest _plain_pods shape)
        detail["cfg17_pallas"] = _pallas_bench(
            n_pods=120, n_types=24, topo_pods=60, topo_types=24,
            max_slots=128, topo_slots=128, repeats=2,
        )
        # ... and a tiny cfg18 proves the topology-aware gang placement
        # end to end (aware-vs-blind on a racked 2-zone fleet: strictly
        # fewer intra-gang hops at equal-or-better node count, the hard
        # max-hops bound never provably exceeded); the latency ratio is
        # judged at full scale
        detail["cfg18_topoaware"] = _topoaware_bench(
            n_gangs=3, n_plain=60, repeats=2,
        )

    pods_per_sec = primary["pods_per_sec"]
    budget_ok = primary["p50_solve_s"] <= 1.0
    print(
        json.dumps(
            {
                "metric": f"solve_throughput_{N_PODS}pods_{N_TYPES}types",
                "value": pods_per_sec,
                "unit": "pods/sec",
                "vs_baseline": round(pods_per_sec / 100.0, 2),
                "budget_ok": budget_ok,
                # the escape hatch's use is part of the record: a run
                # without verification is not comparable to one with it
                "verification": not NO_VERIFY,
                # a filtered round (--configs) is not comparable to a
                # full one either — record what was selected
                "configs": only,
                "detail": detail,
            }
        )
    )
    if not budget_ok:
        # enforced floor, scheduling_benchmark_test.go:53 pattern: the JSON
        # line above is still emitted; the rc flags the regression
        raise SystemExit(1)


def _lint_report():
    """``bench.py --lint``: run graftlint over the tree and report per-rule
    wall time as one JSON line (same contract as the solve benches), so the
    lint pass's cost is tracked alongside kernel perf as the tree grows."""
    import sys

    t0 = time.perf_counter()
    from tools.graftlint import run as lint_run
    from tools.graftlint.engine import CACHE_PATH, LINT_BUDGET_SECONDS

    # the incremental cache is part of the measured contract: a cold CI
    # run reports misses, a warm editor-loop run reports the hit rate the
    # LINT_BUDGET_SECONDS trajectory actually rides on
    result = lint_run(["karpenter_core_tpu"], cache_path=CACHE_PATH)
    total = time.perf_counter() - t0
    for f, _src in result.new:
        # surface the actual violations (stderr keeps the stdout contract
        # of exactly one JSON line)
        print(f.render(), file=sys.stderr)
    # family labels ride the timing JSON so a dashboard reads "rangecheck
    # got slower", not "GL6xx got slower"
    family_names = {
        "GL1xx": "jaxpurity", "GL2xx": "determinism", "GL3xx": "concurrency",
        "GL4xx": "parity", "GL5xx": "shardcheck", "GL6xx": "rangecheck",
        "GL7xx": "lockgraph", "GL000": "suppression-hygiene",
    }
    family_seconds: dict = {}
    for rid, dt in result.rule_seconds.items():
        fam = rid[:3] + "xx" if rid != "GL000" else "GL000"
        family_seconds[fam] = family_seconds.get(fam, 0.0) + dt
    scanned = result.cache_hits + result.cache_misses
    print(
        json.dumps(
            {
                "metric": "graftlint_wall_seconds",
                "value": round(total, 4),
                "unit": "s",
                "budget_ok": total < LINT_BUDGET_SECONDS,
                "detail": {
                    "files": result.files,
                    "new_findings": len(result.new),
                    "baselined": len(result.baselined),
                    "suppressed": len(result.suppressed),
                    "rule_seconds": {
                        rid: round(dt, 4)
                        for rid, dt in sorted(result.rule_seconds.items())
                    },
                    "family_seconds": {
                        fam: round(dt, 4)
                        for fam, dt in sorted(family_seconds.items())
                    },
                    "family_names": {
                        fam: family_names.get(fam, fam)
                        for fam in sorted(family_seconds)
                    },
                    "cache": {
                        "hits": result.cache_hits,
                        "misses": result.cache_misses,
                        "hit_rate": round(result.cache_hits / scanned, 3)
                        if scanned
                        else 0.0,
                    },
                },
            }
        )
    )
    if result.new or total >= LINT_BUDGET_SECONDS:
        raise SystemExit(1)


if __name__ == "__main__":
    import sys

    if "--lint" in sys.argv:
        _lint_report()
    elif "--restart-probe" in sys.argv:
        _restart_probe()
    elif "--multidev-probe" in sys.argv:
        _multidev_probe()
    else:
        main()
