"""Round bench: device-solver scheduling throughput on the kwok catalog.

Scenario = BASELINE.json config 1 scaled to this round: cpu/mem-request-only
pending pods, single NodePool, kwok instance catalog (reference harness:
scheduling_benchmark_test.go:75-95 grid, 100 pods/sec CI floor at :53).
Prints ONE JSON line; vs_baseline is pods/sec over the reference's enforced
100 pods/sec floor.

Runs on whatever backend JAX selects (real TPU chip under the driver;
force CPU with JAX_PLATFORM_NAME=cpu).
"""
from __future__ import annotations

import json
import os
import time

N_PODS = int(os.environ.get("BENCH_PODS", "5000"))
N_TYPES = int(os.environ.get("BENCH_TYPES", "400"))
GIB = 2.0**30


def build():
    from karpenter_core_tpu.api.objects import ObjectMeta, Pod
    from karpenter_core_tpu.api.nodepool import NodePool, NodePoolSpec
    from karpenter_core_tpu.cloudprovider.kwok import bench_catalog
    from karpenter_core_tpu.models.provisioner import DeviceScheduler

    catalog = bench_catalog(N_TYPES)
    pool = NodePool(metadata=ObjectMeta(name="default"))
    pool.spec = NodePoolSpec()
    # diverse cpu/mem shapes -> many pod equivalence classes (the FFD scan
    # length); mirrors the benchmark's diverse pod mix minus topology
    pods = [
        Pod(
            metadata=ObjectMeta(name=f"p{i}"),
            resource_requests={
                "cpu": 0.1 * (1 + i % 16),
                "memory": 0.25 * GIB * (1 + i % 12),
            },
        )
        for i in range(N_PODS)
    ]
    sched = DeviceScheduler([pool], {"default": catalog}, max_slots=1024)
    return sched, pods


def main():
    sched, pods = build()

    t0 = time.perf_counter()
    res = sched.solve(pods)  # cold: includes jit compile
    cold = time.perf_counter() - t0
    assert res.all_pods_scheduled(), list(res.pod_errors.items())[:3]

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        res = sched.solve(pods)
        times.append(time.perf_counter() - t0)
    p50 = sorted(times)[len(times) // 2]
    pods_per_sec = N_PODS / p50

    print(
        json.dumps(
            {
                "metric": f"solve_throughput_{N_PODS}pods_{N_TYPES}types",
                "value": round(pods_per_sec, 1),
                "unit": "pods/sec",
                "vs_baseline": round(pods_per_sec / 100.0, 2),
                "detail": {
                    "p50_solve_s": round(p50, 3),
                    "cold_solve_s": round(cold, 3),
                    "nodes": res.node_count(),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
