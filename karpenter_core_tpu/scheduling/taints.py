"""Taint / toleration checking (reference: pkg/scheduling/taints.go:35-59)."""
from __future__ import annotations

from typing import Iterable, List

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import (
    TAINT_EFFECT_NO_EXECUTE,
    TAINT_EFFECT_NO_SCHEDULE,
    Pod,
    Taint,
)

DISRUPTED_NO_SCHEDULE_TAINT = Taint(
    key=apilabels.DISRUPTED_TAINT_KEY, effect=TAINT_EFFECT_NO_SCHEDULE
)
UNREGISTERED_NO_EXECUTE_TAINT = Taint(
    key=apilabels.UNREGISTERED_TAINT_KEY, effect=TAINT_EFFECT_NO_EXECUTE
)

# Taints expected on a node while it is initializing; ignored on uninitialized
# managed nodes (reference: pkg/scheduling/taints.go:35-41).
KNOWN_EPHEMERAL_TAINTS = (
    Taint(key="node.kubernetes.io/not-ready", effect=TAINT_EFFECT_NO_SCHEDULE),
    Taint(key="node.kubernetes.io/unreachable", effect=TAINT_EFFECT_NO_SCHEDULE),
    Taint(
        key="node.cloudprovider.kubernetes.io/uninitialized",
        effect=TAINT_EFFECT_NO_SCHEDULE,
        value="true",
    ),
    UNREGISTERED_NO_EXECUTE_TAINT,
)


class Taints(list):
    """list[Taint] with toleration checking."""

    def tolerates(self, pod: Pod) -> List[str]:
        """Error strings for every taint the pod does not tolerate
        (taints.go:46-59)."""
        errs = []
        for taint in self:
            if not any(t.tolerates(taint) for t in pod.tolerations):
                errs.append(f"did not tolerate {taint}")
        return errs

    def merge(self, other: Iterable[Taint]) -> "Taints":
        out = Taints(self)
        for taint in other:
            if not any(
                t.key == taint.key and t.effect == taint.effect for t in out
            ):
                out.append(taint)
        return out
