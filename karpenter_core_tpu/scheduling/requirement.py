"""Compressed label-value set algebra.

Host-side twin of the reference's ``scheduling.Requirement``
(reference: pkg/scheduling/requirement.go:33-242): a set over label values
represented either explicitly (``In``) or as a complement set (``NotIn`` /
``Exists``) with optional integer Gt/Lt bounds and MinValues flexibility.

On device, each Requirement lowers to a boolean mask over the solve's
closed-world value vocabulary (solver/vocab.py); Intersection becomes AND,
complement becomes NOT. This class is the semantics oracle the device masks
are property-tested against.
"""
from __future__ import annotations

import sys
from typing import Iterable, Optional

from karpenter_core_tpu.api import labels as apilabels

MAX_LEN = sys.maxsize  # stand-in for Go's math.MaxInt64 set cardinality

# Operators (mirror corev1.NodeSelectorOperator)
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"

NEGATIVE_OPERATORS = frozenset({OP_NOT_IN, OP_DOES_NOT_EXIST})


def _within(value: str, greater_than: Optional[int], less_than: Optional[int]) -> bool:
    if greater_than is None and less_than is None:
        return True
    try:
        iv = int(value)
    except ValueError:
        return False
    if greater_than is not None and iv <= greater_than:
        return False
    if less_than is not None and iv >= less_than:
        return False
    return True


class Requirement:
    """A set of allowed values for one label key."""

    __slots__ = ("key", "complement", "values", "greater_than", "less_than", "min_values")

    def __init__(
        self,
        key: str,
        *,
        complement: bool = False,
        values: Iterable[str] = (),
        greater_than: Optional[int] = None,
        less_than: Optional[int] = None,
        min_values: Optional[int] = None,
    ):
        self.key = key
        self.complement = complement
        self.values = set(values)
        self.greater_than = greater_than
        self.less_than = less_than
        self.min_values = min_values

    # -- constructors ------------------------------------------------------

    @classmethod
    def new(
        cls,
        key: str,
        operator: str,
        values: Iterable[str] = (),
        min_values: Optional[int] = None,
    ) -> "Requirement":
        """Mirror of NewRequirementWithFlexibility (requirement.go:43-85)."""
        key = apilabels.NORMALIZED_LABELS.get(key, key)
        values = list(values)
        if operator == OP_IN:
            return cls(key, values=values, min_values=min_values)
        r = cls(key, complement=True, min_values=min_values)
        if operator == OP_DOES_NOT_EXIST:
            r.complement = False
        if operator == OP_NOT_IN:
            r.values.update(values)
        if operator == OP_GT:
            r.greater_than = int(values[0])
        if operator == OP_LT:
            r.less_than = int(values[0])
        return r

    # -- algebra -----------------------------------------------------------

    def intersection(self, other: "Requirement") -> "Requirement":
        """Mirror of Requirement.Intersection (requirement.go:155-188)."""
        complement = self.complement and other.complement
        greater_than = _max_opt(self.greater_than, other.greater_than)
        less_than = _min_opt(self.less_than, other.less_than)
        min_values = _max_opt(self.min_values, other.min_values)
        if (
            greater_than is not None
            and less_than is not None
            and greater_than >= less_than
        ):
            return Requirement.new(self.key, OP_DOES_NOT_EXIST, min_values=min_values)

        if self.complement and other.complement:
            values = self.values | other.values
        elif self.complement and not other.complement:
            values = other.values - self.values
        elif not self.complement and other.complement:
            values = self.values - other.values
        else:
            values = self.values & other.values
        values = {v for v in values if _within(v, greater_than, less_than)}
        if not complement:
            greater_than, less_than = None, None
        return Requirement(
            self.key,
            complement=complement,
            values=values,
            greater_than=greater_than,
            less_than=less_than,
            min_values=min_values,
        )

    def has(self, value: str) -> bool:
        """True if the requirement allows the value (requirement.go:209-214)."""
        if self.complement:
            return value not in self.values and _within(
                value, self.greater_than, self.less_than
            )
        return value in self.values and _within(
            value, self.greater_than, self.less_than
        )

    def operator(self) -> str:
        """Mirror of Requirement.Operator (requirement.go:224-235)."""
        if self.complement:
            return OP_NOT_IN if self.length() < MAX_LEN else OP_EXISTS
        return OP_IN if self.length() > 0 else OP_DOES_NOT_EXIST

    def length(self) -> int:
        """Set cardinality with complement sets counted from MAX_LEN (requirement.go:237-242)."""
        if self.complement:
            return MAX_LEN - len(self.values)
        return len(self.values)

    def any_value(self) -> str:
        """A representative allowed value (requirement.go:190-204)."""
        op = self.operator()
        if op == OP_IN:
            return next(iter(sorted(self.values)))
        if op in (OP_NOT_IN, OP_EXISTS):
            lo = (self.greater_than + 1) if self.greater_than is not None else 0
            hi = self.less_than if self.less_than is not None else lo + (1 << 20)
            for candidate in range(lo, hi):
                if str(candidate) not in self.values:
                    return str(candidate)
        return ""

    def sorted_values(self) -> list:
        return sorted(self.values)

    def copy(self) -> "Requirement":
        return Requirement(
            self.key,
            complement=self.complement,
            values=set(self.values),
            greater_than=self.greater_than,
            less_than=self.less_than,
            min_values=self.min_values,
        )

    # -- dunder ------------------------------------------------------------

    def __eq__(self, other) -> bool:
        if not isinstance(other, Requirement):
            return NotImplemented
        return (
            self.key == other.key
            and self.complement == other.complement
            and self.values == other.values
            and self.greater_than == other.greater_than
            and self.less_than == other.less_than
            and self.min_values == other.min_values
        )

    def __hash__(self):
        return hash(
            (
                self.key,
                self.complement,
                frozenset(self.values),
                self.greater_than,
                self.less_than,
                self.min_values,
            )
        )

    def __repr__(self) -> str:
        op = self.operator()
        if op in (OP_EXISTS, OP_DOES_NOT_EXIST):
            s = f"{self.key} {op}"
        else:
            vals = self.sorted_values()
            if len(vals) > 5:
                vals = vals[:5] + [f"and {len(vals) - 5} others"]
            s = f"{self.key} {op} {vals}"
        if self.greater_than is not None:
            s += f" >{self.greater_than}"
        if self.less_than is not None:
            s += f" <{self.less_than}"
        return s


def _max_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def _min_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)
