from karpenter_core_tpu.scheduling.requirement import Requirement  # noqa: F401
from karpenter_core_tpu.scheduling.requirements import Requirements  # noqa: F401
from karpenter_core_tpu.scheduling.taints import Taints, KNOWN_EPHEMERAL_TAINTS  # noqa: F401
