"""CSI attach-limit accounting per node
(reference: pkg/scheduling/volumeusage.go:44-229).

``Volumes`` maps csi-driver name → set of PVC keys (namespace/name); union
semantics dedupe shared (RWX) claims. ``VolumeUsage`` tracks one node's
mounted volumes against per-driver limits sourced from that node's CSINode.
``get_volumes`` resolves a pod's PVC-backed volumes to drivers the same way
the reference does: bound PV's csi driver first, else the storage class's
provisioner; unresolvable shapes are skipped, not errors
(volumeusage.go:82-150 GetVolumes/resolveDriver).
"""
from __future__ import annotations

from typing import Dict, Optional, Set

from karpenter_core_tpu.api.objects import (
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    StorageClass,
)

Volumes = Dict[str, Set[str]]  # driver -> {pvc keys}


def union(a: Volumes, b: Volumes) -> Volumes:
    out: Volumes = {k: set(v) for k, v in a.items()}
    for k, v in b.items():
        out.setdefault(k, set()).update(v)
    return out


def pvc_name_for(pod: Pod, volume) -> Optional[str]:
    """Ephemeral volumes materialize a PVC named <pod>-<volume>
    (volumeutil.GetPersistentVolumeClaim)."""
    if volume.ephemeral:
        return f"{pod.metadata.name}-{volume.name}"
    return volume.pvc_name


def get_volumes(kube, pod: Pod) -> Volumes:
    """Resolve the pod's PVC-backed volumes to {driver -> {pvc key}}.

    Missing PVCs are skipped (manually deleted; tracking must not wedge,
    volumeusage.go:88-93); non-CSI or unresolvable drivers are skipped."""
    out: Volumes = {}
    for vol in pod.volumes:
        claim_name = pvc_name_for(pod, vol)
        if claim_name is None:
            continue  # emptyDir / hostPath etc.
        pvc = kube.get(
            PersistentVolumeClaim, claim_name, pod.metadata.namespace
        )
        if pvc is None:
            continue
        driver = _resolve_driver(kube, pvc)
        if driver:
            out.setdefault(driver, set()).add(pvc.key())
    return out


def _resolve_driver(kube, pvc: PersistentVolumeClaim) -> str:
    """Bound PV's CSI driver wins; else the storage class provisioner
    (volumeusage.go:113-150 resolveDriver)."""
    if pvc.volume_name:
        pv = kube.get(PersistentVolume, pvc.volume_name)
        if pv is not None and pv.csi_driver:
            return pv.csi_driver
        return ""  # bound to a non-CSI volume: not limit-tracked
    if not pvc.storage_class_name:
        return ""
    sc = kube.get(StorageClass, pvc.storage_class_name)
    if sc is None:
        return ""
    return sc.provisioner


class VolumeUsage:
    """One node's volume usage vs its CSINode limits
    (volumeusage.go:183-229)."""

    def __init__(self):
        self.volumes: Volumes = {}
        self.limits: Dict[str, int] = {}

    def add_limit(self, driver: str, value: int) -> None:
        self.limits[driver] = value

    def exceeds_limits(self, vols: Volumes) -> Optional[str]:
        joined = union(self.volumes, vols)
        for driver, pvcs in joined.items():
            limit = self.limits.get(driver)
            if limit is not None and len(pvcs) > limit:
                return (
                    f"would exceed volume limit for {driver}, "
                    f"{len(pvcs)} > {limit}"
                )
        return None

    def add(self, vols: Volumes) -> None:
        self.volumes = union(self.volumes, vols)

    def copy(self) -> "VolumeUsage":
        out = VolumeUsage()
        out.limits = dict(self.limits)
        out.volumes = {k: set(v) for k, v in self.volumes.items()}
        return out
