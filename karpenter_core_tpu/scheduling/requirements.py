"""Keyed requirement sets and compatibility rules.

Host-side twin of the reference's ``scheduling.Requirements``
(reference: pkg/scheduling/requirements.go:36-304): a map from label key to
Requirement with intersect-on-add, plus the two compatibility relations the
scheduler is built on:

* ``compatible`` — custom (non-well-known) keys the incoming side constrains
  must be defined by the receiver (unless the incoming operator is negative),
  then ``intersects`` must hold (requirements.go:175-187).
* ``intersects`` — for every key both sides define, the intersection must be
  non-empty, except when both operators are negative (requirements.go:283-304).

On device this whole relation evaluates as per-key mask intersections
(ops/masks.py); these methods are the oracle for those kernels.
"""
from __future__ import annotations

from typing import Iterable, Optional

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import Pod
from karpenter_core_tpu.scheduling.requirement import (
    NEGATIVE_OPERATORS,
    OP_EXISTS,
    OP_IN,
    Requirement,
)


class Requirements(dict):
    """dict[str, Requirement] with reference Add/Compatible/Intersects semantics."""

    def __init__(self, reqs: Iterable[Requirement] = ()):
        super().__init__()
        self.add(*reqs)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_labels(cls, labels: dict) -> "Requirements":
        """NewLabelRequirements (requirements.go:53-59)."""
        return cls(
            Requirement.new(k, OP_IN, [v]) for k, v in labels.items()
        )

    @classmethod
    def from_node_selector_requirements(cls, reqs) -> "Requirements":
        """NewNodeSelectorRequirements: minValues deliberately dropped — only
        NodePools may introduce flexibility (requirements.go:38-44)."""
        return cls(Requirement.new(r.key, r.operator, r.values) for r in reqs)

    @classmethod
    def from_node_selector_requirements_with_min_values(cls, reqs) -> "Requirements":
        """NewNodeSelectorRequirementsWithMinValues — the NodePool path
        (requirements.go:46-52)."""
        return cls(
            Requirement.new(r.key, r.operator, r.values, min_values=r.min_values)
            for r in reqs
        )

    @classmethod
    def from_pod(cls, pod: Pod) -> "Requirements":
        """NewPodRequirements (requirements.go:62-110): node selector + first
        required node-affinity term, with the single heaviest preferred term
        folded in when no required terms exist."""
        return cls._pod_requirements(pod, include_preferred=True)

    @classmethod
    def from_pod_strict(cls, pod: Pod) -> "Requirements":
        """NewStrictPodRequirements: required terms only."""
        return cls._pod_requirements(pod, include_preferred=False)

    @classmethod
    def _pod_requirements(cls, pod: Pod, include_preferred: bool) -> "Requirements":
        requirements = cls.from_labels(pod.node_selector)
        # PVC-derived zone pins AND in unconditionally — relaxation only
        # mutates pod.affinity, so these survive by construction (the
        # reference ANDs them into every node-selector term instead,
        # volumetopology.go:68-72)
        if pod.volume_requirements:
            requirements.add(
                *cls.from_node_selector_requirements(
                    pod.volume_requirements
                ).values()
            )
        affinity = pod.affinity.node_affinity if pod.affinity else None
        if affinity is None:
            return requirements
        # The heaviest preferred term folds in unconditionally (the relaxation
        # loop unconstrains it later if unsatisfiable), then the first required
        # term intersects on top (requirements.go:90-110).
        if include_preferred and affinity.preferred:
            preferred = sorted(affinity.preferred, key=lambda t: -t.weight)
            requirements.add(
                *cls.from_node_selector_requirements(
                    preferred[0].preference.match_expressions
                ).values()
            )
        if affinity.required:
            requirements.add(
                *cls.from_node_selector_requirements(
                    affinity.required[0].match_expressions
                ).values()
            )
        return requirements

    # -- mutation ----------------------------------------------------------

    def add(self, *reqs: Requirement) -> None:
        """Intersect-on-collision (requirements.go:127-134)."""
        for req in reqs:
            existing = dict.get(self, req.key)
            if existing is not None:
                req = req.intersection(existing)
            self[req.key] = req

    # -- access ------------------------------------------------------------

    def get(self, key: str) -> Requirement:  # type: ignore[override]
        """Undefined keys read as Exists — allow-any (requirements.go:157-162)."""
        existing = dict.get(self, key)
        if existing is None:
            return Requirement.new(key, OP_EXISTS)
        return existing

    def keys_set(self) -> set:
        return set(self.keys())

    def has(self, key: str) -> bool:
        return key in self

    def has_min_values(self) -> bool:
        return any(r.min_values is not None for r in self.values())

    def copy(self) -> "Requirements":
        out = Requirements()
        for k, v in self.items():
            dict.__setitem__(out, k, v.copy())
        return out

    # -- relations ---------------------------------------------------------

    def compatible(
        self, incoming: "Requirements", allow_undefined: frozenset = frozenset()
    ) -> list:
        """Returns a list of error strings; empty means compatible
        (requirements.go:175-187)."""
        errs = []
        for key in incoming.keys_set() - allow_undefined:
            op = incoming.get(key).operator()
            if self.has(key) or op in NEGATIVE_OPERATORS:
                continue
            errs.append(f"label {key!r} does not have known values")
        errs.extend(self.intersects(incoming))
        return errs

    def is_compatible(
        self, incoming: "Requirements", allow_undefined: frozenset = frozenset()
    ) -> bool:
        return not self.compatible(incoming, allow_undefined)

    def intersects(self, incoming: "Requirements") -> list:
        """Overlap check on shared keys (requirements.go:283-304)."""
        errs = []
        for key in self.keys_set() & incoming.keys_set():
            existing = self.get(key)
            inc = incoming.get(key)
            if existing.intersection(inc).length() == 0:
                if (
                    inc.operator() in NEGATIVE_OPERATORS
                    and existing.operator() in NEGATIVE_OPERATORS
                ):
                    continue
                errs.append(f"key {key}, {inc!r} not in {existing!r}")
        return errs

    # -- output ------------------------------------------------------------

    def to_labels(self) -> dict:
        """Representative labels for keys the framework may inject itself —
        well-known labels are excluded because the cloud provider injects them
        (requirements.go Labels(), labels.go IsRestrictedNodeLabel:118-131)."""
        out = {}
        for key, req in self.items():
            if not apilabels.is_restricted_node_label(key):
                value = req.any_value()
                if value:
                    out[key] = value
        return out

    def __repr__(self) -> str:
        return ", ".join(repr(r) for _, r in sorted(self.items()))


ALLOW_UNDEFINED_WELL_KNOWN_LABELS = apilabels.WELL_KNOWN_LABELS


def has_preferred_node_affinity(pod: Optional[Pod]) -> bool:
    return bool(
        pod
        and pod.affinity
        and pod.affinity.node_affinity
        and pod.affinity.node_affinity.preferred
    )
