"""Operator: wires store, cluster state, cloud provider, and controllers
into one reconcile loop (reference: pkg/operator/operator.go:105-223,
kwok/main.go:28-47).

The reference runs ~28 controllers concurrently on a controller-runtime
manager; here the loop is synchronous and cooperative — each pass drives
every controller once, and `run_until_idle` iterates until the store stops
mutating. That is exactly how the reference's envtest suites drive
reconcilers (pkg/test/expectations/expectations.go), promoted to the
framework's runtime; determinism is what makes 50k-pod benches and
differential tests reproducible.

The binder stands in for kube-scheduler: pods nominated to an existing node
bind immediately; pods nominated to a new NodeClaim bind once its node
registers.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_core_tpu.api.nodeclaim import NodeClaim
from karpenter_core_tpu.api.objects import Node, Pod
from karpenter_core_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_core_tpu.controllers.disruption.controller import (
    DisruptionController,
)
from karpenter_core_tpu.controllers.node.health import NodeHealth
from karpenter_core_tpu.controllers.node.termination import NodeTermination
from karpenter_core_tpu.controllers.nodeclaim.disruption import (
    NodeClaimDisruption,
    PodEvents,
)
from karpenter_core_tpu.controllers.nodeclaim.gc import (
    Consistency,
    Expiration,
    GarbageCollection,
)
from karpenter_core_tpu.controllers.nodeclaim.hydration import Hydration
from karpenter_core_tpu.controllers.nodeclaim.lifecycle import NodeClaimLifecycle
from karpenter_core_tpu.controllers.nodepool.controllers import (
    Counter,
    Hash,
    Readiness,
    Validation,
)
from karpenter_core_tpu.controllers.provisioning.provisioner import Provisioner
from karpenter_core_tpu.events import Recorder
from karpenter_core_tpu.kube.store import KubeStore
from karpenter_core_tpu.solver.fleet import (
    DEFAULT_BATCH_WINDOW_MS,
    DEFAULT_MAX_BATCH,
)
from karpenter_core_tpu.state.cluster import Cluster
from karpenter_core_tpu.utils import pod as podutil
from karpenter_core_tpu.utils.clock import Clock

# -- reconcile fault isolation -----------------------------------------------
# One controller's exception must not kill the pass (the reference runs ~28
# independent controllers on a manager; an error there requeues ONE object
# with rate limiting, controller-runtime's DefaultTypedControllerRateLimiter).
# A guarded invocation that raises puts its controller on exponential requeue
# backoff; repeated consecutive errors mark it crash-looping and readyz()
# reports the control plane degraded.
RECONCILE_BACKOFF_BASE = 1.0
RECONCILE_BACKOFF_CAP = 60.0
CRASHLOOP_THRESHOLD = 3


def _parse_bool(value: str) -> bool:
    """Flag/env bool: the feature-gate truthy set, rejecting typos loudly
    (a misspelled 'fales' must not silently enable verification-off)."""
    low = value.strip().lower()
    if low in ("true", "1", "yes", "on"):
        return True
    if low in ("false", "0", "no", "off"):
        return False
    raise ValueError(f"not a boolean: {value!r}")


@dataclass
class Options:
    """Flag surface (reference: pkg/operator/options/options.go:49-102, plus
    the new solver seam). Resolution order mirrors AddFlags + env fallback
    (options.go:85-144): explicit flag > KARPENTER_* env var > default;
    feature gates parse from the comma-separated "Name=bool" string."""

    solver: str = "greedy"  # greedy | tpu
    # where the tpu solver runs: in this process, or behind the solverd
    # sidecar (solver/service.py) with RPC fault tolerance + greedy
    # degradation (solver/remote.py). solver_addr="" spawns a supervised
    # local sidecar (solver/supervisor.py); set it to reach an external one.
    solver_mode: str = "inproc"  # inproc | sidecar
    # which solve BACKEND runs behind the Solver seam (relaxsolve,
    # ISSUE 13): ffd = first-fit-decreasing (classic), relax = the
    # convex-relaxation optimizer with FFD as the scored/anytime
    # fallback. (--solver-mode was already taken by the inproc|sidecar
    # process topology above, so the backend selector is
    # --solver-backend; on the solverd child and the wire it IS named
    # solver mode — X-Solver-Mode / solverd --solver-mode.) In-proc it
    # threads into DeviceScheduler(solver_mode=); in sidecar mode it
    # rides every RPC (wire field + header) AND the spawned child's
    # argv as its default for mode-less clients.
    solver_backend: str = "ffd"  # ffd | relax
    # which KERNEL implementation answers the FFD scan dispatches under
    # whichever backend is selected above (ISSUE 18): xla = the classic
    # per-op lowering of ops/ffd.py, pallas = the hand-fused per-class
    # kernel (ops/pallas_ffd.py, VMEM-resident slot state; interpreted
    # off-TPU so the choice is valid everywhere). Byte-identical results
    # either way — this is a latency lever, not a semantics switch.
    # In-proc it threads into DeviceScheduler(kernel_backend=); in
    # sidecar mode it rides the spawned child's argv (solverd --kernel).
    solver_kernel: str = "xla"  # xla | pallas
    solver_addr: str = ""
    solver_timeout: float = 30.0  # per-RPC deadline, seconds
    # host-side verification of every device/sidecar solve result
    # (solver/verify.py) before the reconcilers act on it: the trust
    # anchor that lets optimizing backends swap in behind the Solver seam.
    # A rejected result degrades that solve to greedy with
    # solver_result_rejected_total{reason} + a Warning event.
    solver_verify: bool = True
    # crash-only survivability knobs for a SPAWNED sidecar (an external
    # --solver-addr sidecar configures its own): the hard wall-clock bound
    # on the exclusive device step (0 disables; rides the spawn argv as
    # solverd --watchdog-seconds), and the poison-pill journal path that
    # lets the gateway's quarantine survive the very crash it predicts
    # (empty = in-memory quarantine only)
    solver_watchdog_seconds: float = 120.0
    solver_quarantine_journal: str = ""
    # shard the solve over the first N local devices (parallel/mesh.py
    # slot mesh; 0 = all local devices, 1 = single-device). In-proc this
    # threads into the DeviceScheduler; in sidecar mode it rides the
    # spawned child's command line (solverd --devices) — an external
    # --solver-addr sidecar configures its own. Requests clamp to what
    # exists, so a slice config degrades to single-device on a 1-chip box.
    solver_devices: int = 1
    # fleet tenancy (solver/fleet.py): this operator's identity at a SHARED
    # sidecar — rides every RPC (wire field + X-Solver-Tenant header) for
    # fair queueing / per-tenant accounting, and labels the circuit gauge
    solver_tenant: str = "default"
    # gateway sizing, passed through to a SPAWNED sidecar (an external
    # --solver-addr sidecar configures its own): admission bound before
    # 429 sheds, and 'tenant=weight,...' fair-share weights
    solver_queue_depth: int = 16
    solver_tenant_weights: str = ""
    # continuous cross-tenant batching at the spawned sidecar's gateway:
    # max compatible queued problems one device grant may solve as a
    # single vmapped batch (1 disables coalescing), and the few-ms window
    # a grant leader may hold the device for still-decoding requests
    # (0 = coalesce only what is already queued). The solverd defaults
    # (solver/fleet.py), single-sourced so operator-spawned and
    # externally-launched sidecars can never diverge on a default bump;
    # an external --solver-addr sidecar configures its own.
    solver_max_batch: int = DEFAULT_MAX_BATCH
    solver_batch_window_ms: float = DEFAULT_BATCH_WINDOW_MS
    # horizontally scaled solver tier (segmentstore + fleet routing,
    # ISSUE 14): spawn N supervised solverds on distinct ports and route
    # client-side with digest affinity + spill-over (solver/remote.
    # FleetRouter). 1 = the classic single sidecar. An external
    # --solver-addr may name a comma-separated member list instead.
    solver_fleet: int = 1
    # closed-loop elastic tier (solver/autoscale.py, ISSUE 17): when
    # enabled, a TierAutoscaler sizes the SPAWNED fleet between min/max
    # off the gateways' queue-wait/shed signals — scale-up through
    # FleetSupervisor.add_member, scale-down through the faultless drain
    # path, brownout ladder at max scale. --solver-fleet stays the
    # STARTING size; 0 min/max default to 1 / max(fleet, min).
    solver_autoscale: bool = False
    solver_fleet_min: int = 0
    solver_fleet_max: int = 0
    # solve-request wire form: delta = content-addressed segment
    # manifests with miss repair and full-wire fallback (unchanged
    # catalogs never re-upload); full = every request ships the whole
    # problem (the pre-v5 behavior, and the escape hatch)
    solver_wire: str = "delta"  # delta | full
    batch_max_duration: float = 10.0
    batch_idle_duration: float = 1.0
    log_level: str = "info"
    poll_interval: float = 1.0  # CLI loop pacing
    max_iters: int = 0  # CLI loop bound (0 = until interrupted)
    feature_gates: Dict[str, bool] = field(default_factory=dict)
    device_scheduler_opts: Dict = field(default_factory=dict)
    # host/device profiling hooks (the reference's pprof surface,
    # operator.go:159-175): cProfile the next N solves + a jax.profiler
    # trace per profiled solve, written under profile_dir
    profile_solves: int = 0
    profile_dir: str = "/tmp/karpenter-profiles"

    # served HTTP surface (operator.go:105-198): 0 disables, -1 picks free
    health_port: int = 0

    _FLAGS = {
        "health_port": ("--health-port", "KARPENTER_HEALTH_PORT", int),
        "solver": ("--solver", "KARPENTER_SOLVER", str),
        "solver_mode": ("--solver-mode", "KARPENTER_SOLVER_MODE", str),
        "solver_backend": (
            "--solver-backend", "KARPENTER_SOLVER_BACKEND", str,
        ),
        "solver_kernel": (
            "--kernel", "KARPENTER_SOLVER_KERNEL", str,
        ),
        "solver_addr": ("--solver-addr", "KARPENTER_SOLVER_ADDR", str),
        "solver_timeout": (
            "--solver-timeout", "KARPENTER_SOLVER_TIMEOUT", float,
        ),
        "solver_verify": (
            "--solver-verify", "KARPENTER_SOLVER_VERIFY", _parse_bool,
        ),
        "solver_watchdog_seconds": (
            "--solver-watchdog-seconds",
            "KARPENTER_SOLVER_WATCHDOG_SECONDS",
            float,
        ),
        "solver_quarantine_journal": (
            "--solver-quarantine-journal",
            "KARPENTER_SOLVER_QUARANTINE_JOURNAL",
            str,
        ),
        "solver_tenant": (
            "--solver-tenant", "KARPENTER_SOLVER_TENANT", str,
        ),
        "solver_devices": (
            "--solver-devices", "KARPENTER_SOLVER_DEVICES", int,
        ),
        "solver_queue_depth": (
            "--solver-queue-depth", "KARPENTER_SOLVER_QUEUE_DEPTH", int,
        ),
        "solver_tenant_weights": (
            "--solver-tenant-weights",
            "KARPENTER_SOLVER_TENANT_WEIGHTS",
            str,
        ),
        "solver_max_batch": (
            "--solver-max-batch", "KARPENTER_SOLVER_MAX_BATCH", int,
        ),
        "solver_batch_window_ms": (
            "--solver-batch-window-ms",
            "KARPENTER_SOLVER_BATCH_WINDOW_MS",
            float,
        ),
        "solver_fleet": (
            "--solver-fleet", "KARPENTER_SOLVER_FLEET", int,
        ),
        "solver_autoscale": (
            "--solver-autoscale", "KARPENTER_SOLVER_AUTOSCALE", _parse_bool,
        ),
        "solver_fleet_min": (
            "--solver-fleet-min", "KARPENTER_SOLVER_FLEET_MIN", int,
        ),
        "solver_fleet_max": (
            "--solver-fleet-max", "KARPENTER_SOLVER_FLEET_MAX", int,
        ),
        "solver_wire": (
            "--solver-wire", "KARPENTER_SOLVER_WIRE", str,
        ),
        "batch_max_duration": (
            "--batch-max-duration", "KARPENTER_BATCH_MAX_DURATION", float,
        ),
        "batch_idle_duration": (
            "--batch-idle-duration", "KARPENTER_BATCH_IDLE_DURATION", float,
        ),
        "log_level": ("--log-level", "KARPENTER_LOG_LEVEL", str),
        "poll_interval": ("--poll-interval", "KARPENTER_POLL_INTERVAL", float),
        "max_iters": ("--max-iters", "KARPENTER_MAX_ITERS", int),
        "profile_solves": (
            "--profile-solves", "KARPENTER_PROFILE_SOLVES", int,
        ),
        "profile_dir": ("--profile-dir", "KARPENTER_PROFILE_DIR", str),
    }

    @classmethod
    def parse(cls, argv=None, env=None) -> "Options":
        import os as _os

        argv = list(argv or [])
        env = dict(env if env is not None else _os.environ)
        opts = cls()
        known = {flag for flag, _, _ in cls._FLAGS.values()} | {
            "--feature-gates"
        }
        flat: Dict[str, str] = {}
        i = 0
        while i < len(argv):
            arg = argv[i]
            name = arg.split("=", 1)[0]
            if name not in known:
                raise ValueError(f"unknown flag {arg!r}")
            if "=" in arg:
                flat[name] = arg.split("=", 1)[1]
            elif i + 1 < len(argv):
                flat[name] = argv[i + 1]
                i += 1
            else:
                raise ValueError(f"flag {arg!r} needs a value")
            i += 1
        for attr, (flag, envvar, conv) in cls._FLAGS.items():
            if flag in flat:
                setattr(opts, attr, conv(flat[flag]))
            elif envvar in env:
                setattr(opts, attr, conv(env[envvar]))
        gates = flat.get(
            "--feature-gates", env.get("KARPENTER_FEATURE_GATES", "")
        )
        for part in filter(None, (p.strip() for p in gates.split(","))):
            name, _, value = part.partition("=")
            opts.feature_gates[name] = value.lower() in ("true", "1", "yes")
        # non-positive durations silently wedge the loop (a zero RPC
        # deadline fails every solve; a zero poll interval busy-spins) —
        # reject them at the flag surface, not deep in a controller
        for attr in ("solver_timeout", "batch_max_duration", "poll_interval",
                     "solver_queue_depth"):
            value = getattr(opts, attr)
            if value <= 0:
                flag = cls._FLAGS[attr][0]
                raise ValueError(
                    f"{flag} must be positive, got {value}"
                )
        if not opts.solver_tenant:
            raise ValueError("--solver-tenant must be non-empty")
        # 0 = all local devices is the only non-positive request that
        # means anything; a negative count is a typo, not a mesh
        if opts.solver_devices < 0:
            raise ValueError(
                "--solver-devices must be >= 0 (0 = all local devices),"
                f" got {opts.solver_devices}"
            )
        if opts.solver_watchdog_seconds < 0:
            raise ValueError(
                "--solver-watchdog-seconds must be >= 0 (0 disables),"
                f" got {opts.solver_watchdog_seconds}"
            )
        if opts.solver_max_batch < 1:
            raise ValueError(
                "--solver-max-batch must be >= 1 (1 disables coalescing),"
                f" got {opts.solver_max_batch}"
            )
        if opts.solver_batch_window_ms < 0:
            raise ValueError(
                "--solver-batch-window-ms must be >= 0 (0 = never wait),"
                f" got {opts.solver_batch_window_ms}"
            )
        if opts.solver_fleet < 1:
            raise ValueError(
                "--solver-fleet must be >= 1 (1 = single sidecar),"
                f" got {opts.solver_fleet}"
            )
        if opts.solver_fleet > 1 and opts.solver_addr:
            # the fleet size only governs SPAWNED children; an external
            # address wins and would silently ignore the flag — a user
            # who believes they have a 4-member fleet must hear otherwise
            raise ValueError(
                "--solver-fleet > 1 spawns supervised sidecars and"
                " cannot combine with --solver-addr; for an external"
                " fleet pass a comma-separated member list as"
                " --solver-addr instead"
            )
        if opts.solver_fleet_min < 0 or opts.solver_fleet_max < 0:
            raise ValueError(
                "--solver-fleet-min/--solver-fleet-max must be >= 0"
                " (0 = derive from --solver-fleet), got"
                f" {opts.solver_fleet_min}/{opts.solver_fleet_max}"
            )
        if opts.solver_autoscale:
            if opts.solver_addr:
                # the autoscaler spawns and retires SUPERVISED members;
                # an external fleet's lifecycle is not ours to resize
                raise ValueError(
                    "--solver-autoscale governs spawned sidecars and"
                    " cannot combine with --solver-addr"
                )
            if opts.solver != "tpu" or opts.solver_mode != "sidecar":
                raise ValueError(
                    "--solver-autoscale requires --solver=tpu"
                    " --solver-mode=sidecar (there is no tier to size"
                    f" under solver={opts.solver!r}"
                    f" mode={opts.solver_mode!r})"
                )
            mn = opts.solver_fleet_min or 1
            mx = opts.solver_fleet_max or max(opts.solver_fleet, mn)
            if mx < mn:
                raise ValueError(
                    f"--solver-fleet-max ({mx}) must be >="
                    f" --solver-fleet-min ({mn})"
                )
            if not mn <= opts.solver_fleet <= mx:
                raise ValueError(
                    f"--solver-fleet ({opts.solver_fleet}) must start"
                    f" inside [--solver-fleet-min, --solver-fleet-max]"
                    f" = [{mn}, {mx}]"
                )
        elif opts.solver_fleet_min or opts.solver_fleet_max:
            # bounds without the loop would silently do nothing — the
            # user believes they have elasticity; tell them otherwise
            raise ValueError(
                "--solver-fleet-min/--solver-fleet-max require"
                " --solver-autoscale"
            )
        if opts.solver_wire not in ("delta", "full"):
            raise ValueError(
                f"unknown solver wire mode {opts.solver_wire!r}"
                " (delta | full)"
            )
        # malformed weights must fail at the flag surface, not inside a
        # respawned sidecar's argparse three failures deep
        from karpenter_core_tpu.solver.fleet import parse_tenant_weights

        parse_tenant_weights(opts.solver_tenant_weights)
        if opts.solver not in ("greedy", "tpu"):
            raise ValueError(f"unknown solver {opts.solver!r}")
        if opts.solver_mode not in ("inproc", "sidecar"):
            raise ValueError(f"unknown solver mode {opts.solver_mode!r}")
        if opts.solver_backend not in ("ffd", "relax"):
            raise ValueError(
                f"unknown solver backend {opts.solver_backend!r}"
            )
        if opts.solver_kernel not in ("xla", "pallas"):
            # reject loudly at the flag surface: a typo'd kernel name
            # must not silently fall back to xla and fake a speedup
            raise ValueError(
                f"unknown kernel {opts.solver_kernel!r} (xla | pallas)"
            )
        if opts.solver_mode == "sidecar" and opts.solver != "tpu":
            # the sidecar hosts the DEVICE solver; accepting this combo
            # would silently run greedy in-proc while logging sidecar mode
            raise ValueError(
                "--solver-mode=sidecar requires --solver=tpu "
                f"(got solver={opts.solver!r})"
            )
        return opts


class Operator:
    def __init__(
        self,
        kube: Optional[KubeStore] = None,
        cloud_provider=None,
        clock: Optional[Clock] = None,
        options: Optional[Options] = None,
        instance_types=None,
        solver_client=None,
    ):
        self.clock = clock or Clock()
        # object timestamps (creation, condition transitions) follow the
        # operator's clock so fake-clock tests are fully deterministic
        from karpenter_core_tpu.utils import timesource

        timesource.set_source(self.clock.now)
        self.kube = kube or KubeStore(self.clock)
        self.options = options or Options()
        from karpenter_core_tpu.cloudprovider.metrics import MetricsDecorator
        from karpenter_core_tpu.cloudprovider.unavailableofferings import (
            UnavailableOfferings,
        )

        # the ICE cache is shared three ways: lifecycle marks offerings from
        # typed InsufficientCapacityError context, the provisioner's solve
        # paths exclude them, and a provider that exposes its own cache (the
        # kwok/fake create paths skip cached offerings when picking) keeps
        # using the SAME instance so all views agree
        if cloud_provider is None:
            self.unavailable_offerings = UnavailableOfferings(self.clock)
            cloud_provider = KwokCloudProvider(
                self.kube,
                instance_types,
                unavailable_offerings=self.unavailable_offerings,
            )
        else:
            # `is None`, not truthiness: an EMPTY provider cache is falsy
            # (len 0) but must still be adopted, or lifecycle would mark a
            # different cache than the provider's create path consults
            adopted = getattr(cloud_provider, "unavailable_offerings", None)
            self.unavailable_offerings = (
                adopted
                if adopted is not None
                else UnavailableOfferings(self.clock)
            )
        self.cloud_provider = MetricsDecorator(cloud_provider)
        self.cluster = Cluster(self.kube, self.clock)
        self.recorder = Recorder(self.clock)
        # solverd sidecar wiring (solver_mode=sidecar): a supervised child
        # process (unless an external --solver-addr is given) plus the
        # fault-tolerant RPC client the provisioner routes solves through
        self.solver_supervisor = None
        self.solver_client = None
        self.solver_autoscaler = None
        if solver_client is not None:
            # injection seam (the digital twin, twin/harness.py): the
            # caller owns the client/router — typically one whose breaker
            # cooldowns, retry sleeps and quarantine TTLs ride a VIRTUAL
            # clock so days of fleet churn replay deterministically in
            # minutes — and the tier it points at, so no supervisor spawns
            if self.options.solver_mode != "sidecar":
                raise ValueError(
                    "solver_client injection requires solver_mode=sidecar"
                )
            self.solver_client = solver_client
        elif self.options.solver == "tpu" and self.options.solver_mode == "sidecar":
            from karpenter_core_tpu.solver.remote import (
                FleetRouter,
                SolverClient,
            )

            # --solver-addr may name an external fleet as a comma-
            # separated member list; empty spawns supervised children
            addrs = [
                a.strip()
                for a in self.options.solver_addr.split(",")
                if a.strip()
            ]
            if not addrs:
                from karpenter_core_tpu.solver.supervisor import (
                    FleetSupervisor,
                    SolverSupervisor,
                )

                child_kwargs = dict(
                    # the spawned sidecar arms jax.profiler capture lazily
                    # (POST /profile), so pass the operator's profile dir
                    # through: TPU-side traces become grabbable from the
                    # running child without a redeploy
                    profile_dir=self.options.profile_dir,
                    # fleet-gateway sizing for the child (an external
                    # --solver-addr sidecar configures its own)
                    queue_depth=self.options.solver_queue_depth,
                    tenant_weights=self.options.solver_tenant_weights,
                    # continuous-batching shape for the child's gateway
                    max_batch=self.options.solver_max_batch,
                    batch_window_ms=self.options.solver_batch_window_ms,
                    # only a non-default device count rides the argv, so a
                    # respawned child re-reads the operator's choice
                    devices=(
                        self.options.solver_devices
                        if self.options.solver_devices != 1
                        else None
                    ),
                    # crash-only survivability: the watchdog bound is
                    # explicit policy (it rides the argv so a respawned
                    # child keeps it), and the poison journal is what
                    # makes gateway-side quarantine survive the crash it
                    # predicts
                    watchdog_seconds=self.options.solver_watchdog_seconds,
                    quarantine_journal=(
                        self.options.solver_quarantine_journal or None
                    ),
                    # the child's default solve backend; per-request
                    # selection still rides every RPC's wire field
                    solve_mode=(
                        self.options.solver_backend
                        if self.options.solver_backend != "ffd"
                        else None
                    ),
                    # the child's FFD-scan kernel implementation; only a
                    # non-default choice rides the argv, so a respawned
                    # child keeps the operator's selection
                    kernel=(
                        self.options.solver_kernel
                        if self.options.solver_kernel != "xla"
                        else None
                    ),
                )
                if (
                    self.options.solver_fleet > 1
                    or self.options.solver_autoscale
                ):
                    # N children on distinct ports; the router below does
                    # digest-affinity placement across them (ISSUE 14).
                    # The autoscaler needs the fleet shape even at a
                    # starting size of 1 — add_member/retire_member are
                    # its actuators.
                    self.solver_supervisor = FleetSupervisor(
                        self.options.solver_fleet,
                        on_event=self._publish_sidecar_event,
                        **child_kwargs,
                    )
                    addrs = self.solver_supervisor.start()
                else:
                    self.solver_supervisor = SolverSupervisor(
                        on_event=self._publish_sidecar_event,
                        **child_kwargs,
                    )
                    addrs = [self.solver_supervisor.start()]

            fleet_shaped = (
                len(addrs) > 1 or self.options.solver_autoscale
            )

            def _make_client(a: str, member: str) -> "SolverClient":
                return SolverClient(
                    a,
                    timeout=self.options.solver_timeout,
                    on_state_change=self._publish_circuit_event,
                    # this operator's identity at a (possibly shared)
                    # sidecar
                    tenant=self.options.solver_tenant,
                    # delta vs full solve-request wire (ISSUE 14)
                    wire_mode=self.options.solver_wire,
                    member=member if fleet_shaped else "",
                )

            if fleet_shaped:
                # the router shares ONE client-side poison quarantine
                # across members and per-member breakers/sent-caches
                self.solver_client = FleetRouter(
                    [
                        _make_client(a, str(i))
                        for i, a in enumerate(addrs)
                    ],
                    tenant=self.options.solver_tenant,
                )
            else:
                self.solver_client = _make_client(addrs[0], "0")
            if (
                self.options.solver_autoscale
                and self.solver_supervisor is not None
            ):
                from karpenter_core_tpu.solver.autoscale import (
                    SpawnedTier,
                    TierAutoscaler,
                )

                mn = self.options.solver_fleet_min or 1
                mx = self.options.solver_fleet_max or max(
                    self.options.solver_fleet, mn
                )
                self.solver_autoscaler = TierAutoscaler(
                    SpawnedTier(
                        self.solver_supervisor,
                        [self.solver_client],
                        _make_client,
                    ),
                    mn,
                    mx,
                    on_decision=self._publish_autoscale_event,
                )
        # in-proc TPU solves follow --solver-devices (sidecar mode leaves
        # the device choice to the child, which owns the chips); an
        # explicit device_scheduler_opts["devices"] wins over the flag
        device_opts = dict(self.options.device_scheduler_opts)
        if self.options.solver == "tpu":
            # the backend selector reaches BOTH scheduler constructions:
            # DeviceScheduler(solver_mode=) in-proc, and RemoteScheduler
            # reads it out of device_scheduler_opts for the wire field +
            # X-Solver-Mode header
            device_opts.setdefault(
                "solver_mode", self.options.solver_backend
            )
            # the FFD-scan kernel selector (--kernel) reaches the in-proc
            # DeviceScheduler the same way; in sidecar mode the spawned
            # child's argv carries it instead (the child owns the chips)
            if self.solver_client is None:
                device_opts.setdefault(
                    "kernel_backend", self.options.solver_kernel
                )
        if self.options.solver == "tpu" and self.solver_client is None:
            device_opts.setdefault("devices", self.options.solver_devices)
        self.provisioner = Provisioner(
            self.kube,
            self.cluster,
            self.cloud_provider,
            self.clock,
            solver=self.options.solver,
            device_scheduler_opts=device_opts,
            recorder=self.recorder,
            solver_client=self.solver_client,
            unavailable_offerings=self.unavailable_offerings,
            verify_results=self.options.solver_verify,
            # pods already promised capacity by an in-flight nomination
            # must not re-enter the solve (the bind-conflict double-book
            # the twin's fuzzer found — see Provisioner._nominated_pods)
            nominated_pods=self._nominated_pod_keys,
        )
        self.provisioner.profile_solves = self.options.profile_solves
        self.provisioner.profile_dir = self.options.profile_dir
        self.lifecycle = NodeClaimLifecycle(
            self.kube, self.cluster, self.cloud_provider, self.clock,
            unavailable_offerings=self.unavailable_offerings,
            recorder=self.recorder,
        )
        self.termination = NodeTermination(
            self.kube, self.cluster, self.cloud_provider, self.clock,
            recorder=self.recorder,
        )
        self.nodeclaim_disruption = NodeClaimDisruption(
            self.kube, self.cloud_provider, self.clock
        )
        self.pod_events = PodEvents(self.kube, self.cluster, self.clock)
        self.disruption = DisruptionController(
            self.kube,
            self.cluster,
            self.provisioner,
            self.cloud_provider,
            self.clock,
            feature_gates=self.options.feature_gates,
            recorder=self.recorder,
        )
        self.hydration = Hydration(self.kube)
        self.expiration = Expiration(self.kube, self.clock)
        self.garbage_collection = GarbageCollection(
            self.kube, self.cloud_provider, self.clock
        )
        self.consistency = Consistency(self.kube, self.recorder, self.clock)
        self.nodepool_counter = Counter(self.kube, self.cluster)
        self.nodepool_hash = Hash(self.kube)
        self.nodepool_readiness = Readiness(
            self.kube, self.cloud_provider, self.clock
        )
        self.nodepool_validation = Validation(self.kube, self.clock)
        self.node_health = NodeHealth(
            self.kube,
            self.cluster,
            self.cloud_provider,
            self.clock,
            enabled=self.options.feature_gates.get("NodeRepair", False),
        )
        from karpenter_core_tpu.controllers.status import StatusController

        self.status = StatusController(self.kube, self.recorder, self.clock)
        # pod-trigger batching gates the solve (batcher.go:33-110); the
        # store's synchronous watch is the trigger controller
        # (provisioning/controller.go:54-76)
        from karpenter_core_tpu.controllers.provisioning.batcher import Batcher

        self.batcher = Batcher(
            self.clock,
            max_duration=self.options.batch_max_duration,
            idle_duration=self.options.batch_idle_duration,
        )
        self.kube.watch(self._trigger_on_pod)
        # claim/node name -> pod keys awaiting bind
        self.nominations: Dict[str, List[str]] = {}
        # controller name -> (not_before, delay, consecutive_errors,
        # pass_id_recorded): the per-controller requeue backoff state
        # (_guarded); pass_id scopes the skip-gate so a fault armed DURING
        # a pass never skips that same pass's remaining objects
        self._controller_faults: Dict[str, tuple] = {}
        self._pass_id = 0
        # controllers _guarded saw this pass (invoked OR backoff-skipped):
        # a faulted controller that no longer appears at all — its failing
        # object was deleted and no workload remains — must drop its fault,
        # or readyz would report a crash-loop forever with nothing failing
        self._pass_seen: set = set()

    def _nominated_pod_keys(self) -> Dict[str, str]:
        """{pod key -> target} for LIVE nominations (binder ledger): the
        binder prunes dead targets every pass BEFORE provisioning runs,
        so a claim that died returns its pods to the solve the same
        pass. The provisioner excludes these pods from the solve AND
        reserves their capacity on the target node."""
        return {
            key: target
            for target, keys in self.nominations.items()
            for key in keys
        }

    def _trigger_on_pod(self, event: str, kind: str, obj) -> None:
        if kind != "Pod" or event == "DELETED":
            return
        if podutil.is_provisionable(obj):
            self.batcher.trigger()

    # -- solverd sidecar surface -------------------------------------------

    def _publish_sidecar_event(self, reason: str, message: str) -> None:
        """Supervisor lifecycle -> the event stream, the way the reference
        surfaces controller conditions (SidecarUnavailable is the 'sidecar
        unavailable' condition the ops surface watches)."""
        from karpenter_core_tpu.events import Event

        self.recorder.publish(Event(
            involved_object="Solverd/sidecar",
            type="Warning" if "Unavailable" in reason or "Failed" in reason
            else "Normal",
            reason=reason,
            message=message,
        ))

    def _publish_autoscale_event(self, action: str, arg: str) -> None:
        """Autoscaler decisions -> the event stream so the ops surface can
        audit every resize/brownout transition after the fact."""
        from karpenter_core_tpu.events import Event

        if action == "hold":
            return
        self.recorder.publish(Event(
            involved_object="Solverd/sidecar",
            type="Warning" if action.startswith("rung") else "Normal",
            reason="SolverFleetScale",
            message=f"autoscaler decided {action} ({arg})",
        ))

    def _publish_circuit_event(self, state: str) -> None:
        from karpenter_core_tpu.events import Event

        self.recorder.publish(Event(
            involved_object="Solverd/sidecar",
            type="Warning" if state == "open" else "Normal",
            reason="SolverCircuitOpen" if state == "open"
            else "SolverCircuitClosed" if state == "closed"
            else "SolverCircuitHalfOpen",
            message=f"solver circuit breaker is {state}; "
            + (
                "solves degrade to the host greedy path"
                if state == "open"
                else "device solves resume"
            ),
        ))

    def shutdown(self) -> None:
        """Stop owned background resources (the supervised sidecar)."""
        if self.solver_supervisor is not None:
            self.solver_supervisor.stop()

    # -- health surface (operator.go:181-198 healthz/readyz) ---------------

    def healthz(self) -> bool:
        """Liveness: the process can serve (always true in-process)."""
        return True

    def readyz(self) -> bool:
        """Readiness: cluster state has caught up with the store — the
        Synced gate every solve already requires (state/cluster.go:96-150) —
        AND no controller is crash-looping (a controller past the
        consecutive-error threshold means the control plane is degraded;
        the probe surface must say so)."""
        if any(
            fault[2] >= CRASHLOOP_THRESHOLD
            for fault in self._controller_faults.values()
        ):
            return False
        # a solverd member respawning past the storm threshold means the
        # device tier is melting (supervisor.RESPAWN_STORM_*): solves
        # still degrade to greedy, but the probe surface must say degraded
        if (
            self.solver_supervisor is not None
            and self.solver_supervisor.respawn_storm()
        ):
            return False
        return self.cluster.synced()

    # -- fault isolation (see module constants above) ----------------------

    def _guarded(self, controller: str, fn, *args) -> None:
        """Run one reconciler invocation inside the controller's failure
        domain: an exception increments reconcile_errors, publishes a
        Warning event, and escalates the controller's requeue backoff —
        the pass continues. The backoff gate only honors faults recorded
        in EARLIER passes, so the remaining objects of a pass still
        reconcile after a sibling's error, and a mixed controller (one
        broken object among healthy ones) clears its fault state on the
        next success instead of starving siblings or flipping readyz —
        crash-loop detection targets whole-controller failure."""
        self._pass_seen.add(controller)
        fault = self._controller_faults.get(controller)
        now = self.clock.now()
        if (
            fault is not None
            and now < fault[0]
            and fault[3] != self._pass_id
        ):
            return  # still on requeue backoff from a prior pass
        try:
            fn(*args)
        except Exception as e:  # noqa: BLE001 — isolation is the point
            self._record_reconcile_error(controller, e)
        else:
            if self._controller_faults.pop(controller, None) is not None:
                self._export_crashloop()

    def _record_reconcile_error(self, controller: str, e: Exception) -> None:
        from karpenter_core_tpu.events import Event
        from karpenter_core_tpu.metrics import wiring as m

        m.RECONCILE_ERRORS.inc(
            {"controller": controller, "error": type(e).__name__}
        )
        self.recorder.publish(Event(
            involved_object=f"Controller/{controller}",
            type="Warning",
            reason="ReconcileError",
            message=f"{type(e).__name__}: {e}",
        ))
        fault = self._controller_faults.get(controller)
        if fault is not None and fault[3] == self._pass_id:
            return  # already escalated this pass; don't compound the delay
        delay = (
            RECONCILE_BACKOFF_BASE
            if fault is None
            else min(fault[1] * 2.0, RECONCILE_BACKOFF_CAP)
        )
        # an optimistic-lock race is an expected requeue in EVERY
        # controller, not evidence of a crash-loop: it backs off like any
        # error (the controller-runtime rate limiter) but never advances
        # the consecutive count that degrades readyz
        from karpenter_core_tpu.kube.store import ConflictError

        if isinstance(e, ConflictError):
            consecutive = 0 if fault is None else fault[2]
        else:
            consecutive = 1 if fault is None else fault[2] + 1
        self._controller_faults[controller] = (
            self.clock.now() + delay, delay, consecutive, self._pass_id,
        )
        self._export_crashloop()

    def _export_crashloop(self) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        m.CONTROLLER_CRASHLOOPING.set(float(sum(
            1
            for fault in self._controller_faults.values()
            if fault[2] >= CRASHLOOP_THRESHOLD
        )))

    def reconcile_backoff_wait_remaining(self) -> float:
        """Seconds until the nearest controller requeue backoff unblocks
        (0 when none) — lets a fake-clock driver elapse the backoff."""
        now = self.clock.now()
        waits = [
            fault[0] - now for fault in self._controller_faults.values()
            if fault[0] > now
        ]
        return min(waits) if waits else 0.0

    # -- one pass ----------------------------------------------------------

    def reconcile_once(self, disrupt: bool = True) -> None:
        self._pass_id += 1
        self._pass_seen = set()
        if self.solver_supervisor is not None:
            # supervise the sidecar(s) every pass; after a respawn the
            # client follows the (possibly fresh) address — no operator
            # restart. A FleetSupervisor reports WHICH members respawned
            # so the router re-points exactly those.
            restarted = self.solver_supervisor.poll()
            if self.solver_client is not None:
                if isinstance(restarted, list):
                    for i in restarted:
                        self.solver_client.set_member_addr(
                            i, self.solver_supervisor.addrs[i]
                        )
                elif restarted:
                    self.solver_client.set_addr(self.solver_supervisor.addr)
        if self.solver_autoscaler is not None:
            # one observe->decide->actuate step per reconcile pass; the
            # controller loop IS the autoscaler's clock, so twin replays
            # that drive reconcile_once on a virtual clock stay
            # deterministic.
            self._guarded("solver.autoscale", self.solver_autoscaler.step)
        for pool in list(self.kube.list_nodepools()):
            self._guarded("nodepool.hash", self.nodepool_hash.reconcile, pool)
            self._guarded(
                "nodepool.validation", self.nodepool_validation.reconcile, pool
            )
            self._guarded(
                "nodepool.readiness", self.nodepool_readiness.reconcile, pool
            )
            self._guarded(
                "nodepool.counter", self.nodepool_counter.reconcile, pool
            )
        for claim in list(self.kube.list_nodeclaims()):
            self._guarded("nodeclaim.lifecycle", self.lifecycle.reconcile, claim)
            self._guarded("nodeclaim.hydration", self.hydration.reconcile, claim)
            self._guarded(
                "nodeclaim.disruption",
                self.nodeclaim_disruption.reconcile,
                claim,
            )
            self._guarded("nodeclaim.expiration", self.expiration.reconcile, claim)
            self._guarded(
                "nodeclaim.consistency", self.consistency.reconcile, claim
            )
        self._guarded("nodeclaim.gc", self.garbage_collection.reconcile)
        for node in list(self.kube.list_nodes()):
            self._guarded("node.termination", self.termination.reconcile, node)
            self._guarded("node.health", self.node_health.reconcile, node)
        self._guarded("binder", self._bind_nominated)
        provisionable = any(
            podutil.is_provisionable(p) for p in self.kube.list_pods()
        )
        # self-heal: pods can become provisionable without a Pod write (a
        # nominated claim died; a pre-populated store) — open a window for
        # them so the batcher gate can never starve the solve
        if provisionable and not self.batcher.open:
            self.batcher.trigger()
        if self.batcher.ready():
            # a closed window resets even with nothing to solve (deleted
            # pods), or its stale age would instantly close the next burst's
            # window and split it into per-pod solves
            self.batcher.reset()
            if provisionable:
                self._guarded("provisioning", self._provision)
        if disrupt:
            self._guarded("disruption", self.disruption.reconcile)
        self._guarded("status", self.status.reconcile)
        self._guarded("metrics", self._export_metrics)
        # drop faults of controllers with no remaining workload (their
        # failing object vanished — nothing is failing anymore)
        stale = [
            name for name in self._controller_faults
            if name not in self._pass_seen
        ]
        if stale:
            for name in stale:
                del self._controller_faults[name]
            self._export_crashloop()

    def _export_metrics(self) -> None:
        """State gauges + pod/node/nodepool exporters (state/metrics.go:36-67,
        pkg/controllers/metrics/{pod,node,nodepool}). Multi-series gauges
        reset before re-export so a phase/nodepool/resource that disappears
        drops its series instead of freezing at the last value (the
        reference's gauge stores delete stale series on every update)."""
        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.utils import resources as resutil

        m.CLUSTER_NODE_COUNT.set(len(self.cluster.nodes()))
        m.CLUSTER_SYNCED.set(1.0 if self.cluster.synced() else 0.0)
        all_pods = self.kube.list_pods()
        by_phase: Dict[str, int] = {}
        for p in all_pods:
            by_phase[p.phase] = by_phase.get(p.phase, 0) + 1
        m.PODS_STATE.reset()
        for phase, n in by_phase.items():
            m.PODS_STATE.set(n, {"phase": phase})
        alloc: Dict[str, float] = {}
        for node in self.kube.list_nodes():
            alloc = resutil.merge(alloc, node.status.allocatable)
        m.NODES_ALLOCATABLE.reset()
        for name, qty in alloc.items():
            m.NODES_ALLOCATABLE.set(qty, {"resource_type": name})
        bound = [p for p in all_pods if p.node_name]
        m.NODES_POD_REQUESTS.reset()
        m.NODES_POD_LIMITS.reset()
        if bound:
            for name, qty in resutil.requests_for_pods(*bound).items():
                m.NODES_POD_REQUESTS.set(qty, {"resource_type": name})
            for name, qty in resutil.limits_for_pods(*bound).items():
                m.NODES_POD_LIMITS.set(qty, {"resource_type": name})
        m.NODEPOOL_USAGE.reset()
        m.NODEPOOL_LIMIT.reset()
        for pool in self.kube.list_nodepools():
            for name, qty in (pool.status.resources or {}).items():
                m.NODEPOOL_USAGE.set(
                    qty, {"nodepool": pool.name, "resource_type": name}
                )
            if pool.spec.limits:
                for name, qty in dict(pool.spec.limits).items():
                    m.NODEPOOL_LIMIT.set(
                        qty, {"nodepool": pool.name, "resource_type": name}
                    )

    def run_until_idle(self, max_iters: int = 100, disrupt: bool = True) -> int:
        """Reconcile until the store stops changing; returns passes used.

        A pending disruption command waiting out its validation TTL is not
        idle: with a steppable (fake) clock the wait elapses here — the
        synchronous stand-in for the reference blocking on clock.After
        (validation.go:88-96) — so consolidation stays closed-loop."""
        for i in range(max_iters):
            before = self.kube.mutations
            self.reconcile_once(disrupt=disrupt)
            if self.kube.mutations == before and not self.disruption.in_flight:
                waits = [self.batcher.wait_remaining()]
                waits.append(self.termination.backoff_wait_remaining())
                waits.append(self.reconcile_backoff_wait_remaining())
                if disrupt:
                    waits.append(self.disruption.validation_wait_remaining())
                    # node-nomination TTLs gate disruption candidacy the
                    # same way the validation TTL gates commands
                    waits.append(self.cluster.nomination_wait_remaining())
                waits = [w for w in waits if w > 0]
                if waits and hasattr(self.clock, "step"):
                    # fire the nearest timer first (batch close / TTL elapse)
                    self.clock.step(min(waits))
                    continue
                return i + 1
        return max_iters

    # -- provisioning + binding -------------------------------------------

    def _provision(self) -> None:
        nominated = self.provisioner.provision()
        for pod_key, target in nominated.items():
            self.nominations.setdefault(target, []).append(pod_key)
        self._bind_nominated()

    def _bind_nominated(self) -> None:
        for target, pod_keys in list(self.nominations.items()):
            node = self.kube.get(Node, target)
            if node is None:
                claim = self.kube.get(NodeClaim, target)
                if claim is None:
                    # claim died (e.g. insufficient capacity): pods go back
                    # through the provisioner
                    del self.nominations[target]
                    continue
                if not claim.is_registered():
                    continue
                node = self.kube.get(Node, claim.status.node_name)
                if node is None:
                    continue
            for key in pod_keys:
                ns, name = key.split("/", 1)
                pod = self.kube.get(Pod, name, ns)
                if pod is None or pod.node_name:
                    continue  # deleted or already bound elsewhere
                self.kube.bind(pod, node.name)
            del self.nominations[target]
            # every nominated bind landed: release the node's disruption
            # protection now instead of waiting out the TTL backstop (a
            # bind that CONFLICTED raised above, keeping entry AND
            # nomination alive for the retry)
            self.cluster.clear_node_nomination(node.name)
