"""Logging configuration (reference: pkg/operator/logging/logging.go:35-79):
level from --log-level, plus a NopLogger for muting simulations the way the
reference silences SimulateScheduling (helpers.go:82,91).
"""
from __future__ import annotations

import logging as _logging

_LEVELS = {
    "debug": _logging.DEBUG,
    "info": _logging.INFO,
    "warn": _logging.WARNING,
    "warning": _logging.WARNING,
    "error": _logging.ERROR,
}


def configure(level: str = "info") -> _logging.Logger:
    logger = _logging.getLogger("karpenter")
    if not logger.handlers:
        handler = _logging.StreamHandler()
        handler.setFormatter(
            _logging.Formatter(
                "%(asctime)s\t%(levelname)s\t%(name)s\t%(message)s"
            )
        )
        logger.addHandler(handler)
        logger.propagate = False
    logger.setLevel(_LEVELS.get(level.lower(), _logging.INFO))
    return logger


def nop_logger() -> _logging.Logger:
    """A logger that drops everything (logging.go:35 NopLogger)."""
    logger = _logging.getLogger("karpenter.nop")
    if not logger.handlers:
        logger.addHandler(_logging.NullHandler())
        logger.propagate = False
    logger.setLevel(_logging.CRITICAL + 1)
    return logger
