"""Digital twin: a time-compressed, seeded-deterministic simulation of the
FULL operator loop (ROADMAP item 5, PAPER.md §7's kwok-style closed loop).

N simulated clusters — independent ``Operator``s with distinct catalogs —
run against one shared solverd tier (in-thread daemons behind each
operator's ``FleetRouter``) under scripted and rate-seeded fault schedules
composed from the chaos harness seams plus fleet-level faults (member
murder mid-solve, operator↔fleet partition windows, segment-store
amnesia). A virtual clock threads through every TTL/backoff surface so
days of churn replay in minutes; invariant monitors assert pod
conservation, gang atomicity, eviction-budget compliance and
zero-verifier-rejections at every virtual tick; a ledger accumulates
$-cost, time-to-bind SLOs, preemption burn and solver-tier utilization
over virtual time. ``twin/shrink.py`` fuzzes seeded scenarios and shrinks
any invariant violation to a minimal JSON repro a pytest replays
byte-deterministically.
"""
from karpenter_core_tpu.twin.clock import VirtualClock
from karpenter_core_tpu.twin.harness import DigitalTwin, TwinResult
from karpenter_core_tpu.twin.invariants import InvariantMonitor, Violation
from karpenter_core_tpu.twin.ledger import Ledger
from karpenter_core_tpu.twin.scenario import (
    FleetFault,
    Scenario,
    Storm,
    TestHook,
    WorkloadWave,
    decode_scenario,
    encode_scenario,
    scenario_fingerprint,
    scenario_from_json,
    scenario_to_json,
)
from karpenter_core_tpu.twin.shrink import fuzz, replay, save_repro, shrink

__all__ = [
    "DigitalTwin",
    "FleetFault",
    "InvariantMonitor",
    "Ledger",
    "Scenario",
    "Storm",
    "TestHook",
    "TwinResult",
    "VirtualClock",
    "Violation",
    "WorkloadWave",
    "decode_scenario",
    "encode_scenario",
    "fuzz",
    "replay",
    "save_repro",
    "scenario_fingerprint",
    "scenario_from_json",
    "scenario_to_json",
    "shrink",
]
