"""Tesserae-shaped workload generators for the twin.

Three workload classes, one per wave kind (twin/scenario.WorkloadWave):

* ``training`` — gang-annotated pods (solver/gangs.py pod-group contract,
  min-size = gang size: all-or-nothing) at the wave's priority tier, the
  distributed-training shape whose atomicity the invariant monitor pins;
* ``serving``  — replica pods behind a PodDisruptionBudget
  (min_available), the latency-SLO class whose time-to-bind percentiles
  the ledger reports and whose eviction budget the monitor enforces;
* ``batch``    — preemptible filler (the wave's priority, typically <= 0),
  the class preemption legitimately evicts.

Pod names, labels and sizes are pure functions of (wave index, pod index,
per-wave child RNG) — construction order never leaks into identity, so
two runs of one scenario create byte-identical workloads. Every pod
carries an owner reference (the ReplicaSet stand-in) so eviction returns
it to Pending instead of deleting it.
"""
from __future__ import annotations

import random
from typing import Dict, List, Tuple

from karpenter_core_tpu.api.objects import (
    LabelSelector,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodDisruptionBudget,
)
from karpenter_core_tpu.chaos import fold_seed
from karpenter_core_tpu.solver.gangs import (
    GANG_ANNOTATION,
    GANG_MAX_HOPS_ANNOTATION,
    GANG_MIN_SIZE_ANNOTATION,
    GANG_RANK_ANNOTATION,
)
from karpenter_core_tpu.twin.scenario import WorkloadWave

GIB = 2.0**30

# the workload class riding every twin pod (the ledger's SLO dimension)
CLASS_LABEL = "twin.karpenter.sh/workload-class"
WAVE_LABEL = "twin.karpenter.sh/wave"

# cpu jitter factors drawn per pod: mixed sizes pack differently than a
# monoculture, which is what makes the bin-packing honest
_SIZE_FACTORS = (0.5, 1.0, 1.0, 2.0)


def _pod(
    name: str,
    wave_id: str,
    cls: str,
    cpu: float,
    memory_gib: float,
    labels: Dict[str, str],
    annotations: Dict[str, str],
    priority: int,
) -> Pod:
    meta = ObjectMeta(name=name)
    meta.labels = {CLASS_LABEL: cls, WAVE_LABEL: wave_id, **labels}
    meta.annotations = dict(annotations)
    meta.owner_references = [
        OwnerReference(kind="ReplicaSet", name=f"rs-{wave_id}", uid=wave_id)
    ]
    return Pod(
        metadata=meta,
        resource_requests={"cpu": cpu, "memory": memory_gib * GIB},
        priority=priority,
    )


def pods_for_wave(
    wave: WorkloadWave, wave_id: str, seed: int
) -> Tuple[List[Pod], List[PodDisruptionBudget]]:
    """Materialize one wave: (pods, pdbs). ``wave_id`` is the wave's
    CONTENT-derived identity (scenario.wave_ids) — pod names and the
    folded child RNG stream key off it, never off tuple position, so
    dropping or reordering sibling waves (the shrinker, a hand-edited
    fixture) re-rolls nothing here."""
    rng = random.Random(fold_seed(seed, f"wave/{wave_id}"))
    pods: List[Pod] = []
    pdbs: List[PodDisruptionBudget] = []
    if wave.kind == "training":
        # validate_scenario pins count to a positive gang_size multiple
        for g in range(wave.count // wave.gang_size):
            gang_name = f"{wave_id}-g{g}"
            for i in range(wave.gang_size):
                annotations = {
                    GANG_ANNOTATION: gang_name,
                    GANG_MIN_SIZE_ANNOTATION: str(wave.gang_size),
                }
                if wave.max_hops >= 0:
                    # comms-sensitive gang (topoaware, ISSUE 20): a hard
                    # network-hop bound plus per-member collective rank,
                    # so the solver must place the gang rank-adjacent
                    # within the bound and the invariant monitor can
                    # re-derive both from annotations + node labels
                    annotations[GANG_MAX_HOPS_ANNOTATION] = str(
                        wave.max_hops
                    )
                    annotations[GANG_RANK_ANNOTATION] = str(i)
                pods.append(_pod(
                    name=f"{gang_name}-{i}",
                    wave_id=wave_id,
                    cls="training",
                    cpu=wave.cpu,
                    memory_gib=wave.memory_gib,
                    labels={"app": gang_name},
                    annotations=annotations,
                    priority=wave.priority,
                ))
    elif wave.kind == "serving":
        app = f"svc-{wave_id}"
        for i in range(wave.count):
            pods.append(_pod(
                name=f"{wave_id}-{i}",
                wave_id=wave_id,
                cls="serving",
                cpu=wave.cpu * rng.choice(_SIZE_FACTORS),
                memory_gib=wave.memory_gib,
                labels={"app": app},
                annotations={},
                priority=wave.priority,
            ))
        if wave.min_available > 0:
            pdb = PodDisruptionBudget(
                metadata=ObjectMeta(name=f"pdb-{wave_id}"),
                selector=LabelSelector(match_labels=(("app", app),)),
                min_available=wave.min_available,
            )
            pdbs.append(pdb)
    elif wave.kind == "batch":
        for i in range(wave.count):
            pods.append(_pod(
                name=f"{wave_id}-{i}",
                wave_id=wave_id,
                cls="batch",
                cpu=wave.cpu * rng.choice(_SIZE_FACTORS),
                memory_gib=wave.memory_gib,
                labels={"app": f"batch-{wave_id}"},
                annotations={},
                priority=wave.priority,
            ))
    else:
        raise ValueError(f"unknown wave kind {wave.kind!r}")
    return pods, pdbs


def gang_of(pod: Pod) -> str:
    return pod.metadata.annotations.get(GANG_ANNOTATION, "")


def gang_min_size(pod: Pod) -> int:
    raw = pod.metadata.annotations.get(GANG_MIN_SIZE_ANNOTATION, "0")
    try:
        return int(raw)
    except ValueError:
        return 0


def workload_class(pod: Pod) -> str:
    return pod.metadata.labels.get(CLASS_LABEL, "other")
