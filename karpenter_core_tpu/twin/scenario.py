"""Scenario model: the twin's input, as data.

A scenario is everything a twin run depends on — seed, cluster count,
virtual duration, workload waves, fault schedules (chaos rates, ICE
storms, fleet-level faults), and test-only hooks — expressed as frozen
dataclasses with a CANONICAL JSON encoding. Canonical means: stable field
names, lists sorted by their natural keys, ``json.dumps(sort_keys=True)``
with fixed separators — so ``scenario_fingerprint`` is a pure function of
the scenario's content and a shrunk repro committed as a fixture replays
byte-for-byte. The GL201/GL202 determinism lint family covers this module
(tools/graftlint/rules/determinism.py): unordered iteration or unsorted
json.dumps in these encoders fails lint, not a code review.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, Tuple

SCENARIO_VERSION = 1


@dataclass(frozen=True)
class WorkloadWave:
    """One arrival wave of a workload class at virtual offset ``at``.

    ``kind`` shapes the pods (twin/workloads.py): ``training`` emits
    gang-annotated pods (``gang_size`` per gang, all-or-nothing),
    ``serving`` emits replica pods under a PodDisruptionBudget
    (``min_available``), ``batch`` emits preemptible filler. ``lifetime``
    schedules the whole wave's deletion (serving churn / batch drain);
    0 keeps it forever. ``max_hops`` >= 0 makes a training wave
    comms-sensitive (topoaware, ISSUE 20): every gang declares that hard
    network-hop bound and every member carries its rank annotation, so
    the solver must place the gang rank-adjacent within the bound; -1
    (default) leaves the gang distance-blind — byte-identical pods to
    the pre-topoaware twin."""

    at: float
    cluster: int
    kind: str  # training | serving | batch
    count: int
    cpu: float = 0.5
    memory_gib: float = 1.0
    gang_size: int = 0
    priority: int = 0
    lifetime: float = 0.0
    min_available: int = 0
    max_hops: int = -1


@dataclass(frozen=True)
class Storm:
    """An ICE window: the head of the cluster's catalog is stocked out in
    the named zones/capacity types during [start, start+duration) of
    virtual time (materialized as chaos.IceStorm against the cluster's
    own catalog; cluster -1 storms every cluster)."""

    start: float
    duration: float
    cluster: int = -1
    head: int = 4
    zones: Tuple[str, ...] = ("zone-a", "zone-b")
    capacity_types: Tuple[str, ...] = ("spot",)


@dataclass(frozen=True)
class FleetFault:
    """A fleet-tier fault at virtual offset ``at``:

    * ``murder`` — member ``member``'s daemon is torn down mid-window (an
      in-flight or subsequent solve sees the transport die) and respawns
      one tick later with empty caches and a fresh instance id;
    * ``partition`` — operator ``cluster`` (-1 = all) cannot reach the
      fleet for ``duration`` virtual seconds (every RPC fails as a
      transport fault: retries, breaker charges, greedy degradation);
    * ``amnesia`` — member ``member``'s segment store forgets everything
      (the delta wire's miss/re-upload handshake must repair it)."""

    at: float
    kind: str  # murder | partition | amnesia
    member: int = 0
    cluster: int = -1
    duration: float = 0.0


@dataclass(frozen=True)
class TestHook:
    """A test-only invariant saboteur (the shrinker demo rides it): at
    virtual offset ``at``, ``lose_bound_pod`` silently deletes one bound
    pod from cluster ``cluster``'s store WITHOUT telling the workload
    bookkeeping — the exact defect shape an operator bug that drops a
    binding would produce, guaranteed to trip pod conservation."""

    at: float
    kind: str  # lose_bound_pod
    cluster: int = 0

    # not a pytest class, despite the Test- name
    __test__ = False


@dataclass(frozen=True)
class Scenario:
    seed: int = 0
    clusters: int = 1
    duration: float = 300.0
    tick: float = 30.0
    solver: str = "greedy"  # greedy | tpu
    # 0 = solves run in-process; N >= 1 = N in-thread solverd members
    # shared by every cluster through a FleetRouter (requires solver=tpu)
    fleet: int = 0
    wire: str = "delta"  # delta | full (fleet mode's request wire)
    # incremental re-solve (incsolve, ISSUE 16): clients name their prior
    # solve's fingerprint on every request so the solverd tier's
    # PackingLedger warm-starts churn-proportional re-solves (requires a
    # fleet tier — the ledger lives daemon-side)
    incremental: bool = False
    # elastic tier (fleetscale, ISSUE 17): the TierAutoscaler sizes the
    # fleet between [fleet_min or 1, fleet_max or fleet] on the virtual
    # clock, from the scenario's own deterministic backlog signal.
    # ``fleet`` stays the STARTING size; fleet faults may then name any
    # member index up to the max bound (out-of-range at fire time skips
    # deterministically — the member it targeted was never grown or was
    # already retired)
    autoscale: bool = False
    fleet_min: int = 0
    fleet_max: int = 0
    # SLO bound doubling as the starvation invariant: an expected pod
    # pending longer than this at a stable tick is a violation
    max_pending: float = 600.0
    # rack topology (topoaware, ISSUE 20): N >= 1 makes every cluster's
    # kwok provider stamp created nodes with deterministic rack (and
    # superpod) labels — racks of N nodes per zone, superpods of two
    # racks — so gang placements become hop-attributable; 0 (default)
    # keeps catalogs rack-less and the whole topo layer disengaged
    rack_size: int = 0
    rates: Dict[str, float] = field(default_factory=dict)
    waves: Tuple[WorkloadWave, ...] = ()
    storms: Tuple[Storm, ...] = ()
    fleet_faults: Tuple[FleetFault, ...] = ()
    hooks: Tuple[TestHook, ...] = ()


def _encode_items(items, cls) -> list:
    """Each dataclass item as a plain dict, the list sorted by the
    dataclass's own field order (natural key = (at/start, ...)): encoding
    order never depends on construction order."""
    names = [f.name for f in dataclasses.fields(cls)]
    rows = []
    for item in sorted(items, key=dataclasses.astuple):
        row = {}
        for name in names:
            value = getattr(item, name)
            row[name] = list(value) if isinstance(value, tuple) else value
        rows.append(row)
    return rows


def encode_scenario(s: Scenario) -> dict:
    return {
        "version": SCENARIO_VERSION,
        "seed": s.seed,
        "clusters": s.clusters,
        "duration": s.duration,
        "tick": s.tick,
        "solver": s.solver,
        "fleet": s.fleet,
        "wire": s.wire,
        "incremental": s.incremental,
        "autoscale": s.autoscale,
        "fleet_min": s.fleet_min,
        "fleet_max": s.fleet_max,
        "max_pending": s.max_pending,
        "rack_size": s.rack_size,
        "rates": dict(sorted(s.rates.items())),
        "waves": _encode_items(s.waves, WorkloadWave),
        "storms": _encode_items(s.storms, Storm),
        "fleet_faults": _encode_items(s.fleet_faults, FleetFault),
        "hooks": _encode_items(s.hooks, TestHook),
    }


def scenario_to_json(s: Scenario) -> str:
    return json.dumps(
        encode_scenario(s), sort_keys=True, separators=(",", ":")
    )


def _decode_items(rows, cls) -> tuple:
    names = {f.name for f in dataclasses.fields(cls)}
    out = []
    for row in rows or []:
        kwargs = {}
        for key in sorted(row):
            if key not in names:
                raise ValueError(
                    f"unknown {cls.__name__} field {key!r} in scenario"
                )
            value = row[key]
            kwargs[key] = tuple(value) if isinstance(value, list) else value
        out.append(cls(**kwargs))
    return tuple(sorted(out, key=dataclasses.astuple))


def decode_scenario(data: dict) -> Scenario:
    version = data.get("version", SCENARIO_VERSION)
    if version != SCENARIO_VERSION:
        raise ValueError(f"unknown scenario version {version!r}")
    known = {f.name for f in dataclasses.fields(Scenario)}
    bogus = sorted(set(data) - known - {"version"})
    if bogus:
        # a typo'd field silently ignored would replay a DIFFERENT
        # scenario than the fixture claims to pin
        raise ValueError(f"unknown scenario field(s) {bogus}")
    s = Scenario(
        seed=int(data.get("seed", 0)),
        clusters=int(data.get("clusters", 1)),
        duration=float(data.get("duration", 300.0)),
        tick=float(data.get("tick", 30.0)),
        solver=data.get("solver", "greedy"),
        fleet=int(data.get("fleet", 0)),
        wire=data.get("wire", "delta"),
        incremental=bool(data.get("incremental", False)),
        autoscale=bool(data.get("autoscale", False)),
        fleet_min=int(data.get("fleet_min", 0)),
        fleet_max=int(data.get("fleet_max", 0)),
        max_pending=float(data.get("max_pending", 600.0)),
        rack_size=int(data.get("rack_size", 0)),
        rates={k: float(v) for k, v in sorted((data.get("rates") or {}).items())},
        waves=_decode_items(data.get("waves"), WorkloadWave),
        storms=_decode_items(data.get("storms"), Storm),
        fleet_faults=_decode_items(data.get("fleet_faults"), FleetFault),
        hooks=_decode_items(data.get("hooks"), TestHook),
    )
    validate_scenario(s)
    return s


def scenario_from_json(text: str) -> Scenario:
    return decode_scenario(json.loads(text))


def validate_scenario(s: Scenario) -> None:
    if s.clusters < 1:
        raise ValueError(f"scenario needs >= 1 cluster, got {s.clusters}")
    if s.duration <= 0 or s.tick <= 0:
        raise ValueError("scenario duration and tick must be positive")
    if s.solver not in ("greedy", "tpu"):
        raise ValueError(f"unknown scenario solver {s.solver!r}")
    if s.wire not in ("delta", "full"):
        raise ValueError(f"unknown scenario wire {s.wire!r}")
    if s.fleet and s.solver != "tpu":
        raise ValueError("a fleet tier requires solver=tpu")
    if s.incremental and not s.fleet:
        # the PackingLedger lives daemon-side; without a solverd tier
        # there is no ledger to warm-start from
        raise ValueError("incremental re-solve requires a fleet tier")
    if s.fleet_min < 0 or s.fleet_max < 0:
        raise ValueError("fleet_min/fleet_max must be >= 0")
    if s.autoscale:
        if not s.fleet:
            raise ValueError("autoscale requires a fleet tier (fleet>=1)")
        mn = s.fleet_min or 1
        mx = s.fleet_max or max(s.fleet, mn)
        if mx < mn:
            raise ValueError(
                f"fleet_max ({mx}) must be >= fleet_min ({mn})"
            )
        if not (mn <= s.fleet <= mx):
            raise ValueError(
                f"starting fleet size {s.fleet} outside"
                f" autoscale bounds [{mn}, {mx}]"
            )
    elif s.fleet_min or s.fleet_max:
        raise ValueError("fleet_min/fleet_max require autoscale")
    if s.rack_size < 0:
        raise ValueError(f"rack_size must be >= 0, got {s.rack_size}")
    def _cluster_in_range(what: str, cluster: int, wildcard: bool) -> None:
        lo = -1 if wildcard else 0  # -1 = every cluster, where allowed
        if not (lo <= cluster < s.clusters):
            raise ValueError(
                f"{what} targets cluster {cluster} outside"
                f" [{lo}, {s.clusters})"
            )

    for wave in s.waves:
        _cluster_in_range(f"wave at t={wave.at}", wave.cluster, False)
        if wave.kind not in ("training", "serving", "batch"):
            raise ValueError(f"unknown wave kind {wave.kind!r}")
        if wave.kind == "training":
            if wave.gang_size < 1:
                raise ValueError("training waves need gang_size >= 1")
            if wave.count < wave.gang_size or wave.count % wave.gang_size:
                # a silent round-up/down would make the scenario file lie
                # about how many pods actually materialize
                raise ValueError(
                    f"training wave count {wave.count} must be a positive"
                    f" multiple of gang_size {wave.gang_size}"
                )
            if not (-1 <= wave.max_hops <= 3):
                # the annotation contract clamps hostile ints server-side;
                # a scenario FILE declaring an impossible bound is a typo,
                # not an adversary — reject it loudly
                raise ValueError(
                    f"training wave max_hops {wave.max_hops} outside"
                    " [-1, 3]"
                )
        elif wave.max_hops != -1:
            raise ValueError(
                f"max_hops only applies to training waves, not {wave.kind!r}"
            )
    for storm in s.storms:
        _cluster_in_range(f"storm at t={storm.start}", storm.cluster, True)
    for fault in s.fleet_faults:
        if fault.kind not in ("murder", "partition", "amnesia"):
            raise ValueError(f"unknown fleet fault kind {fault.kind!r}")
        if not s.fleet:
            raise ValueError("fleet faults require a fleet tier (fleet>=1)")
        # under autoscale the live member set is dynamic, so faults may
        # target any slot up to the max bound; a slot empty at fire time
        # skips deterministically (harness)
        member_bound = (
            max(s.fleet, s.fleet_max or s.fleet) if s.autoscale else s.fleet
        )
        if fault.kind in ("murder", "amnesia") and not (
            0 <= fault.member < member_bound
        ):
            raise ValueError(
                f"fleet fault targets member {fault.member} outside"
                f" [0, {member_bound})"
            )
        if fault.kind == "partition":
            _cluster_in_range(
                f"partition at t={fault.at}", fault.cluster, True
            )
    for hook in s.hooks:
        if hook.kind != "lose_bound_pod":
            raise ValueError(f"unknown test hook kind {hook.kind!r}")
        _cluster_in_range(f"hook at t={hook.at}", hook.cluster, False)


def canonical_scenario(s: Scenario) -> Scenario:
    """The scenario with every collection in its canonical (encoded)
    order. The harness normalizes through this before running, so two
    constructions that differ only in tuple order — which share a
    fingerprint, because the encoder sorts — also share a run."""
    return dataclasses.replace(
        s,
        waves=tuple(sorted(s.waves, key=dataclasses.astuple)),
        storms=tuple(sorted(s.storms, key=dataclasses.astuple)),
        fleet_faults=tuple(sorted(s.fleet_faults, key=dataclasses.astuple)),
        hooks=tuple(sorted(s.hooks, key=dataclasses.astuple)),
        rates={k: v for k, v in sorted(s.rates.items())},
    )


def wave_ids(waves: Tuple[WorkloadWave, ...]) -> list:
    """Stable per-wave identities derived from CONTENT, not position:
    pod names and the wave's child RNG stream key off this, so dropping
    one wave from a scenario (the shrinker) or reordering the tuple (a
    hand-edited fixture) never re-rolls the surviving waves. Identical
    duplicate waves disambiguate by occurrence index — deterministic
    under the canonical order."""
    seen: Dict[str, int] = {}
    out = []
    for wave in waves:
        blob = repr(dataclasses.astuple(wave)).encode()
        base = f"{wave.kind[0]}{hashlib.sha256(blob).hexdigest()[:6]}"
        n = seen.get(base, 0)
        seen[base] = n + 1
        out.append(base if n == 0 else f"{base}x{n}")
    return out


def scenario_fingerprint(s: Scenario) -> str:
    """Content address of the scenario (canonical JSON bytes, sha256/16):
    identical fingerprints MUST replay identical event traces and
    ledgers — the contract the determinism tests pin."""
    digest = hashlib.sha256(scenario_to_json(s).encode()).hexdigest()
    return digest[:16]
