"""Virtual time for the digital twin.

Every TTL/backoff surface in the control plane already takes an injectable
clock (utils/clock.Clock) or time function: the operator's reconcile
backoffs, the ICE cache, validation TTLs, the recorder's dedupe window.
The solver tier's client-side state — circuit-breaker cooldowns, retry
sleeps, poison-quarantine TTLs — takes ``time_fn``/``sleep`` callables
instead. ``VirtualClock`` is one object that serves both shapes, so the
twin can thread a SINGLE virtual timeline through all of them and replay
days of churn in minutes: ``sleep`` advances time instead of spending it,
and ``monotonic`` aliases ``now`` (virtual time never steps backward —
``advance_to`` is monotone by construction).
"""
from __future__ import annotations

from karpenter_core_tpu.utils.clock import FakeClock


class VirtualClock(FakeClock):
    """A steppable clock that also quacks like time.monotonic/time.sleep."""

    def monotonic(self) -> float:
        return self.now()

    def sleep(self, seconds: float) -> None:
        """A virtual sleep costs virtual time, not wall time — retry
        backoffs and Retry-After waits elapse instantly but still ORDER
        correctly against every TTL riding the same clock."""
        if seconds > 0:
            self.step(seconds)

    def advance_to(self, t: float) -> None:
        """Move to absolute virtual time t, never backward (reconcile
        passes may have stepped past a tick boundary while elapsing
        batcher windows or backoffs)."""
        if t > self.now():
            self.set(t)
