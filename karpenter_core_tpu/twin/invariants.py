"""Continuous invariant monitors: what must hold at EVERY stable virtual
tick, no matter which faults fired.

The twin calls ``check`` after each tick's reconcile settles
(run_until_idle), so transient mid-reconcile states never false-positive;
what it asserts is the operator's convergence contract under chaos:

* **pod conservation** — every pod the workload generator created (and
  has not deleted) still exists, is bound to a REAL node, and no expected
  pod starves past the scenario's max_pending SLO bound;
* **capacity** — per-node bound requests within allocatable (cpu+memory);
* **gang atomicity** — a pod group is bound all-or-nothing: at a stable
  tick its bound count is 0 or >= its min size, never a strand;
* **gang distance** — a pod group declaring a hard network-hop bound
  (``pod-group-max-hops``, topoaware ISSUE 20) is never left bound
  PROVABLY wider than it: the monitor re-derives the placement's hop
  bound purely from annotations + node topology labels (the verifier's
  sound lower bound, so a missing rack label can never false-positive);
* **eviction-budget compliance** — no PodDisruptionBudget's healthy count
  sits below its desired-healthy floor once its pods are past the
  settling grace (preemption and consolidation must route around PDBs,
  and an evicted replica must re-bind);
* **verifier rejections** — solver_result_rejected_total must not move:
  a rejection means the device tier produced an untrustworthy packing,
  which is a bug even though the ladder caught it.

A violation is data (virtual timestamp, cluster, invariant, detail), not
an exception: the fuzzer's shrinker needs the run to FINISH and report so
it can minimize the scenario that produced it.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

from karpenter_core_tpu.api.objects import POD_RUNNING, Pod
from karpenter_core_tpu.solver import gangs as gangmod
from karpenter_core_tpu.twin import workloads
from karpenter_core_tpu.utils.pdb import _resolve

_CPU_EPS = 1e-9
_MEM_EPS = 1.0


@dataclass(frozen=True)
class Violation:
    at: float
    cluster: int
    invariant: str  # pod_conservation | capacity | gang_atomicity
    #              | gang_distance | eviction_budget | verifier_rejection
    detail: str

    def encode(self) -> dict:
        return {
            "at": self.at,
            "cluster": self.cluster,
            "invariant": self.invariant,
            "detail": self.detail,
        }


def _rejected_total() -> float:
    from karpenter_core_tpu.metrics import wiring as m

    return sum(m.SOLVER_RESULT_REJECTED.values.values())


class InvariantMonitor:
    def __init__(self, max_pending: float = 600.0, settle_grace: float = 60.0):
        self.max_pending = max_pending
        self.settle_grace = settle_grace
        self.violations: List[Violation] = []
        # metrics are process-global; the monitor judges DELTAS since its
        # own construction so back-to-back twin runs stay independent
        self._rejected_seen = _rejected_total()

    def check(
        self,
        t: float,
        operators: List,
        expected: Dict[int, Dict[str, Pod]],
    ) -> List[Violation]:
        """Run every invariant over every cluster at stable virtual time
        ``t``; returns (and accumulates) the NEW violations."""
        fresh: List[Violation] = []
        for cluster, op in enumerate(operators):
            live = expected.get(cluster, {})
            fresh.extend(self._check_cluster(t, cluster, op, live))
        rejected = _rejected_total()
        if rejected > self._rejected_seen:
            fresh.append(Violation(
                at=t, cluster=-1, invariant="verifier_rejection",
                detail=(
                    f"solver_result_rejected_total moved by"
                    f" {rejected - self._rejected_seen:g}"
                ),
            ))
            self._rejected_seen = rejected
        self.violations.extend(fresh)
        return fresh

    # -- per-cluster checks ------------------------------------------------

    def _check_cluster(
        self, t: float, cluster: int, op, live: Dict[str, Pod]
    ) -> List[Violation]:
        out: List[Violation] = []

        def flag(invariant: str, detail: str) -> None:
            out.append(Violation(
                at=t, cluster=cluster, invariant=invariant, detail=detail
            ))

        nodes = {n.name: n for n in op.kube.list_nodes()}
        pods = {p.name: p for p in op.kube.list_pods()}

        # pod conservation + starvation
        for name in sorted(live):
            pod = pods.get(name)
            if pod is None:
                flag(
                    "pod_conservation",
                    f"expected pod {name} vanished from the store",
                )
                continue
            if pod.node_name and pod.node_name not in nodes:
                flag(
                    "pod_conservation",
                    f"pod {name} bound to ghost node {pod.node_name}",
                )
            elif not pod.node_name:
                age = t - pod.metadata.creation_timestamp
                if age > self.max_pending:
                    flag(
                        "pod_conservation",
                        f"pod {name} pending {age:.0f}s"
                        f" > max_pending {self.max_pending:.0f}s",
                    )

        # per-node capacity (cpu + memory)
        used: Dict[str, Dict[str, float]] = {}
        for name in sorted(pods):
            pod = pods[name]
            if not pod.node_name:
                continue
            acc = used.setdefault(pod.node_name, {"cpu": 0.0, "memory": 0.0})
            acc["cpu"] += pod.resource_requests.get("cpu", 0.0)
            acc["memory"] += pod.resource_requests.get("memory", 0.0)
        for node_name in sorted(used):
            node = nodes.get(node_name)
            if node is None:
                continue  # already flagged as a ghost bind above
            alloc = node.status.allocatable
            if used[node_name]["cpu"] > alloc.get("cpu", 0.0) + _CPU_EPS:
                flag(
                    "capacity",
                    f"node {node_name} cpu {used[node_name]['cpu']:.3f}"
                    f" > allocatable {alloc.get('cpu', 0.0):.3f}",
                )
            if used[node_name]["memory"] > alloc.get("memory", 0.0) + _MEM_EPS:
                flag(
                    "capacity",
                    f"node {node_name} memory over allocatable",
                )

        # gang atomicity over the expected-live gang members
        gangs: Dict[str, List[Pod]] = {}
        for name in sorted(live):
            pod = pods.get(name)
            if pod is None:
                continue
            gang = workloads.gang_of(pod)
            if gang:
                gangs.setdefault(gang, []).append(pod)
        for gang in sorted(gangs):
            members = gangs[gang]
            bound = sum(1 for p in members if p.node_name)
            min_size = max(
                (workloads.gang_min_size(p) for p in members), default=0
            )
            if 0 < bound < min_size:
                flag(
                    "gang_atomicity",
                    f"gang {gang} stranded at {bound}/{len(members)}"
                    f" bound (min {min_size})",
                )
            # gang distance (topoaware): a declared hard hop bound must
            # hold over the bound members' ACTUAL node topology labels —
            # the same sound lower bound the verifier rejects on, so the
            # two layers cannot drift and a missing rack label skips the
            # member instead of manufacturing a violation
            max_hops = gangmod.gang_max_hops_for(members)
            if (
                max_hops is not None
                and max_hops < gangmod.MAX_HOP_DISTANCE
            ):
                placed = [
                    dict(nodes[p.node_name].labels or {})
                    for p in members
                    if p.node_name and p.node_name in nodes
                ]
                worst = gangmod.placement_hop_bound(placed)
                if worst > max_hops:
                    flag(
                        "gang_distance",
                        f"gang {gang} bound across {worst} network hops,"
                        f" above its declared max-hops bound {max_hops}",
                    )

        # eviction-budget compliance: PDB healthy floor at stable ticks
        for pdb in sorted(op.kube.list_pdbs(), key=lambda b: b.name):
            if pdb.selector is None or pdb.min_available is None:
                continue
            matching = [
                pods[name]
                for name in sorted(live)
                if name in pods
                and pdb.selector.matches(pods[name].metadata.labels)
            ]
            if not matching:
                continue
            youngest = max(
                p.metadata.creation_timestamp for p in matching
            )
            if t - youngest < self.settle_grace:
                continue  # the wave is still settling; starvation covers it
            healthy = sum(1 for p in matching if p.phase == POD_RUNNING)
            desired = min(
                _resolve(pdb.min_available, len(matching), round_up=True),
                len(matching),
            )
            if healthy < desired:
                flag(
                    "eviction_budget",
                    f"pdb {pdb.name} healthy {healthy} <"
                    f" desired {desired} of {len(matching)}",
                )
        return out
