"""DigitalTwin: N simulated clusters + one solverd tier, one virtual
timeline, every fault seam scripted — the closed loop, compressed.

Each cluster is a full ``Operator`` (its own KubeStore, kwok provider
with a DISTINCT catalog, chaos-wrapped kube/cloud seams) sharing one
``VirtualClock``; with ``scenario.fleet`` > 0 the solve path runs through
a REAL fleetd tier — in-thread solverd daemons behind HTTP, each
operator's ``FleetRouter`` doing digest-affinity placement over them —
whose client-side state (breaker cooldowns, retry sleeps, quarantine
TTLs) rides the same virtual clock via the operator's ``solver_client``
injection seam. Fleet-level faults compose on top of the chaos harness:

* ``murder``    — a member's server is torn down (transport dies under
  the client), respawning one tick later with a fresh daemon: empty
  segment store, cold caches, new instance id — the client must pay one
  miss/re-upload round and nothing else;
* ``partition`` — an operator's view of the whole tier fails as
  transport faults for a window (degrade-to-greedy, quarantine strikes,
  never a lost pod);
* ``amnesia``   — a member's segment store is swapped empty in place.

Determinism contract: identical (seed, scenario) → byte-identical event
trace and ledger JSON. Everything that could differ between two runs of
one process — claim-name and uid counters, ephemeral port numbers,
process-global metric absolutes — is reset, scrubbed, or delta'd.
"""
from __future__ import annotations

import itertools
import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.api.nodepool import NodePool, NodePoolSpec
from karpenter_core_tpu.api.objects import ObjectMeta, Pod
from karpenter_core_tpu.chaos import (
    ChaosCloudProvider,
    ChaosKubeClient,
    ChaosSchedule,
    IceStorm,
    fold_seed,
)
from karpenter_core_tpu.cloudprovider.kwok import KwokCloudProvider, build_catalog
from karpenter_core_tpu.cloudprovider.types import OfferingKey
from karpenter_core_tpu.kube.store import KubeStore
from karpenter_core_tpu.operator import Operator, Options
from karpenter_core_tpu.twin import workloads
from karpenter_core_tpu.twin.clock import VirtualClock
from karpenter_core_tpu.twin.invariants import InvariantMonitor, Violation
from karpenter_core_tpu.twin.ledger import Ledger, price_index
from karpenter_core_tpu.twin.scenario import (
    Scenario,
    canonical_scenario,
    scenario_fingerprint,
    validate_scenario,
    wave_ids,
)

# every twin run starts its virtual timeline here (FakeClock's epoch):
# absolute virtual timestamps are deterministic because the origin is
TWIN_EPOCH = 1_000_000.0

# ephemeral ports differ between runs; the trace must not
_PORT_RE = re.compile(r"127\.0\.0\.1:\d+")


def _scrub(text: str) -> str:
    return _PORT_RE.sub("127.0.0.1:<port>", text)


def _counter_total(counter) -> float:
    return sum(counter.values.values())


def _metric_snapshot() -> Dict[str, float]:
    from karpenter_core_tpu.metrics import wiring as m

    return {
        "rpc_fallbacks": _counter_total(m.SOLVER_RPC_FALLBACKS),
        "result_rejected": _counter_total(m.SOLVER_RESULT_REJECTED),
        "host_fallback_pods": _counter_total(m.SOLVER_HOST_FALLBACK_PODS),
        "preemption_evictions": _counter_total(m.SOLVER_PREEMPTION_EVICTIONS),
        # incsolve (ISSUE 16): warm/partial replays actually served — the
        # drift-judge tests gate on these to stay non-vacuous
        "incremental_warm": (
            m.SOLVER_INCREMENTAL.values.get((("outcome", "warm"),), 0.0)
            + m.SOLVER_INCREMENTAL.values.get((("outcome", "partial"),), 0.0)
        ),
        "incremental_total": _counter_total(m.SOLVER_INCREMENTAL),
    }


def _reset_identity_counters() -> None:
    """Claim names and object uids draw from process-global counters; two
    runs of one scenario in one process must mint identical identities
    (the test_chaos _reset_claim_counter precedent, widened)."""
    from karpenter_core_tpu.api import objects as apiobjects
    from karpenter_core_tpu.controllers.provisioning.scheduling import (
        nodeclaimtemplate,
    )

    apiobjects._uid_counter = itertools.count(1)
    nodeclaimtemplate._claim_counter = itertools.count(1)


def cluster_catalog(i: int):
    """Distinct per-cluster instance catalogs (different cpu grids and
    memory families), so the tier's prepared-state caches and the delta
    wire's segment stores see N genuinely different problem halves."""
    grids = ([1, 2, 4, 8, 16], [2, 4, 8, 16, 32], [1, 2, 4, 8, 16, 32])
    mems = ([2, 4], [4, 8], [2, 8])
    return build_catalog(
        cpu_grid=list(grids[i % 3]), mem_factors=list(mems[i % 3])
    )


@dataclass
class TwinResult:
    scenario: Scenario
    fingerprint: str
    violations: List[Violation]
    ledger: Ledger
    trace: List[tuple]
    counters: Dict[str, float] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def trace_json(self) -> str:
        return json.dumps(
            [list(entry) for entry in self.trace], separators=(",", ":")
        )

    def ledger_json(self) -> str:
        return self.ledger.to_json()

    def first_violation(self) -> Optional[Violation]:
        return self.violations[0] if self.violations else None


class _FleetTier:
    """The shared solverd tier: in-thread daemons behind real HTTP, plus
    the murder/respawn/amnesia machinery. In-thread (not subprocess) so a
    tier-1 twin run costs no spawn latency and stays deterministic; the
    transport, codec, gateway and caches are the production objects."""

    def __init__(self, n: int, vclock: VirtualClock):
        from karpenter_core_tpu.solver import fleet as fleetmod
        from karpenter_core_tpu.solver import service

        self._fleetmod = fleetmod
        self._service = service
        self.vclock = vclock
        self.daemons: List = []
        self.servers: List = []
        self.addrs: List[str] = []
        # stable member identities surviving index shifts under elastic
        # resize (fleetscale, ISSUE 17): ids are never reused, so the
        # routers' rendezvous ranks and the utilization ledger never
        # alias a retired member's successor
        self.member_ids: List[str] = []
        self._next = 0
        self.member_solves: Dict[str, int] = {}
        for _ in range(n):
            self.grow()

    def grow(self) -> int:
        """Spawn one fresh member (autoscaler scale-up actuator); returns
        its index in the live member list."""
        daemon, srv, addr = self._spawn()
        self.daemons.append(daemon)
        self.servers.append(srv)
        self.addrs.append(addr)
        self.member_ids.append(str(self._next))
        self._next += 1
        return len(self.daemons) - 1

    def retire(self, i: int) -> None:
        """Crash-only scale-down, in-thread: flush the member's queue
        (each queued request answers 503 — the faultless drain path),
        close its socket, drop it from the live set. Indices above i
        shift down, exactly like FleetRouter.remove_member — the run
        keeps the two aligned."""
        if self.servers[i] is not None:
            self._bank_solves(i)
            self.daemons[i].drain()
            self.servers[i].shutdown()
            self.servers[i].server_close()
        self.daemons.pop(i)
        self.servers.pop(i)
        self.addrs.pop(i)
        self.member_ids.pop(i)

    def live_count(self) -> int:
        return sum(1 for srv in self.servers if srv is not None)

    def _spawn(self):
        daemon = self._service.SolverDaemon(
            quarantine=self._fleetmod.PoisonQuarantine(
                site="gateway", time_fn=self.vclock.monotonic
            ),
        )
        srv = self._service.serve(0, daemon=daemon)
        addr = f"127.0.0.1:{srv.server_address[1]}"
        return daemon, srv, addr

    def murder(self, i: int) -> None:
        """Tear the member down: its socket closes under any client."""
        self._bank_solves(i)
        self.servers[i].shutdown()
        self.servers[i].server_close()
        self.servers[i] = None

    def respawn(self, i: int, routers: List) -> None:
        """Fresh daemon (empty segment store, cold caches, new instance
        id) on a fresh port; every operator's router re-points, exactly
        as reconcile_once does after a FleetSupervisor restart."""
        daemon, srv, addr = self._spawn()
        self.daemons[i] = daemon
        self.servers[i] = srv
        self.addrs[i] = addr
        for router in routers:
            router.set_member_addr(i, addr)

    def amnesia(self, i: int) -> None:
        from karpenter_core_tpu.solver import segments

        self.daemons[i].segment_store = segments.SegmentStore()

    def _bank_solves(self, i: int) -> None:
        mid = self.member_ids[i]
        self.member_solves[mid] = (
            self.member_solves.get(mid, 0) + self.daemons[i].solves
        )

    def utilization(self) -> Dict[str, int]:
        # keyed by stable member id: a retired member's banked solves
        # survive it leaving the live list
        out: Dict[str, int] = dict(self.member_solves)
        for i, daemon in enumerate(self.daemons):
            if self.servers[i] is not None:
                mid = self.member_ids[i]
                out[mid] = out.get(mid, 0) + daemon.solves
        return out

    def stop(self) -> None:
        for srv in self.servers:
            if srv is not None:
                srv.shutdown()
                srv.server_close()


class _TwinTierAdapter:
    """The TierAutoscaler's tier surface over the in-thread fleet,
    DETERMINISTIC by construction: production SpawnedTier reads wall-time
    queue-wait percentiles off /statz, which two replays of one scenario
    would never reproduce byte-for-byte — so the twin derives pressure
    from the scenario's own state instead (expected-but-unbound pods per
    live member, a pure function of the virtual timeline). Scale-up grows
    a member and hands every cluster's router a gated virtual-clock
    client; scale-down retires through the in-thread drain path with the
    router un-routed FIRST, same ordering as production."""

    # how many backlogged pods one member absorbs per tick before the
    # tier counts as over budget (pressure 1.0)
    PODS_PER_MEMBER = 8.0

    def __init__(self, tier: _FleetTier, routers, new_clients, backlog_fn):
        self.tier = tier
        self.routers = routers
        self.new_clients = new_clients  # (addr, member_id) -> [client/router]
        self.backlog_fn = backlog_fn

    def observe(self):
        from karpenter_core_tpu.solver.autoscale import (
            MemberSignal,
            TierSignals,
        )

        members = [
            MemberSignal(
                member=mid, draining=self.tier.servers[i] is None
            )
            for i, mid in enumerate(self.tier.member_ids)
        ]
        live = sum(1 for ms in members if not ms.draining) or 1
        pressure = self.backlog_fn() / (live * self.PODS_PER_MEMBER)
        return TierSignals(members=members, pressure=pressure, storm=False)

    def scale_up(self) -> None:
        idx = self.tier.grow()
        addr = self.tier.addrs[idx]
        mid = self.tier.member_ids[idx]
        for router, client in zip(self.routers, self.new_clients(addr, mid)):
            router.add_member(client, member_id=mid)

    def scale_down(self, index: int) -> None:
        for router in self.routers:
            router.remove_member(index)
        self.tier.retire(index)

    def set_rung(self, rung: int) -> None:
        for i, daemon in enumerate(self.tier.daemons):
            if self.tier.servers[i] is not None:
                daemon.set_brownout(rung)


class DigitalTwin:
    def __init__(self, scenario: Scenario, reconcile_iters: int = 300):
        validate_scenario(scenario)
        # canonical collection order: constructions that share a
        # fingerprint (the encoder sorts) must also share a run
        self.scenario = canonical_scenario(scenario)
        self.reconcile_iters = reconcile_iters

    # -- construction ------------------------------------------------------

    def _member_client(self, cluster: int, addr: str, member: str, vclock):
        """One cluster's client for one tier member: virtual-clock
        breaker, partition gate — shared by founding members and any the
        autoscaler grows later."""
        from karpenter_core_tpu.solver.remote import SolverClient

        client = SolverClient(
            addr,
            timeout=30.0,
            tenant=f"c{cluster}",
            wire_mode=self.scenario.wire,
            member=member,
            sleep=vclock.sleep,
        )
        # the client's fault-tolerance state rides VIRTUAL time: a
        # breaker cooldown or quarantine TTL elapses with the
        # scenario, not with the wall — days of churn in minutes
        client.breaker.time_fn = vclock.monotonic
        self._install_partition_gate(cluster, client)
        return client

    def _make_router(self, cluster: int, tier: _FleetTier, vclock):
        from karpenter_core_tpu.solver.fleet import PoisonQuarantine
        from karpenter_core_tpu.solver.remote import FleetRouter

        # autoscaled tiers label members even at a starting size of 1:
        # the set is about to change and rendezvous ranks key off ids
        labeled = len(tier.addrs) > 1 or self.scenario.autoscale
        members = [
            self._member_client(
                cluster, addr, tier.member_ids[j] if labeled else "", vclock
            )
            for j, addr in enumerate(tier.addrs)
        ]
        return FleetRouter(
            members,
            tenant=f"c{cluster}",
            quarantine=PoisonQuarantine(
                site="client", time_fn=vclock.monotonic
            ),
        )

    def _install_partition_gate(self, cluster: int, client) -> None:
        from karpenter_core_tpu.solver.remote import RemoteSolverError

        def active() -> bool:
            offset = self._vclock.now() - TWIN_EPOCH
            for fault in self.scenario.fleet_faults:
                if fault.kind != "partition":
                    continue
                if fault.cluster not in (-1, cluster):
                    continue
                if fault.at <= offset < fault.at + fault.duration:
                    return True
            return False

        orig = client.call

        def gated(*args, _orig=orig, **kwargs):
            if active():
                raise RemoteSolverError(
                    "error", "twin: operator-fleet partition window"
                )
            return _orig(*args, **kwargs)

        client.call = gated

    def _make_operator(
        self, cluster: int, vclock, tier: Optional[_FleetTier]
    ) -> Tuple[Operator, KubeStore, ChaosSchedule]:
        s = self.scenario
        catalog = cluster_catalog(cluster)
        schedule = ChaosSchedule(
            seed=fold_seed(s.seed, f"cluster{cluster}"),
            rates=dict(s.rates),
        )
        store = KubeStore(vclock)
        storms = []
        for storm in s.storms:
            if storm.cluster not in (-1, cluster):
                continue
            storms.append(IceStorm(
                start=TWIN_EPOCH + storm.start,
                duration=storm.duration,
                offerings=tuple(
                    OfferingKey(it.name, zone, ct)
                    for it in catalog[: storm.head]
                    for zone in storm.zones
                    for ct in storm.capacity_types
                ),
            ))
        provider = ChaosCloudProvider(
            KwokCloudProvider(store, catalog, rack_size=s.rack_size),
            schedule,
            storms=storms,
            clock=vclock,
        )
        kube = ChaosKubeClient(store, schedule)
        if tier is not None:
            options = Options(
                solver="tpu",
                solver_mode="sidecar",
                solver_tenant=f"c{cluster}",
                solver_wire=s.wire,
                # incsolve (ISSUE 16): the client names its prior solve's
                # fingerprint on every request; the tier's PackingLedger
                # replays the unchanged half of last round's packing
                device_scheduler_opts=(
                    {"incremental": True} if s.incremental else {}
                ),
            )
            client = self._make_router(cluster, tier, vclock)
        else:
            options = Options(solver=s.solver)
            client = None
        op = Operator(
            kube=kube,
            cloud_provider=provider,
            clock=vclock,
            options=options,
            solver_client=client,
        )
        pool = NodePool(metadata=ObjectMeta(name="default"))
        pool.spec = NodePoolSpec()
        store.create(pool)
        return op, store, schedule

    # -- the run -----------------------------------------------------------

    def run(self) -> TwinResult:
        s = self.scenario
        _reset_identity_counters()
        vclock = VirtualClock(TWIN_EPOCH)
        self._vclock = vclock
        tier = _FleetTier(s.fleet, vclock) if s.fleet else None
        notes: List[tuple] = []
        note_seq = itertools.count()

        def note(kind: str, detail: str) -> None:
            notes.append((
                round(vclock.now() - TWIN_EPOCH, 3),
                "twin",
                next(note_seq),
                kind,
                _scrub(detail),
            ))

        operators: List[Operator] = []
        stores: List[KubeStore] = []
        schedules: List[ChaosSchedule] = []
        routers: List = []
        try:
            for i in range(s.clusters):
                op, store, schedule = self._make_operator(i, vclock, tier)
                operators.append(op)
                stores.append(store)
                schedules.append(schedule)
                if tier is not None:
                    routers.append(op.solver_client)

            price_indices = {
                i: price_index(cluster_catalog(i)) for i in range(s.clusters)
            }
            monitor = InvariantMonitor(max_pending=s.max_pending)
            ledger = Ledger()
            baseline = _metric_snapshot()
            expected: Dict[int, Dict[str, Pod]] = {
                i: {} for i in range(s.clusters)
            }
            wave_names: Dict[str, List[str]] = {}
            bound_seen: Dict[int, set] = {i: set() for i in range(s.clusters)}
            active_partitions: set = set()
            down_members: Dict[str, float] = {}  # member id -> respawn due

            autoscaler = None
            if s.autoscale and tier is not None:
                from karpenter_core_tpu.solver.autoscale import (
                    TierAutoscaler,
                )

                def _backlog() -> float:
                    # expected-but-unbound pods across every cluster: the
                    # deterministic demand signal (wall-free, replayable)
                    total = 0
                    for i in range(s.clusters):
                        for name in expected[i]:
                            pod = stores[i].get(Pod, name)
                            if pod is not None and not pod.node_name:
                                total += 1
                    return float(total)

                def _new_clients(addr: str, mid: str):
                    return [
                        self._member_client(i, addr, mid, vclock)
                        for i in range(len(routers))
                    ]

                autoscaler = TierAutoscaler(
                    _TwinTierAdapter(tier, routers, _new_clients, _backlog),
                    s.fleet_min or 1,
                    s.fleet_max or max(s.fleet, s.fleet_min or 1),
                    # hysteresis in TICKS of virtual time: react after one
                    # over-budget tick, relax after two quiet ones, with a
                    # longer scale-down cooldown (the production shape,
                    # compressed to the scenario's timescale)
                    up_stable=1,
                    down_stable=2,
                    up_cooldown_s=s.tick,
                    down_cooldown_s=2 * s.tick,
                    rung_up_stable=1,
                    rung_down_stable=2,
                    time_fn=lambda: vclock.now() - TWIN_EPOCH,
                    on_decision=lambda action, arg: note(
                        "autoscale", f"{action} {arg}"
                    ),
                )

            # the timeline: (due offset, kind order, seq) -> action.
            # Wave identity is CONTENT-derived (scenario.wave_ids): pod
            # names/RNG streams survive sibling waves being dropped or
            # reordered
            ids = wave_ids(s.waves)
            events: List[tuple] = []
            for wi, wave in enumerate(s.waves):
                events.append((wave.at, 0, wi, "wave", wave))
                if wave.lifetime > 0:
                    events.append(
                        (wave.at + wave.lifetime, 1, wi, "delete_wave", wave)
                    )
            for fi, fault in enumerate(s.fleet_faults):
                if fault.kind in ("murder", "amnesia"):
                    events.append((fault.at, 2, fi, fault.kind, fault))
            for hi, hook in enumerate(s.hooks):
                events.append((hook.at, 3, hi, hook.kind, hook))
            events.sort(key=lambda e: e[:3])
            cursor = 0

            n_ticks = max(int(-(-s.duration // s.tick)), 1)
            prev_t = 0.0
            for k in range(1, n_ticks + 1):
                t = min(k * s.tick, s.duration)
                vclock.advance_to(TWIN_EPOCH + t)
                # respawn members whose murder window elapsed (looked up
                # by stable id — a scale-down may have shifted indices)
                for mid in sorted(down_members):
                    if down_members[mid] <= t:
                        del down_members[mid]
                        if mid in tier.member_ids:
                            tier.respawn(tier.member_ids.index(mid), routers)
                            note("respawn", f"fleet member {mid} respawned")
                # apply everything due by this tick
                while cursor < len(events) and events[cursor][0] <= t:
                    _, _, idx, kind, payload = events[cursor]
                    cursor += 1
                    if kind == "wave":
                        self._apply_wave(
                            payload, ids[idx], stores, expected, wave_names
                        )
                        note("wave", (
                            f"cluster {payload.cluster}: {payload.kind}"
                            f" wave {ids[idx]} x{payload.count}"
                        ))
                    elif kind == "delete_wave":
                        self._delete_wave(
                            payload, ids[idx], stores, expected, wave_names
                        )
                        note("delete_wave", (
                            f"cluster {payload.cluster}: wave {ids[idx]}"
                            " retired"
                        ))
                    elif kind == "murder":
                        # under autoscale the index targets the CURRENT
                        # live list; an empty slot (never grown, already
                        # retired) skips deterministically
                        if payload.member < len(tier.member_ids) and (
                            tier.servers[payload.member] is not None
                        ):
                            mid = tier.member_ids[payload.member]
                            tier.murder(payload.member)
                            down_members[mid] = t + s.tick
                            note("murder", (
                                f"fleet member {mid} murdered"
                            ))
                    elif kind == "amnesia":
                        if payload.member < len(tier.member_ids) and (
                            tier.servers[payload.member] is not None
                        ):
                            mid = tier.member_ids[payload.member]
                            tier.amnesia(payload.member)
                            note("amnesia", (
                                f"fleet member {mid} segment"
                                " store wiped"
                            ))
                    elif kind == "lose_bound_pod":
                        self._apply_lose_pod(payload, stores, expected, note)
                # partition window edges, at tick granularity
                now_active = set()
                for fi, fault in enumerate(s.fleet_faults):
                    if fault.kind != "partition":
                        continue
                    if fault.at <= t < fault.at + fault.duration:
                        now_active.add(fi)
                for fi in sorted(now_active - active_partitions):
                    note("partition_start", (
                        f"cluster {s.fleet_faults[fi].cluster} partitioned"
                        " from the fleet"
                    ))
                for fi in sorted(active_partitions - now_active):
                    note("partition_end", "partition healed")
                active_partitions = now_active

                # autoscaler step BEFORE the settle: the tier resizes on
                # the backlog the tick arrived with, then the operators
                # solve against the resized tier (one control period per
                # tick, riding the virtual clock)
                if autoscaler is not None:
                    autoscaler.step()

                # one closed-loop settle per cluster
                for op in operators:
                    op.run_until_idle(max_iters=self.reconcile_iters)

                # SLO accounting: first tick each expected pod shows bound
                for i, op in enumerate(operators):
                    live = expected[i]
                    for name in sorted(live):
                        if name in bound_seen[i]:
                            continue
                        pod = op.kube.get(Pod, name)
                        if pod is None or not pod.node_name:
                            continue
                        bound_seen[i].add(name)
                        latency = (
                            vclock.now() - pod.metadata.creation_timestamp
                        )
                        ledger.record_bind(
                            workloads.workload_class(pod), latency
                        )
                        if latency > s.max_pending:
                            ledger.slo_misses += 1

                monitor.check(vclock.now(), operators, expected)
                ledger.sample(
                    t - prev_t, operators, price_indices,
                    tier_members=tier.live_count() if tier else 0,
                )
                prev_t = t

            after = _metric_snapshot()
            delta = {
                key: after[key] - baseline[key] for key in sorted(baseline)
            }
            ledger.preemption_evictions = int(delta["preemption_evictions"])
            ledger.utilization = {
                "chaos_draws": {
                    str(i): schedules[i].draws for i in range(s.clusters)
                },
                # faults that actually FIRED (draws count every call,
                # faulted or ok — a non-vacuousness gate needs these)
                "chaos_injected": {
                    str(i): (
                        sum(operators[i].kube.injected.values())
                        + sum(
                            operators[i].cloud_provider.injected.values()
                        )
                    )
                    for i in range(s.clusters)
                },
                "rpc_fallbacks": delta["rpc_fallbacks"],
                "host_fallback_pods": delta["host_fallback_pods"],
            }
            if tier is not None:
                ledger.utilization["member_solves"] = tier.utilization()

            trace = self._merge_trace(notes, operators)
            return TwinResult(
                scenario=s,
                fingerprint=scenario_fingerprint(s),
                violations=list(monitor.violations),
                ledger=ledger,
                trace=trace,
                counters=delta,
            )
        finally:
            for op in operators:
                op.shutdown()
            if tier is not None:
                tier.stop()

    # -- event application -------------------------------------------------

    def _apply_wave(self, wave, wave_id, stores, expected, wave_names):
        pods, pdbs = workloads.pods_for_wave(
            wave, wave_id, self.scenario.seed
        )
        store = stores[wave.cluster]
        names = []
        for pdb in pdbs:
            store.create(pdb)
        for pod in pods:
            store.create(pod)
            expected[wave.cluster][pod.name] = pod
            names.append(pod.name)
        wave_names[wave_id] = names

    def _delete_wave(self, wave, wave_id, stores, expected, wave_names):
        store = stores[wave.cluster]
        for name in wave_names.get(wave_id, []):
            pod = store.get(Pod, name)
            if pod is not None:
                store.delete(pod)
            expected[wave.cluster].pop(name, None)
        from karpenter_core_tpu.api.objects import PodDisruptionBudget

        pdb = store.get(PodDisruptionBudget, f"pdb-{wave_id}")
        if pdb is not None:
            store.delete(pdb)

    def _apply_lose_pod(self, hook, stores, expected, note) -> None:
        """The test-only invariant saboteur: silently drop one bound pod
        from the store, leaving the workload bookkeeping convinced it
        still exists — pod conservation MUST catch this."""
        store = stores[hook.cluster]
        for name in sorted(expected[hook.cluster]):
            pod = store.get(Pod, name)
            if pod is not None and pod.node_name:
                store.delete(pod)
                note("lose_bound_pod", f"test hook dropped bound pod {name}")
                return

    # -- trace -------------------------------------------------------------

    def _merge_trace(self, notes: List[tuple], operators) -> List[tuple]:
        entries: List[tuple] = list(notes)
        for i, op in enumerate(operators):
            for seq, event in enumerate(op.recorder.events):
                entries.append((
                    round(event.timestamp - TWIN_EPOCH, 3),
                    f"cluster{i}",
                    seq,
                    f"{event.type}/{event.reason}",
                    _scrub(f"{event.involved_object}: {event.message}"),
                ))
        entries.sort(key=lambda e: (e[0], str(e[1]), e[2]))
        return entries


def run_scenario(scenario: Scenario, **kwargs) -> TwinResult:
    return DigitalTwin(scenario, **kwargs).run()
