"""The twin's judge: $-cost, SLO, preemption burn and tier utilization
accumulated over VIRTUAL time.

Every per-solve microbench so far reports p50s; the ledger reports what
the paper's closed loop actually buys — the integral of fleet node cost
over time, time-to-bind percentiles per workload class, how much
preemption budget the run burned, and how the solver tier's work spread
across members. Everything here is derived from virtual timestamps and
deterministic counts, NEVER wall time or process-global metric absolutes
(metric deltas are taken by the harness against run-start baselines), so
``to_json`` is byte-identical across two runs of one scenario — the
determinism contract the twin's tests pin alongside the event trace.

GL201/GL202 cover this module's encode path: unordered iteration in the
serialization would silently break that contract.
"""
from __future__ import annotations

import json
from typing import Dict, List

from karpenter_core_tpu.api import labels as apilabels

SECONDS_PER_HOUR = 3600.0


def price_index(catalog) -> Dict[tuple, float]:
    """(instance_type, zone, capacity_type) -> $/hour over one catalog."""
    prices: Dict[tuple, float] = {}
    for it in catalog:
        for offering in it.offerings:
            prices[tuple(offering.key(it.name))] = offering.price
    return prices


def node_price(node, prices: Dict[tuple, float]) -> float:
    key = (
        node.labels.get(apilabels.LABEL_INSTANCE_TYPE, ""),
        node.labels.get(apilabels.LABEL_TOPOLOGY_ZONE, ""),
        node.labels.get(apilabels.CAPACITY_TYPE_LABEL_KEY, ""),
    )
    return prices.get(key, 0.0)


def _percentile(sorted_values: List[float], q: float) -> float:
    """Deterministic nearest-rank percentile over an already-sorted list."""
    if not sorted_values:
        return 0.0
    rank = max(int(q * len(sorted_values) + 0.5) - 1, 0)
    return sorted_values[min(rank, len(sorted_values) - 1)]


class Ledger:
    def __init__(self):
        # cluster -> accumulated $·hours (price integral over virtual time)
        self.cost_dollar_hours: Dict[int, float] = {}
        # cluster -> peak concurrent nodes seen at any tick
        self.peak_nodes: Dict[int, int] = {}
        # cluster -> accumulated node·seconds (node-count integral over
        # virtual time): the node-quality surface the incremental
        # re-solve drift judge compares against a fresh-solve twin run
        # (incsolve, ISSUE 16 — mean_nodes = node_seconds / duration)
        self.node_seconds: Dict[int, float] = {}
        # workload class -> list of time-to-bind seconds (virtual)
        self.bind_latencies: Dict[str, List[float]] = {}
        self.ticks = 0
        self.virtual_seconds = 0.0
        # elastic solver tier (fleetscale, ISSUE 17): member-count
        # integral over virtual time — the tier-$ half of the drift
        # judge's node-$ + tier-$ score against a fixed-size control
        # (mean_members = member_seconds / duration)
        self.member_seconds = 0.0
        self.peak_members = 0
        # gang network spread (topoaware, ISSUE 20): per-cluster PEAK
        # intra-gang hop distance over rack-attributable bound members,
        # and gang·ticks spent straggler-exposed (a gang spanning >= 2
        # hops — beyond one superpod — at a stable tick). Recorded per
        # run; stragglers-AVOIDED is the delta against a distance-blind
        # control run of the same scenario (bench cfg18 / twin tests).
        # Rack-less runs record {} / 0 — nothing to attribute.
        self.gang_max_hops: Dict[int, int] = {}
        self.straggler_gang_ticks = 0
        # filled by the harness at finish() from metric deltas/tier state
        self.preemption_evictions = 0
        self.slo_misses = 0
        self.utilization: Dict[str, object] = {}

    # -- accumulation ------------------------------------------------------

    def sample(
        self, dt: float, operators, price_indices, tier_members: int = 0
    ) -> None:
        """One tick's cost integral: each cluster's live nodes priced from
        ITS catalog, charged for dt virtual seconds; the solver tier's
        live member count charged the same way (member·seconds)."""
        self.ticks += 1
        self.virtual_seconds += dt
        self.member_seconds += tier_members * dt
        self.peak_members = max(self.peak_members, tier_members)
        for cluster, op in enumerate(operators):
            prices = price_indices[cluster]
            nodes = op.kube.list_nodes()
            rate = sum(node_price(n, prices) for n in nodes)
            self.cost_dollar_hours[cluster] = (
                self.cost_dollar_hours.get(cluster, 0.0)
                + rate * dt / SECONDS_PER_HOUR
            )
            self.peak_nodes[cluster] = max(
                self.peak_nodes.get(cluster, 0), len(nodes)
            )
            self.node_seconds[cluster] = (
                self.node_seconds.get(cluster, 0.0) + len(nodes) * dt
            )
            self._sample_gang_hops(cluster, op, nodes)

    def _sample_gang_hops(self, cluster: int, op, nodes) -> None:
        """One tick's gang network spread: max pairwise hop distance per
        bound gang, measured over members on rack-labeled nodes only (on
        a rack-less catalog there is nothing to attribute, so legacy
        runs' ledgers gain only constant keys)."""
        from karpenter_core_tpu.solver import gangs as gangmod
        from karpenter_core_tpu.twin import workloads

        by_name = {n.name: n for n in nodes}
        placements: Dict[str, List[dict]] = {}
        for pod in op.kube.list_pods():
            if not pod.node_name:
                continue
            gang = workloads.gang_of(pod)
            node = by_name.get(pod.node_name)
            if not gang or node is None:
                continue
            labels = dict(node.labels or {})
            if labels.get(apilabels.LABEL_TOPOLOGY_RACK):
                placements.setdefault(gang, []).append(labels)
        for gang in sorted(placements):
            placed = placements[gang]
            if len(placed) < 2:
                continue
            worst = max(
                gangmod.hop_distance(a, b)
                for i, a in enumerate(placed)
                for b in placed[i + 1:]
            )
            self.gang_max_hops[cluster] = max(
                self.gang_max_hops.get(cluster, 0), worst
            )
            if worst >= 2:
                self.straggler_gang_ticks += 1

    def record_bind(self, workload_class: str, latency_s: float) -> None:
        self.bind_latencies.setdefault(workload_class, []).append(latency_s)

    # -- reporting ---------------------------------------------------------

    def slo(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for cls in sorted(self.bind_latencies):
            values = sorted(self.bind_latencies[cls])
            out[cls] = {
                "n": len(values),
                "p50_s": round(_percentile(values, 0.50), 6),
                "p95_s": round(_percentile(values, 0.95), 6),
                "max_s": round(values[-1], 6) if values else 0.0,
            }
        return out

    def encode(self) -> dict:
        """Canonical ledger dict (stable keys, sorted iteration, rounded
        floats): the byte-determinism surface."""
        return {
            "cost_dollar_hours": {
                str(cluster): round(self.cost_dollar_hours[cluster], 6)
                for cluster in sorted(self.cost_dollar_hours)
            },
            "peak_nodes": {
                str(cluster): self.peak_nodes[cluster]
                for cluster in sorted(self.peak_nodes)
            },
            "node_seconds": {
                str(cluster): round(self.node_seconds[cluster], 6)
                for cluster in sorted(self.node_seconds)
            },
            "gang_max_hops": {
                str(cluster): self.gang_max_hops[cluster]
                for cluster in sorted(self.gang_max_hops)
            },
            "straggler_gang_ticks": self.straggler_gang_ticks,
            "slo": self.slo(),
            "slo_misses": self.slo_misses,
            "preemption_evictions": self.preemption_evictions,
            "utilization": self.utilization,
            "ticks": self.ticks,
            "virtual_seconds": round(self.virtual_seconds, 6),
            "member_seconds": round(self.member_seconds, 6),
            "peak_members": self.peak_members,
        }

    def to_json(self) -> str:
        return json.dumps(
            self.encode(), sort_keys=True, separators=(",", ":")
        )
