"""Scenario fuzzing + failing-scenario shrinking.

The robustness payoff of the twin: ``fuzz`` sweeps seeds over a scenario
shape; when any run trips an invariant, ``shrink`` minimizes the scenario
while the SAME invariant keeps tripping — dropping the fleet tier, spare
clusters, fault events, storms, workload waves and rate keys, halving
wave sizes, truncating the schedule to just past the first violation —
and the minimal scenario serializes to a JSON repro (``save_repro``) that
``replay`` re-runs byte-deterministically. A solver regression found by a
fuzz soak becomes a committed fixture-driven test, not a flaky memory.

Shrinking is MONOTONE because every random stream is independently
seeded: chaos seams draw from per-seam child RNGs (chaos.ChaosSchedule),
workload waves from per-wave child RNGs (twin/workloads.py) — removing
one element never reshuffles the draws of the survivors.
"""
from __future__ import annotations

import dataclasses
import json
import math
from typing import Iterable, Iterator, List, Optional

from karpenter_core_tpu.twin.harness import TWIN_EPOCH, TwinResult, run_scenario
from karpenter_core_tpu.twin.scenario import (
    Scenario,
    encode_scenario,
    scenario_from_json,
    validate_scenario,
)


def fuzz(
    base: Scenario,
    seeds: Iterable[int],
    stop_after: int = 1,
    **run_kwargs,
) -> List[TwinResult]:
    """Run the scenario shape under each seed; returns the FAILING results
    (stops after ``stop_after`` failures — the shrinker wants one)."""
    failing: List[TwinResult] = []
    for seed in seeds:
        result = run_scenario(
            dataclasses.replace(base, seed=seed), **run_kwargs
        )
        if not result.ok:
            failing.append(result)
            if stop_after and len(failing) >= stop_after:
                break
    return failing


def _still_fails(scenario: Scenario, invariant: str, run_kwargs) -> bool:
    try:
        validate_scenario(scenario)
    except ValueError:
        return False
    result = run_scenario(scenario, **run_kwargs)
    return any(v.invariant == invariant for v in result.violations)


def _without_index(items: tuple, i: int) -> tuple:
    return items[:i] + items[i + 1:]


def _candidates(s: Scenario) -> Iterator[Scenario]:
    """Strictly-smaller variants, cheapest-win first. Every candidate is
    a COMPLETE scenario (the predicate re-runs it from scratch), so a
    rejected candidate costs one run and changes nothing."""
    # drop the whole fleet tier (and its faults): if the violation
    # survives on the in-proc greedy path, the repro needs no tier at all
    if s.fleet:
        yield dataclasses.replace(
            s, fleet=0, solver="greedy", fleet_faults=()
        )
    # drop the highest cluster when nothing references it anymore
    if s.clusters > 1:
        top = s.clusters - 1
        used = {w.cluster for w in s.waves} | {h.cluster for h in s.hooks}
        if top not in used:
            yield dataclasses.replace(
                s,
                clusters=top,
                storms=tuple(
                    st for st in s.storms if st.cluster != top
                ),
                fleet_faults=tuple(
                    f for f in s.fleet_faults if f.cluster != top
                ),
            )
    for i in range(len(s.fleet_faults)):
        yield dataclasses.replace(
            s, fleet_faults=_without_index(s.fleet_faults, i)
        )
    for i in range(len(s.storms)):
        yield dataclasses.replace(s, storms=_without_index(s.storms, i))
    if s.rates:
        yield dataclasses.replace(s, rates={})
        for key in sorted(s.rates):
            rest = {k: v for k, v in sorted(s.rates.items()) if k != key}
            yield dataclasses.replace(s, rates=rest)
    for i in range(len(s.waves)):
        yield dataclasses.replace(s, waves=_without_index(s.waves, i))
    for i, wave in enumerate(s.waves):
        if wave.kind == "training":
            # counts stay positive gang_size multiples (validate pins it)
            floor = wave.gang_size
            halved = (wave.count // 2 // wave.gang_size) * wave.gang_size
        else:
            floor = 1
            halved = wave.count // 2
        if wave.count > floor:
            smaller = dataclasses.replace(wave, count=max(halved, floor))
            yield dataclasses.replace(
                s, waves=s.waves[:i] + (smaller,) + s.waves[i + 1:]
            )
    if s.duration > s.tick:
        yield dataclasses.replace(
            s, duration=max(s.duration / 2, s.tick)
        )


def _truncated(s: Scenario, result: TwinResult, invariant: str) -> Scenario:
    """Cut the schedule just past the first violation of the invariant —
    the single biggest shrink, taken straight from the failing run."""
    firsts = [
        v.at - TWIN_EPOCH
        for v in result.violations
        if v.invariant == invariant
    ]
    if not firsts:
        return s
    cutoff = min(math.ceil(min(firsts) / s.tick) * s.tick, s.duration)
    if cutoff >= s.duration:
        return s
    return dataclasses.replace(s, duration=cutoff)


def shrink(
    scenario: Scenario,
    invariant: Optional[str] = None,
    max_runs: int = 120,
    **run_kwargs,
) -> Scenario:
    """Greedy fixpoint minimization: keep any strictly-smaller candidate
    that still trips the (first) violated invariant; stop when a full
    candidate sweep makes no progress or the run budget is spent."""
    result = run_scenario(scenario, **run_kwargs)
    if result.ok:
        raise ValueError(
            "scenario does not violate any invariant; nothing to shrink"
        )
    invariant = invariant or result.violations[0].invariant
    runs = 1
    current = scenario
    candidate = _truncated(current, result, invariant)
    if candidate is not current and runs < max_runs:
        runs += 1
        if _still_fails(candidate, invariant, run_kwargs):
            current = candidate
    progress = True
    while progress and runs < max_runs:
        progress = False
        for candidate in _candidates(current):
            if runs >= max_runs:
                break
            runs += 1
            if _still_fails(candidate, invariant, run_kwargs):
                current = candidate
                progress = True
                break
    return current


def save_repro(scenario: Scenario, path: str) -> None:
    """Write the scenario as the committed-fixture JSON form (stable key
    order; human-readable indent — the canonical compact form is what
    fingerprints, both decode identically)."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump(encode_scenario(scenario), f, sort_keys=True, indent=1)
        f.write("\n")


def replay(path: str, **run_kwargs) -> TwinResult:
    """Re-run a committed repro fixture; byte-deterministic per the twin's
    identical-seed contract."""
    with open(path, "r", encoding="utf-8") as f:
        scenario = scenario_from_json(f.read())
    return run_scenario(scenario, **run_kwargs)
