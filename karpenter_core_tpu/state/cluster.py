"""Cluster state cache — the L3 layer feeding the solver
(reference: pkg/controllers/state/cluster.go:48-658, statenode.go:115-529).

StateNode merges the Node and NodeClaim views of one machine; Cluster keys
them by provider id, tracks pod↔node bindings, and produces the SimNode
snapshot the scheduler (and later, the device snapshot codec) consumes.
Informer events arrive through KubeStore.watch; `sync()` performs the full
resync the reference's Synced() gate guarantees before a solve.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.nodeclaim import NodeClaim
from karpenter_core_tpu.api.objects import Node, Pod
from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import SimNode
from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
    has_required_pod_anti_affinity,
)
from karpenter_core_tpu.scheduling.taints import KNOWN_EPHEMERAL_TAINTS
from karpenter_core_tpu.utils import resources as resutil
from karpenter_core_tpu.utils.clock import Clock


class StateNode:
    """Node + NodeClaim merged view (statenode.go:115-145)."""

    def __init__(
        self, node: Optional[Node] = None, node_claim: Optional[NodeClaim] = None
    ):
        self.node = node
        self.node_claim = node_claim
        self.marked_for_deletion = False
        self.nominated_until = 0.0

    # -- identity ---------------------------------------------------------

    @property
    def name(self) -> str:
        if self.node is not None:
            return self.node.name
        return self.node_claim.status.node_name or self.node_claim.name

    @property
    def provider_id(self) -> str:
        if self.node is not None and self.node.provider_id:
            return self.node.provider_id
        if self.node_claim is not None:
            return self.node_claim.status.provider_id
        return ""

    @property
    def labels(self) -> dict:
        if self.node is not None:
            return self.node.labels
        return self.node_claim.metadata.labels

    @property
    def nodepool_name(self) -> str:
        return self.labels.get(apilabels.NODEPOOL_LABEL_KEY, "")

    # -- lifecycle predicates (statenode.go:311-327) ----------------------

    def registered(self) -> bool:
        if self.node_claim is not None:
            return self.node_claim.is_registered()
        return self.node is not None  # unmanaged nodes count as registered

    def initialized(self) -> bool:
        if self.node_claim is not None:
            return self.node_claim.is_initialized()
        return self.node is not None

    def managed(self) -> bool:
        return self.node_claim is not None or (
            self.node is not None
            and apilabels.NODEPOOL_LABEL_KEY in self.node.labels
        )

    def deleting(self) -> bool:
        return (
            self.node is not None
            and self.node.metadata.deletion_timestamp is not None
        ) or (
            self.node_claim is not None
            and self.node_claim.metadata.deletion_timestamp is not None
        )

    # -- resources (statenode.go:329-366) ---------------------------------

    def capacity(self) -> dict:
        if self.node is not None and self.node.status.capacity:
            return dict(self.node.status.capacity)
        if self.node_claim is not None:
            return dict(self.node_claim.status.capacity)
        return {}

    def allocatable(self) -> dict:
        if self.node is not None and self.node.status.allocatable:
            return dict(self.node.status.allocatable)
        if self.node_claim is not None:
            return dict(self.node_claim.status.allocatable)
        return {}

    def taints(self) -> list:
        """Scheduling-relevant taints: known-ephemeral and startup taints are
        filtered until the node is initialized (statenode.go:279-309)."""
        raw = list(self.node.taints) if self.node is not None else (
            list(self.node_claim.spec.taints) if self.node_claim else []
        )
        if self.initialized():
            return raw
        startup = (
            list(self.node_claim.spec.startup_taints)
            if self.node_claim is not None
            else []
        )
        out = []
        for t in raw:
            if any(
                t.key == e.key and t.effect == e.effect
                for e in KNOWN_EPHEMERAL_TAINTS
            ):
                continue
            if any(t == s for s in startup):
                continue
            out.append(t)
        return out

    def nominate(self, until: float) -> None:
        self.nominated_until = until

    def nominated(self, now: float) -> bool:
        return self.nominated_until > now


class Cluster:
    """(cluster.go:48-88)"""

    def __init__(self, kube, clock: Optional[Clock] = None):
        self.kube = kube
        self.clock = clock or Clock()
        self.state_nodes: Dict[str, StateNode] = {}  # provider_id (or name)
        self.bindings: Dict[str, str] = {}  # pod key -> node name
        self._pods: Dict[str, Pod] = {}  # pod key -> pod
        self._consolidated_at = 0.0
        self._unconsolidated_at = self.clock.now()
        kube.watch(self._on_event)
        self.sync()

    # -- informer seam ----------------------------------------------------

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "Node":
            if event == "DELETED":
                self._forget_node(obj)
            else:
                self.update_node(obj)
        elif kind == "NodeClaim":
            if event == "DELETED":
                self._forget_nodeclaim(obj)
            else:
                self.update_nodeclaim(obj)
        elif kind == "Pod":
            if event == "DELETED":
                self.delete_pod(obj)
            else:
                self.update_pod(obj)
        if kind in ("Node", "NodeClaim", "NodePool"):
            self.mark_unconsolidated()

    def sync(self) -> None:
        """Full resync from the store (the reference's cache-sync gate,
        cluster.go:96-150, is a superset check; with a synchronous store a
        rebuild is exact)."""
        self.state_nodes = {}
        self.bindings = {}
        self._pods = {}
        for claim in self.kube.list_nodeclaims():
            self.update_nodeclaim(claim)
        for node in self.kube.list_nodes():
            self.update_node(node)
        for pod in self.kube.list_pods():
            self.update_pod(pod)

    def synced(self) -> bool:
        return True  # synchronous store: watch events apply inline

    # -- node/claim bookkeeping -------------------------------------------

    def _key_for(self, provider_id: str, name: str) -> str:
        return provider_id or f"name:{name}"

    def update_node(self, node: Node) -> None:
        key = self._key_for(node.provider_id, node.name)
        sn = self.state_nodes.get(key)
        if sn is None:
            # adopt a claim-only entry whose provider id matches
            sn = self.state_nodes.pop(self._key_for("", node.name), None)
            if sn is None:
                sn = StateNode()
            self.state_nodes[key] = sn
        sn.node = node
        if node.metadata.deletion_timestamp is not None:
            sn.marked_for_deletion = True

    def update_nodeclaim(self, claim: NodeClaim) -> None:
        key = self._key_for(
            claim.status.provider_id, claim.status.node_name or claim.name
        )
        sn = self.state_nodes.get(key)
        if sn is None:
            # adopt the pre-launch name-keyed entry once the claim gains a
            # provider id / node name, so one machine never has two entries
            for stale_key in (
                self._key_for("", claim.name),
                self._key_for("", claim.status.node_name),
            ):
                if stale_key != key and stale_key in self.state_nodes:
                    stale = self.state_nodes[stale_key]
                    if stale.node_claim is claim or (
                        stale.node_claim is not None
                        and stale.node_claim.name == claim.name
                    ):
                        sn = self.state_nodes.pop(stale_key)
                        break
            if sn is None:
                sn = StateNode()
            self.state_nodes[key] = sn
        sn.node_claim = claim
        if claim.metadata.deletion_timestamp is not None:
            sn.marked_for_deletion = True

    def _forget_node(self, node: Node) -> None:
        key = self._key_for(node.provider_id, node.name)
        sn = self.state_nodes.get(key)
        if sn is None:
            return
        if sn.node_claim is None:
            del self.state_nodes[key]
        else:
            sn.node = None

    def _forget_nodeclaim(self, claim: NodeClaim) -> None:
        key = self._key_for(
            claim.status.provider_id, claim.status.node_name or claim.name
        )
        sn = self.state_nodes.get(key)
        if sn is None:
            return
        if sn.node is None:
            del self.state_nodes[key]
        else:
            sn.node_claim = None

    # -- pods -------------------------------------------------------------

    def update_pod(self, pod: Pod) -> None:
        key = pod.key()
        self._pods[key] = pod
        if pod.node_name:
            self.bindings[key] = pod.node_name
        else:
            self.bindings.pop(key, None)

    def delete_pod(self, pod: Pod) -> None:
        self._pods.pop(pod.key(), None)
        self.bindings.pop(pod.key(), None)

    def pods_on_node(self, node_name: str) -> List[Pod]:
        return [
            self._pods[k]
            for k, n in self.bindings.items()
            if n == node_name and k in self._pods
        ]

    def nominate_node(self, node_name: str, until: float) -> None:
        """Mark the named node nominated (pending binds en route): the
        disruption candidate filter skips it until the TTL elapses
        (disruption/types.py; statenode nomination — the provisioner
        calls this for every existing-node placement it returns)."""
        for sn in self.state_nodes.values():
            if sn.name == node_name:
                sn.nominate(max(until, sn.nominated_until))
                return

    def clear_node_nomination(self, node_name: str) -> None:
        """Drop the named node's nomination early: the binder calls this
        once EVERY pod nominated onto the node has bound — the
        protection window has served its purpose, and consolidation
        should not wait out the TTL backstop."""
        for sn in self.state_nodes.values():
            if sn.name == node_name:
                sn.nominated_until = 0.0
                return

    def nomination_wait_remaining(self) -> float:
        """Seconds until the nearest node-nomination TTL lapses (0 when
        none): a fake-clock driver (run_until_idle, the twin) elapses it
        like the batcher/backoff/validation timers so consolidation is
        dampened by the window, never parked behind it."""
        now = self.clock.now()
        waits = [
            sn.nominated_until - now
            for sn in self.state_nodes.values()
            if sn.nominated_until > now
        ]
        return min(waits) if waits else 0.0

    # -- consolidation bookkeeping (cluster.go:397-423) --------------------

    def mark_unconsolidated(self) -> None:
        self._unconsolidated_at = self.clock.now()

    def mark_consolidated(self) -> None:
        self._consolidated_at = self.clock.now()

    def consolidated(self) -> bool:
        """5-minute forced refresh even when nothing changed."""
        if self.clock.since(self._consolidated_at) > 300.0:
            return False
        return self._consolidated_at > self._unconsolidated_at

    # -- snapshots for the scheduler --------------------------------------

    def nodes(self) -> List[StateNode]:
        return list(self.state_nodes.values())

    def sim_nodes(self, include_deleting: bool = False) -> List[SimNode]:
        """SimNode views for schedulable (registered, non-deleting) nodes
        (scheduler.go:318-354 existing-node build)."""
        out = []
        for sn in self.state_nodes.values():
            if sn.node is None or not sn.registered():
                continue
            if (sn.deleting() or sn.marked_for_deletion) and not include_deleting:
                continue
            pods = self.pods_on_node(sn.name)
            used = resutil.requests_for_pods(*[p for p in pods if not p.is_daemonset])
            daemon = resutil.requests_for_pods(*[p for p in pods if p.is_daemonset])
            alloc = sn.allocatable()
            available = resutil.subtract(alloc, resutil.merge(used, daemon))
            available["pods"] = alloc.get("pods", 0.0) - len(pods)
            out.append(
                SimNode(
                    name=sn.name,
                    labels=dict(sn.labels),
                    taints=sn.taints(),
                    available=available,
                    capacity=sn.capacity(),
                    daemon_requests=daemon,
                    initialized=sn.initialized(),
                    nodeclaim_name=sn.node_claim.name if sn.node_claim else "",
                    nodepool_name=sn.nodepool_name,
                    evictable=self._evictable_on(pods),
                )
            )
        return out

    @staticmethod
    def _evictable_on(pods) -> tuple:
        """Bound pods a preemptive solve may evict (gangsched, ISSUE 10):
        reschedulable non-daemonset pods, as capacity views carrying the
        disruption-cost victim ordering. The tier-legality rule (only
        strictly-lower tiers are evictable) is applied at USE — the kernel
        masks by the contending class's tier — so the view is
        priority-complete, not pre-filtered."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
            EvictablePod,
        )
        from karpenter_core_tpu.utils import pod as podutil
        from karpenter_core_tpu.utils.disruption import (
            eviction_cost,
            priority_tier,
        )

        return tuple(
            EvictablePod(
                uid=p.uid,
                priority=priority_tier(p.priority),
                requests=resutil.requests_for_pods(p),
                cost=eviction_cost(p),
            )
            for p in pods
            if not p.is_daemonset and podutil.is_reschedulable(p)
        )

    def existing_pod_triples(self) -> List[Tuple[Pod, dict, str]]:
        """(pod, node labels, node name) for topology domain counting
        (topology.go countDomains:274-321)."""
        by_name = {sn.name: sn for sn in self.state_nodes.values() if sn.node}
        out = []
        for key, node_name in self.bindings.items():
            pod = self._pods.get(key)
            sn = by_name.get(node_name)
            if pod is None or sn is None:
                continue
            out.append((pod, dict(sn.labels), node_name))
        return out

    def pods_with_anti_affinity(self) -> List[Tuple[Pod, dict, str]]:
        return [
            (p, labels, name)
            for p, labels, name in self.existing_pod_triples()
            if has_required_pod_anti_affinity(p)
        ]
