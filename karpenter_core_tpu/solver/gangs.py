"""Priority & gang scheduling: the host half of the gangsched subsystem.

The device half lives in ops/gangsched.py (tier-ordered packing with
gang-atomic commit and a vmapped preemption pass); this module owns
everything that is pure object algebra:

* the pod-group ANNOTATION CONTRACT — how a pod declares its gang, the
  gang's min-count, and its co-location wishes (same zone / same node
  template), modeled on the sig-scheduling PodGroup conventions the
  rank-aware MPI scheduling line of work rides on ("Rank-Aware Resource
  Scheduling for MPI on Kubernetes", PAPERS.md);
* GangSpec assembly over the solve's pod classes (one gang = one or more
  equivalence classes — solver/snapshot.group_pods splits classes on the
  gang signature, so membership is a class property);
* gang-atomicity ENFORCEMENT over a finished ``Results`` — the backstop
  behind the kernel's on-device rollback: any decode-time divergence that
  leaves a gang below its min-count strips the partial placement and
  reports the whole group unschedulable (the verifier rejects partially
  materialized gangs, so this runs before verification on every path);
* the TIERED-GREEDY-WITH-PREEMPTION host fallback: when a gang/priority
  solve degrades off the device path (sidecar down, verification
  rejection), the greedy re-solve still packs tiers high→low, keeps gangs
  atomic, and may still evict strictly-lower-tier bound pods — degraded
  means slower, not semantically different.

Everything here is import-light (no jax): the wire codec reads the
annotation constants and solver/snapshot reads the gang signature at
class-grouping time.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import Pod
from karpenter_core_tpu.utils import resources as resutil
from karpenter_core_tpu.utils.disruption import priority_tier

# -- the pod-group annotation contract --------------------------------------
# One annotation names the gang; the optional companions shape it. All six
# ride ObjectMeta.annotations, so they survive the solve wire unchanged
# (kube/serial encodes the full metadata).
GANG_ANNOTATION = "scheduling.karpenter.sh/pod-group"
# minimum pods that must place for the gang to commit (coscheduling
# minMember); absent/0 → the whole group is the minimum
GANG_MIN_SIZE_ANNOTATION = "scheduling.karpenter.sh/pod-group-min-size"
# co-location: members must land in one topology zone (lowered to a
# synthetic zone-affinity group in ops/topoplan.py)
GANG_SAME_ZONE_ANNOTATION = "scheduling.karpenter.sh/pod-group-same-zone"
# co-location: members' fresh nodes must come from one nodeclaim template
# (lowered to a joint template mask in ops/masks.gang_joint_templates)
GANG_SAME_TEMPLATE_ANNOTATION = (
    "scheduling.karpenter.sh/pod-group-same-node-template"
)
# topoaware (ISSUE 20): HARD ceiling on the intra-gang network distance —
# the max pairwise hop count (see hop_distance below) any two placed
# members may span. Absent → no hard bound, the solver still PREFERS
# near placements (soft semantics); present → a placement provably above
# the bound strips like an atomicity violation and the verifier rejects
# forged ones with the typed `gang_distance` reason.
GANG_MAX_HOPS_ANNOTATION = "scheduling.karpenter.sh/pod-group-max-hops"
# topoaware (ISSUE 20): the pod's rank within its gang (MPI-style).
# Per-POD, deliberately NOT part of pod_gang_sig — ranks must not fragment
# the equivalence-class collapse. Rank only permutes WHICH interchangeable
# pod object lands in WHICH already-chosen slot (rank_order_pods), so
# rank-adjacent pods land network-adjacent.
GANG_RANK_ANNOTATION = "scheduling.karpenter.sh/pod-group-rank"

_TRUE = ("true", "1", "yes")

# The hop metric's ceiling: same rack 0, same superpod 1, same zone 2,
# anything else (or unknown) 3. ops/ffd.TOPO_LEVELS is MAX_HOP_DISTANCE+1
# — the kernel's level-grouped fill buckets slots by this distance.
MAX_HOP_DISTANCE = 3

# generous per-pod rank ceiling — far below int32, so a clamped rank can
# ride any int32 plane without overflow games
_RANK_MAX = 1 << 20


def gang_rank(value: int) -> int:
    """Range-normalize a (possibly hostile, wire-supplied) pod-group rank
    into [0, 2^20]. Registered in graftlint's GL601 normalizer registry:
    every decode-net int that can reach an int32 plane must pass through
    one of these (the PR 11 eviction-priority precedent)."""
    return min(max(int(value), 0), _RANK_MAX)


def gang_max_hops(value: int) -> int:
    """Range-normalize a wire-supplied max-hops bound into
    [0, MAX_HOP_DISTANCE]. A bound at the ceiling constrains nothing —
    exactly right for hostile over-large ints. GL601-registered like
    gang_rank."""
    return min(max(int(value), 0), MAX_HOP_DISTANCE)

# -- device-side gang sentinels ----------------------------------------------
# The gang_of_class / gang_of_step planes (models/provisioner, consumed by
# ops/gangsched) carry a gang index >= 0 for kernel-enforced gangs and one
# of two NEGATIVE sentinels below. The two are NOT interchangeable: a
# ``< 0`` test conflates them, and the preemption pass must gate on
# GANG_FREE exactly — evicting real workload to place a member of a
# fallback-straddling gang could strand eviction claims if the host
# atomicity backstop (enforce_atomicity) strips the gang. One definition
# here (the module both halves already import); graftlint GL602 seeds its
# sentinel-domain registry from GANG_SENTINELS, so sentinel-confusing
# comparisons fail lint instead of review.
GANG_FREE = -1  # class belongs to no gang at all
GANG_FALLBACK_STRADDLING = -2  # member of a gang the host backstop enforces

# domain-registry view consumed by tools/graftlint/rules/rangecheck.py
GANG_SENTINELS = {
    "gang-free": GANG_FREE,
    "fallback-straddling": GANG_FALLBACK_STRADDLING,
}


def pod_gang_sig(pod: Pod) -> Optional[tuple]:
    """The gang signature of one pod: (name, min_size, same_zone,
    same_template, max_hops), or None for gang-free pods. Part of the
    class signature (solver/snapshot._spec_signature), so two pods
    differing in any component land in different classes. max_hops is
    None when the annotation is absent (soft-preference semantics) —
    NOT 0, which would be the tightest hard bound. The per-pod rank
    deliberately stays OUT of the signature (pod_gang_rank)."""
    ann = pod.metadata.annotations or {}
    name = ann.get(GANG_ANNOTATION)
    if not name:
        return None
    raw_min = ann.get(GANG_MIN_SIZE_ANNOTATION, "0")
    try:
        min_size = max(int(raw_min), 0)
    except (TypeError, ValueError):
        min_size = 0
    same_zone = str(ann.get(GANG_SAME_ZONE_ANNOTATION, "")).lower() in _TRUE
    same_template = (
        str(ann.get(GANG_SAME_TEMPLATE_ANNOTATION, "")).lower() in _TRUE
    )
    raw_hops = ann.get(GANG_MAX_HOPS_ANNOTATION)
    max_hops: Optional[int] = None
    if raw_hops is not None:
        try:
            max_hops = gang_max_hops(int(str(raw_hops).strip()))
        except (TypeError, ValueError):
            max_hops = None  # malformed → soft, never a surprise bound
    return (name, min_size, same_zone, same_template, max_hops)


def pod_gang_rank(pod: Pod) -> Optional[int]:
    """The pod's declared rank within its gang, clamped (gang_rank), or
    None when absent/malformed. Per-pod, never part of the class
    signature."""
    ann = pod.metadata.annotations or {}
    raw = ann.get(GANG_RANK_ANNOTATION)
    if raw is None:
        return None
    try:
        return gang_rank(int(str(raw).strip()))
    except (TypeError, ValueError):
        return None


def pod_tier(pod: Pod) -> int:
    return priority_tier(pod.priority)


def has_gangsched(pods: Sequence[Pod]) -> bool:
    """Does this pod set engage the gangsched machinery at all? The
    off-by-default contract hangs on this being False for plain problems:
    when it is, the solve dispatches the exact pre-gang kernels and
    produces byte-identical result wires."""
    return any(
        pod_tier(p) != 0 or pod_gang_sig(p) is not None for p in pods
    )


def degraded_solve(make_scheduler, pods: Sequence[Pod], existing_nodes=(),
                   gangsched=None):
    """THE greedy degradation entry, shared by every fallback seam (device
    verify-failure, sidecar RPC failure/quarantine): problems carrying
    priorities/gangs route through the tiered-greedy-with-preemption
    wrapper so degraded means slower, never semantically different.
    ``gangsched`` carries an already-computed has_gangsched verdict; None
    rescans."""
    if gangsched is None:
        gangsched = has_gangsched(pods)
    if gangsched:
        return host_gang_solve(make_scheduler, pods, existing_nodes)
    return make_scheduler().solve(pods)


@dataclass(frozen=True)
class GangSpec:
    """One pod group as the solver sees it."""

    name: str
    min_count: int  # resolved: max declared min, or the full size when 0
    same_zone: bool
    same_template: bool
    class_indices: Tuple[int, ...]  # indices into the solve's class list
    total: int  # pods across member classes
    # strictest declared hop bound across members (min), None when no
    # member declares one — soft preference only
    max_hops: Optional[int] = None


def collect_gangs(classes) -> List[GangSpec]:
    """Assemble GangSpecs from the solve's PodClass list (classes carry
    .gang — the pod_gang_sig tuple — and .count). Min-count resolves to
    the largest declared min across members, defaulting to the full group
    size (all-or-nothing); co-location flags OR across members (any member
    asking for co-location binds the gang); the hop bound resolves to the
    STRICTEST declared (min across members) — a bound binds the gang the
    way co-location does."""
    by_name: Dict[str, dict] = {}
    for ci, cls in enumerate(classes):
        g = getattr(cls, "gang", None)
        if g is None:
            continue
        name, min_size, same_zone, same_template, max_hops = g
        e = by_name.setdefault(
            name,
            {"min": 0, "zone": False, "tmpl": False, "cis": [], "total": 0,
             "hops": None},
        )
        e["min"] = max(e["min"], min_size)
        e["zone"] = e["zone"] or same_zone
        e["tmpl"] = e["tmpl"] or same_template
        if max_hops is not None:
            e["hops"] = (
                max_hops if e["hops"] is None else min(e["hops"], max_hops)
            )
        e["cis"].append(ci)
        e["total"] += cls.count
    out: List[GangSpec] = []
    for name in sorted(by_name):
        e = by_name[name]
        min_count = e["min"] if e["min"] > 0 else e["total"]
        out.append(
            GangSpec(
                name=name,
                min_count=min(min_count, e["total"]) or e["total"],
                same_zone=e["zone"],
                same_template=e["tmpl"],
                class_indices=tuple(e["cis"]),
                total=e["total"],
                max_hops=e["hops"],
            )
        )
    return out


def gang_members(pods: Sequence[Pod]) -> Dict[str, List[Pod]]:
    out: Dict[str, List[Pod]] = {}
    for p in pods:
        g = pod_gang_sig(p)
        if g is not None:
            out.setdefault(g[0], []).append(p)
    return out


def gang_min_count(pods: Sequence[Pod]) -> int:
    """Resolved min-count for one gang's member pods (same rule as
    collect_gangs, usable by the verifier without classes)."""
    declared = max((pod_gang_sig(p)[1] for p in pods), default=0)
    return declared if 0 < declared <= len(pods) else len(pods)


def gang_max_hops_for(pods: Sequence[Pod]) -> Optional[int]:
    """Resolved hard hop bound for one gang's member pods (strictest
    declared, same rule as collect_gangs), None when no member declares
    one. Usable by the verifier without classes."""
    vals = [
        g[4]
        for p in pods
        if (g := pod_gang_sig(p)) is not None and g[4] is not None
    ]
    return min(vals) if vals else None


# -- the network-hop metric (topoaware, ISSUE 20) ----------------------------
# Distance between two placements from their topology labels alone:
#   same rack      -> 0   (one ICI/ToR domain)
#   same superpod  -> 1   (one spine block)
#   same zone      -> 2
#   else / unknown -> 3   (MAX_HOP_DISTANCE)
# Pure object algebra over label dicts — the kernel's per-slot hop planes
# (ops/topoplan), the verifier's re-derivation (solver/verify), the twin
# monitor and the bench all call THESE, so the four layers cannot drift.

_TOPO_LABEL_KEYS = (
    apilabels.LABEL_TOPOLOGY_RACK,
    apilabels.LABEL_TOPOLOGY_SUPERPOD,
    apilabels.LABEL_TOPOLOGY_ZONE,
)


def hop_distance(a, b) -> int:
    """Pairwise hop distance between two label dicts; unknown levels are
    pessimistic (a missing label can only RAISE the reported distance).
    Use for reporting (ledger/bench); rejection paths must use the sound
    lower bound (placement_hop_bound) instead."""
    a = a or {}
    b = b or {}
    ra, rb = a.get(_TOPO_LABEL_KEYS[0]), b.get(_TOPO_LABEL_KEYS[0])
    if ra and rb and ra == rb:
        return 0
    sa, sb = a.get(_TOPO_LABEL_KEYS[1]), b.get(_TOPO_LABEL_KEYS[1])
    if sa and sb and sa == sb:
        return 1
    za, zb = a.get(_TOPO_LABEL_KEYS[2]), b.get(_TOPO_LABEL_KEYS[2])
    if za and zb and za == zb:
        return 2
    return MAX_HOP_DISTANCE


def placement_hop_bound(labels_list) -> int:
    """PROVABLE max pairwise hop distance over a gang's placements —
    sound for rejection: never overestimates, so a missing label can
    never manufacture a violation. Soundness over completeness:
    placements without a rack label are unattributable and skipped
    entirely; among the attributable rest, a level only raises the bound
    when both sides carry the level's label and they DIFFER."""
    att = [l or {} for l in labels_list
           if (l or {}).get(_TOPO_LABEL_KEYS[0])]
    if len(att) <= 1:
        return 0
    zones = {l[_TOPO_LABEL_KEYS[2]] for l in att
             if l.get(_TOPO_LABEL_KEYS[2])}
    if len(zones) > 1:
        return MAX_HOP_DISTANCE
    sps = {l[_TOPO_LABEL_KEYS[1]] for l in att
           if l.get(_TOPO_LABEL_KEYS[1])}
    if len(sps) > 1:
        return 2
    racks = {l[_TOPO_LABEL_KEYS[0]] for l in att}
    return 1 if len(racks) > 1 else 0


def topo_sort_key(labels) -> tuple:
    """Network-nearness grouping key: placements sorting adjacent under
    this key share zone, then superpod, then rack. The one ordering
    rank_order_pods (below), the kernel's level planes and the host
    fallback all derive from."""
    labels = labels or {}
    return (
        labels.get(_TOPO_LABEL_KEYS[2]) or "",
        labels.get(_TOPO_LABEL_KEYS[1]) or "",
        labels.get(_TOPO_LABEL_KEYS[0]) or "",
    )


def claim_topo_labels(claim) -> Dict[str, str]:
    """Topology attribution for a fresh nodeclaim: a level counts only
    when the claim's requirements pin it to a SINGLE value (the
    verifier's zone-attribution rule, extended down the hierarchy)."""
    out: Dict[str, str] = {}
    reqs = getattr(claim, "requirements", None)
    if reqs is None:
        return out
    for key in _TOPO_LABEL_KEYS:
        req = reqs.get(key)
        if req is None:
            continue
        vals = req.sorted_values()
        if len(vals) == 1:
            out[key] = vals[0]
    return out


def gang_adjacent_order(items, tier_of, gang_name_of) -> list:
    """THE gangsched packing order, over any item type: stable
    tier-descending with gang members adjacent, anchored at each gang's
    first occurrence. One implementation serves the kernel's class sort
    (models/provisioner._sorted_classes) and the host fallback's pod sort
    (tier_sorted) so the two layers can never drift apart."""
    first_seen: Dict[str, int] = {}
    for i, it in enumerate(items):
        g = gang_name_of(it)
        if g is not None and g not in first_seen:
            first_seen[g] = i

    def key(ii):
        i, it = ii
        g = gang_name_of(it)
        return (-tier_of(it), first_seen[g] if g is not None else i, i)

    return [it for _i, it in sorted(enumerate(items), key=key)]


def tier_sorted(pods: Sequence[Pod]) -> List[Pod]:
    """Stable tier-descending order with gang members kept adjacent
    (members place back to back so co-location state is warm)."""
    def gang_name(p):
        g = pod_gang_sig(p)
        return None if g is None else g[0]

    return gang_adjacent_order(pods, pod_tier, gang_name)


# -- atomicity enforcement over a finished Results --------------------------


def enforce_atomicity(results, pods: Sequence[Pod]) -> List[str]:
    """Strip partially-materialized gangs from a Results in place and
    report every member unschedulable. Returns the violated gang names.

    The kernel already rolls failed gangs back on device; this is the
    decode/fallback backstop — a member class that diverged through the
    host repair path and failed can leave its gang-mates placed, and the
    verifier treats that as a hard violation. Stripped groups leave their
    request accounting on the claim/sim (stale HIGH — conservative: the
    packing stays valid, capacity is never understated)."""
    members = gang_members(pods)
    if not members:
        return []
    errors = results.pod_errors
    violated: List[str] = []
    for name, mpods in members.items():
        min_count = gang_min_count(mpods)
        uids = {p.uid for p in mpods}
        placed = sum(
             1
             for group in _placement_groups(results)
             for p in group
             if p.uid in uids
        )
        if placed == 0 or placed >= min_count:
            continue
        violated.append(name)
        spec_msg = (
            f"pod group {name!r} placed {placed}/{len(mpods)} below"
            f" min-count {min_count} — gang unschedulable"
        )
        for claim in list(results.new_node_claims):
            claim.pods = [p for p in claim.pods if p.uid not in uids]
            if not claim.pods:
                claim.destroy()
                results.new_node_claims.remove(claim)
        for sim in results.existing_nodes:
            sim.pods = [p for p in sim.pods if p.uid not in uids]
        for p in mpods:
            errors[p.uid] = spec_msg
    return violated


def _placement_groups(results):
    for claim in results.new_node_claims:
        yield claim.pods
    for sim in results.existing_nodes:
        yield sim.pods


# -- topoaware post-passes over a finished Results (ISSUE 20) ----------------


def enforce_distance(results, pods: Sequence[Pod],
                     node_labels=None) -> List[str]:
    """Strip gangs whose placement PROVABLY exceeds their declared hard
    hop bound, exactly like enforce_atomicity strips partial gangs:
    members come off every claim/sim, the whole group reports
    unschedulable, and the request accounting stays stale-HIGH
    (conservative). Uses placement_hop_bound — sound, so a cluster
    without rack labels can never trip a bound — which is also why the
    verifier's independent gang_distance check never fires on results
    that passed through here. Returns the violated gang names.

    ``node_labels`` maps existing-node name → label dict (the caller's
    view of the cluster); fresh claims attribute via claim_topo_labels."""
    members = gang_members(pods)
    if not members:
        return []
    node_labels = node_labels or {}
    errors = results.pod_errors
    violated: List[str] = []
    for name, mpods in sorted(members.items()):
        bound = gang_max_hops_for(mpods)
        if bound is None or bound >= MAX_HOP_DISTANCE:
            continue  # soft preference only — nothing to enforce
        uids = {p.uid for p in mpods}
        lab = []
        for claim in results.new_node_claims:
            if any(p.uid in uids for p in claim.pods):
                lab.append(claim_topo_labels(claim))
        for sim in results.existing_nodes:
            if any(p.uid in uids for p in sim.pods):
                lab.append(dict(node_labels.get(sim.name) or {}))
        worst = placement_hop_bound(lab)
        if worst <= bound:
            continue
        violated.append(name)
        spec_msg = (
            f"pod group {name!r} placement spans {worst} network hops,"
            f" above the declared max-hops bound {bound} — gang"
            f" unschedulable"
        )
        for claim in list(results.new_node_claims):
            claim.pods = [p for p in claim.pods if p.uid not in uids]
            if not claim.pods:
                claim.destroy()
                results.new_node_claims.remove(claim)
        for sim in results.existing_nodes:
            sim.pods = [p for p in sim.pods if p.uid not in uids]
        for p in mpods:
            errors[p.uid] = spec_msg
    return violated


def rank_order_pods(results, pods: Sequence[Pod], node_labels=None) -> None:
    """Rank-ordered slot assignment within each gang, as a Results-level
    permutation: pods of one equivalence class are interchangeable in
    every check the solve ran, so re-choosing WHICH member object sits in
    WHICH of the class's already-placed slots preserves the packing,
    capacity accounting, evictions — everything. Placement groups sort
    network-near-first (topo_sort_key) and each class's members deal into
    their slots in rank order, so rank-adjacent pods land
    network-adjacent. Gangs with no ranked member are left byte-identical
    (the off-by-default parity contract); runs AFTER any repair/repack
    pass that moves pods between groups."""
    members = gang_members(pods)
    if not members:
        return
    ranked = {
        name
        for name, mp in members.items()
        if any(pod_gang_rank(p) is not None for p in mp)
    }
    if not ranked:
        return
    from karpenter_core_tpu.solver.snapshot import _spec_signature

    node_labels = node_labels or {}
    groups: List[tuple] = []
    for gi, claim in enumerate(results.new_node_claims):
        groups.append(
            (topo_sort_key(claim_topo_labels(claim)), 0, gi, claim)
        )
    for gi, sim in enumerate(results.existing_nodes):
        groups.append(
            (topo_sort_key(node_labels.get(sim.name)), 1, gi, sim)
        )
    groups.sort(key=lambda g: (g[0], g[1], g[2]))
    for name in sorted(ranked):
        uids = {p.uid for p in members[name]}
        # slots per equivalence class, enumerated in topo-sorted group
        # order (label_aware=True is always sound: at least as fine as
        # the grouping the solve used)
        by_cls: Dict[tuple, List[tuple]] = {}
        for _key, _kind, _gi, grp in groups:
            for idx, p in enumerate(grp.pods):
                if p.uid in uids:
                    by_cls.setdefault(
                        _spec_signature(p, True), []
                    ).append((grp, idx))
        for slots in by_cls.values():
            placed = [grp.pods[idx] for grp, idx in slots]
            order = sorted(
                range(len(placed)),
                key=lambda i: (
                    0 if pod_gang_rank(placed[i]) is not None else 1,
                    pod_gang_rank(placed[i]) or 0,
                    i,
                ),
            )
            for (grp, idx), oi in zip(slots, order):
                grp.pods[idx] = placed[oi]


def prune_evictions(results) -> None:
    """Drop eviction claims that no longer enable anything: a node whose
    kernel-planned placements all diverged off it at decode time would
    otherwise carry a dangling claim the verifier rejects as illegal
    preemption. Only the trivially-safe prune runs here (no placed pods on
    the node → the claim is pure cost, never load-bearing for capacity);
    a node that kept SOME placements keeps its claims — if a rare
    divergence made one illegal, verification rejects the solve and the
    tiered fallback re-derives evictions from scratch."""
    ev = getattr(results, "evictions", None)
    if not ev:
        return
    placed_nodes = {sim.name for sim in results.existing_nodes if sim.pods}
    for node in list(ev):
        if node not in placed_nodes:
            del ev[node]


# -- the tiered-greedy-with-preemption fallback ------------------------------


def host_gang_solve(make_scheduler, pods: Sequence[Pod], existing_nodes=()):
    """Degraded-path solve that preserves gangsched semantics.

    ``make_scheduler`` builds ONE fresh greedy Scheduler (the caller's
    usual fallback construction); the solve then runs band-by-band in
    tier-descending order over that single instance — higher tiers claim
    capacity first, exactly the kernel's packing order, because the greedy
    queue's own cpu/memory sort is tier-blind. Claims and existing-node
    sims accumulate across bands (each ``solve`` call packs into the
    remaining capacity); errors merge across bands. Gang atomicity is then
    enforced post-hoc and a simple host preemption pass serves any
    still-unplaced positive-tier pods from ``existing_nodes``' evictable
    capacity, mirroring the kernel's cheapest-strictly-lower-tier rule."""
    tiers = sorted({pod_tier(p) for p in pods}, reverse=True)
    scheduler = make_scheduler()
    if len(tiers) <= 1:
        results = scheduler.solve(tier_sorted(pods))
    else:
        by_tier: Dict[int, List[Pod]] = {}
        for p in pods:
            by_tier.setdefault(pod_tier(p), []).append(p)
        errors: Dict[str, str] = {}
        results = None
        for t in tiers:
            results = scheduler.solve(tier_sorted(by_tier[t]))
            errors.update(results.pod_errors)
        results.pod_errors = errors
    enforce_atomicity(results, pods)
    node_labels = {
        n.name: getattr(n, "labels", None) or {} for n in existing_nodes
    }
    enforce_distance(results, pods, node_labels)
    _host_preempt(results, pods, existing_nodes)
    # rank permutation LAST: preemption may add gang-free pods but never
    # moves gang members, so the ordering survives it — degraded path and
    # device decode share the identical post-pass (slower, never different)
    rank_order_pods(results, pods, node_labels)
    return results


def _host_preempt(results, pods: Sequence[Pod], existing_nodes) -> None:
    """Place still-unschedulable positive-tier, gang-free pods onto
    existing nodes by evicting the cheapest strictly-lower-tier bound pods
    (SimNode.evictable), recording the eviction set on the results. One
    node per pod, minimal-cost prefix per node, minimal-cost node across
    nodes — the host twin of ops/gangsched.preempt_pass. The placement
    itself runs through ExistingNodeSim.add, so preemption enforces every
    admission check the greedy path does (taints, host ports, volume
    attach limits, requirements, topology) — the eviction only buys
    capacity, never a bypass."""
    from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
        IncompatibleError,
    )

    if not existing_nodes:
        return
    errors = results.pod_errors
    if not errors:
        return
    by_uid = {p.uid: p for p in pods}
    cand = [
        by_uid[uid]
        for uid in list(errors)
        if uid in by_uid
        and pod_tier(by_uid[uid]) > 0
        and pod_gang_sig(by_uid[uid]) is None
    ]
    if not cand:
        return
    cand.sort(key=lambda p: -pod_tier(p))
    evictions = getattr(results, "evictions", None)
    if evictions is None:
        return  # a Results shape without the eviction channel
    sims_by_name = {s.name: s for s in results.existing_nodes}
    evicted: set = set()
    for pod in cand:
        t = pod_tier(pod)
        req = resutil.requests_for_pods(pod)
        # (cost, seq, node, prefix of EvictablePod, freed, sim) per node
        candidates: List[tuple] = []
        for seq, node in enumerate(existing_nodes):
            sim = sims_by_name.get(node.name)
            if sim is None:
                # the greedy Scheduler sims every existing node it was
                # built with; a node outside that set has no admission
                # ledger, and preemption must never place without one
                continue
            # the sim's own ledger: requests grows per placement, the
            # freed credit of earlier preemptions rides cached_available
            total = resutil.merge(sim.requests, req)
            if resutil.fits(total, sim.cached_available):
                # fits in an earlier preemption's overshoot residual with
                # zero evictions — cost 0, exactly the kernel's bonus-carry
                # admission (add() below still enforces every check greedy
                # failed this pod on). Reachable only after a prior
                # eviction freed this capacity: greedy itself packed the
                # pristine ledgers.
                candidates.append((0.0, seq, node, [], {}, sim))
                continue
            victims = sorted(
                (
                    e
                    for e in getattr(node, "evictable", ())
                    if e.uid not in evicted and priority_tier(e.priority) < t
                ),
                key=lambda e: (e.cost, e.uid),
            )
            if not victims:
                continue
            prefix: List = []
            freed: dict = {}
            fits = False
            for e in victims:
                prefix.append(e)
                freed = resutil.merge(freed, e.requests)
                if resutil.fits(
                    total, resutil.merge(sim.cached_available, freed)
                ):
                    fits = True
                    break
            if not fits:
                continue
            cost = sum(e.cost for e in prefix)
            candidates.append((cost, seq, node, prefix, freed, sim))
        # cheapest node first; an add() rejection (port conflict, attach
        # limit, topology) reverts the credit and tries the next node, so
        # a requirements-incompatible cheap node never shadows a viable
        # eviction elsewhere
        for _cost, _seq, node, prefix, freed, sim in sorted(
            candidates, key=lambda c: (c[0], c[1])
        ):
            before_avail = dict(sim.cached_available)
            sim.cached_available = resutil.merge(sim.cached_available, freed)
            try:
                sim.add(pod, req)
            except IncompatibleError:
                sim.cached_available = before_avail
                continue
            for e in prefix:
                evicted.add(e.uid)
            if prefix:
                evictions.setdefault(node.name, []).extend(
                    e.uid for e in prefix
                )
            errors.pop(pod.uid, None)
            break
