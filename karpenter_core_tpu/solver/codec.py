"""Snapshot wire codec: the solver's process boundary.

SURVEY §7 and BASELINE frame the solver as a service a control plane talks
to over gRPC/DCN; this codec is that boundary's payload format. A solve
request (the ``Snapshot`` from solver/snapshot.py — pure numpy + interned
vocab) and a solve response (per-class slot assignments) round-trip
through bytes with no Python-specific pickling: arrays ride npz, the
vocab/metadata ride JSON. A Go (or any) client can produce the same
layout; the in-process path simply skips the codec.
"""
from __future__ import annotations

import io
import json
from typing import Dict, List, Tuple

import numpy as np

from karpenter_core_tpu.solver.vocab import EntityMasks, Vocab

_HEADER_KEY = "__header__"


def _masks_to_arrays(prefix: str, m: EntityMasks, out: Dict[str, np.ndarray]):
    out[f"{prefix}_mask"] = m.mask
    out[f"{prefix}_defines"] = m.defines
    out[f"{prefix}_concrete"] = m.concrete
    out[f"{prefix}_negative"] = m.negative
    out[f"{prefix}_gt"] = m.gt
    out[f"{prefix}_lt"] = m.lt


def _masks_from_arrays(prefix: str, z) -> EntityMasks:
    return EntityMasks(
        mask=z[f"{prefix}_mask"],
        defines=z[f"{prefix}_defines"],
        concrete=z[f"{prefix}_concrete"],
        negative=z[f"{prefix}_negative"],
        gt=z[f"{prefix}_gt"],
        lt=z[f"{prefix}_lt"],
    )


def encode_request(
    vocab,
    resource_names: List[str],
    class_masks: EntityMasks,
    class_requests: np.ndarray,
    class_counts: np.ndarray,
    it_masks: EntityMasks,
    it_allocatable: np.ndarray,
) -> bytes:
    """Serialize one solve request. The vocab's interning tables travel in
    the header so the solver reconstructs the identical closed world."""
    header = {
        "version": 1,
        "resource_names": list(resource_names),
        "key_names": list(vocab.key_names),
        "value_names": [list(v) for v in vocab.value_names],
    }
    arrays: Dict[str, np.ndarray] = {
        "class_requests": class_requests,
        "class_counts": class_counts,
        "it_allocatable": it_allocatable,
    }
    _masks_to_arrays("class", class_masks, arrays)
    _masks_to_arrays("it", it_masks, arrays)
    buf = io.BytesIO()
    arrays[_HEADER_KEY] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8
    )
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def decode_request(data: bytes):
    """Inverse of encode_request: (vocab, resource_names, class_masks,
    class_requests, class_counts, it_masks, it_allocatable)."""
    z = np.load(io.BytesIO(data))
    header = json.loads(bytes(z[_HEADER_KEY]).decode())
    # re-intern through Vocab so derived tables (int_values, valid) match
    # the sender's exactly — insertion order preserves every id
    v = Vocab()
    for key in header["key_names"]:
        v.key_id(key)
    for key, names in zip(header["key_names"], header["value_names"]):
        for name in names:
            v.value_id(key, name)
    vocab = v.finalize()
    return (
        vocab,
        list(header["resource_names"]),
        _masks_from_arrays("class", z),
        z["class_requests"],
        z["class_counts"],
        _masks_from_arrays("it", z),
        z["it_allocatable"],
    )


def encode_response(
    takes: np.ndarray, unplaced: np.ndarray, slot_template: np.ndarray
) -> bytes:
    """Serialize one solve response: per-step × per-slot take counts plus
    the chosen template per fresh slot."""
    buf = io.BytesIO()
    np.savez_compressed(
        buf, takes=takes, unplaced=unplaced, slot_template=slot_template
    )
    return buf.getvalue()


def decode_response(data: bytes) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    z = np.load(io.BytesIO(data))
    return z["takes"], z["unplaced"], z["slot_template"]
