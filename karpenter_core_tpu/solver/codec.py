"""Snapshot wire codec: the solver's process boundary.

SURVEY §7 and BASELINE frame the solver as a service a control plane talks
to over gRPC/DCN; this codec is that boundary's payload format, and the
solverd sidecar (solver/service.py, driven by solver/remote.py) actually
serves it. A solve request (the ``Snapshot`` from solver/snapshot.py —
pure numpy + interned vocab) and a solve response (per-class slot
assignments) round-trip through bytes with no Python-specific pickling:
arrays ride npz, the vocab/metadata ride JSON. A Go (or any) client can
produce the same layout; the in-process path simply skips the codec.
The solverd section below extends the same container to the FULL
scheduler input/output (solve problems, results, consolidation sweeps).

The field set of every encoder here is FROZEN per wire version in
tools/graftlint/wire_schema.lock.json (graftlint GL403): changing a
payload's fields without bumping the governing version constant fails
the lint. Codec-PR workflow: edit, bump SNAPSHOT_WIRE_VERSION /
SOLVE_WIRE_VERSION, run `python -m tools.graftlint --update-wire-lock`,
commit the regenerated lock alongside.
"""
from __future__ import annotations

import io
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_core_tpu.solver.vocab import EntityMasks, Vocab

_HEADER_KEY = "__header__"

# the snapshot (pre-tensorized subproblem) wire; the full solverd wire
# below versions separately as SOLVE_WIRE_VERSION
SNAPSHOT_WIRE_VERSION = 1


def _masks_to_arrays(prefix: str, m: EntityMasks, out: Dict[str, np.ndarray]):
    out[f"{prefix}_mask"] = m.mask
    out[f"{prefix}_defines"] = m.defines
    out[f"{prefix}_concrete"] = m.concrete
    out[f"{prefix}_negative"] = m.negative
    out[f"{prefix}_gt"] = m.gt
    out[f"{prefix}_lt"] = m.lt


def _masks_from_arrays(prefix: str, z) -> EntityMasks:
    return EntityMasks(
        mask=z[f"{prefix}_mask"],
        defines=z[f"{prefix}_defines"],
        concrete=z[f"{prefix}_concrete"],
        negative=z[f"{prefix}_negative"],
        gt=z[f"{prefix}_gt"],
        lt=z[f"{prefix}_lt"],
    )


def encode_request(
    vocab,
    resource_names: List[str],
    class_masks: EntityMasks,
    class_requests: np.ndarray,
    class_counts: np.ndarray,
    it_masks: EntityMasks,
    it_allocatable: np.ndarray,
) -> bytes:
    """Serialize one solve request. The vocab's interning tables travel in
    the header so the solver reconstructs the identical closed world."""
    header = {
        "version": SNAPSHOT_WIRE_VERSION,
        "resource_names": list(resource_names),
        "key_names": list(vocab.key_names),
        "value_names": [list(v) for v in vocab.value_names],
    }
    arrays: Dict[str, np.ndarray] = {
        "class_requests": class_requests,
        "class_counts": class_counts,
        "it_allocatable": it_allocatable,
    }
    _masks_to_arrays("class", class_masks, arrays)
    _masks_to_arrays("it", it_masks, arrays)
    buf = io.BytesIO()
    arrays[_HEADER_KEY] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8
    )
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def decode_request(data: bytes):
    """Inverse of encode_request: (vocab, resource_names, class_masks,
    class_requests, class_counts, it_masks, it_allocatable)."""
    z = _load_npz(data)
    header = json.loads(bytes(z[_HEADER_KEY]).decode())
    if header.get("version") != SNAPSHOT_WIRE_VERSION:
        # explicit skew error, same policy as the solverd decoders below: a
        # sender on a different wire layout must not surface as a shape
        # mismatch three layers deeper
        raise ValueError(
            f"unsupported snapshot wire version {header.get('version')}"
        )
    # re-intern through Vocab so derived tables (int_values, valid) match
    # the sender's exactly — insertion order preserves every id
    v = Vocab()
    for key in header["key_names"]:
        v.key_id(key)
    for key, names in zip(header["key_names"], header["value_names"]):
        for name in names:
            v.value_id(key, name)
    vocab = v.finalize()
    return (
        vocab,
        list(header["resource_names"]),
        _masks_from_arrays("class", z),
        z["class_requests"],
        z["class_counts"],
        _masks_from_arrays("it", z),
        z["it_allocatable"],
    )


def encode_response(
    takes: np.ndarray, unplaced: np.ndarray, slot_template: np.ndarray
) -> bytes:
    """Serialize one solve response: per-step × per-slot take counts plus
    the chosen template per fresh slot."""
    buf = io.BytesIO()
    np.savez_compressed(
        buf, takes=takes, unplaced=unplaced, slot_template=slot_template
    )
    return buf.getvalue()


def decode_response(data: bytes) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    z = _load_npz(data)
    return z["takes"], z["unplaced"], z["slot_template"]


# ---------------------------------------------------------------------------
# solverd wire format: the full solve problem and its results.
#
# The snapshot codec above carries one pre-tensorized subproblem; the solverd
# sidecar (solver/service.py) instead receives the whole scheduler input —
# nodepools, per-pool instance types, existing SimNodes, daemonset pods,
# pending pods, topology context — runs DeviceScheduler server-side, and
# returns placements keyed by pod uid / node name / instance-type name so the
# client (solver/remote.py) re-binds them to its own live objects. Same
# container as above (npz; object payloads ride the JSON header), no
# pickling: API objects go through kube/serial's closed-world registry and
# the solver-side types (Requirement, InstanceType, SimNode) get explicit
# field codecs below.
# ---------------------------------------------------------------------------

# v2: solve requests carry unavailable_offerings (the ICE-cache snapshot).
# The field is load-bearing — an old sidecar that silently dropped it would
# pack onto stocked-out offerings and re-open the create→ICE→delete
# livelock — so the version bumps and a mixed deployment fails EXPLICITLY
# (version-skew error → greedy degradation with the decode-failure metric)
# instead of silently losing the mask.
# v3: evictable-pod views + eviction claims (gangsched, ISSUE 10).
# v4: solver_mode — the per-request backend selector behind the Solver
# seam (relaxsolve, ISSUE 13): "ffd" | "relax", back-compat default "ffd"
# when absent. Load-bearing the same way the ICE mask was: an old sidecar
# silently dropping it would serve the heuristic packer to a client that
# asked for (and will be judged on) the optimizing one.
# v5: the delta wire (segmentstore, ISSUE 14) — a solve request may now be
# a MANIFEST of content-addressed segment digests (solver/segments.py)
# instead of the full problem; the sidecar answers a typed miss for
# digests its store lost, and problem_fingerprint becomes derivable from
# the manifest's problem-half digests (both request forms compute the
# SAME fingerprint, so the scheduler cache never splits on wire form).
# The full-wire form stays first-class at v5 — it is the fallback when a
# sidecar cannot resolve a manifest even after the re-upload round.
# v6: prev_fingerprint — the prior-solve reference (incsolve, ISSUE 16).
# NOT load-bearing for correctness (a daemon that ignores it just solves
# fresh, which is always a valid answer), but the version bumps anyway:
# the wire-schema lock (GL403) makes every field-set change an explicit,
# reviewed bump, and a mixed deployment degrades EXPLICITLY through the
# version-skew error → greedy fallback instead of silently shedding the
# warm-start. Key omitted when empty, so a non-incremental request's
# header carries no trace of the feature.
# v7: topoaware gang placement (ISSUE 20). No new fields — rack/superpod
# node labels and the pod-group rank/max-hops annotations ride the
# existing label/annotation maps — but the RESULT contract changed:
# claims' pod_uids now come back rank-ordered for ranked gangs and a
# placement exceeding a hard max-hops bound is rejected server-side, so a
# mixed deployment must degrade explicitly through the version-skew error
# rather than silently serving distance-blind placements to a client
# whose verifier enforces the distance bound. Hostile wire rank/max-hops
# ints are range-clamped at the annotation parse (solver/gangs.gang_rank
# / gang_max_hops, the registered GL601 normalizers) before any int32
# plane store — the eviction-priority (priority_tier) precedent.
SOLVE_WIRE_VERSION = 7

# the solver backends a request may select; "" means unspecified (the
# serving daemon's default applies)
SOLVER_MODES = ("ffd", "relax")


def _json_payload(header: dict) -> bytes:
    arrays = {
        _HEADER_KEY: np.frombuffer(json.dumps(header).encode(), dtype=np.uint8)
    }
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def _load_npz(data: bytes):
    """np.load with container-level damage normalized to ValueError: a
    truncated/corrupt npz raises zipfile.BadZipFile (and friends) which
    would sail past the decode-failure nets in solver/remote.py — every
    decoder here funnels through this so "malformed bytes" is ALWAYS a
    ValueError, never a transport-specific surprise in a reconciler."""
    import zipfile

    try:
        return np.load(io.BytesIO(data))
    except (zipfile.BadZipFile, OSError, EOFError, IndexError) as e:
        raise ValueError(f"malformed wire container: {e}") from e


def _json_header(data: bytes) -> dict:
    z = _load_npz(data)
    try:
        return json.loads(bytes(z[_HEADER_KEY]).decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"malformed wire header: {e}") from e


def _encode_req(r) -> dict:
    return {
        "key": r.key,
        "complement": r.complement,
        "values": sorted(r.values),
        "gt": r.greater_than,
        "lt": r.less_than,
        "min_values": r.min_values,
    }


def _decode_req(d: dict):
    from karpenter_core_tpu.scheduling.requirement import Requirement

    return Requirement(
        d["key"],
        complement=d["complement"],
        values=d["values"],
        greater_than=d["gt"],
        less_than=d["lt"],
        min_values=d["min_values"],
    )


def _encode_reqs(reqs) -> List[dict]:
    # key-sorted so the wire bytes — and the problem fingerprint computed
    # over the decoded header — are canonical for one logical Requirements
    # regardless of host-side insertion order
    return [_encode_req(reqs[k]) for k in sorted(reqs)]


def _decode_reqs(items: List[dict]):
    from karpenter_core_tpu.scheduling import Requirements

    out = Requirements()
    # bypass add()'s intersection: the wire carries final requirement sets
    for d in items:
        r = _decode_req(d)
        out[r.key] = r
    return out


def _encode_instance_type(it) -> dict:
    return {
        "name": it.name,
        "requirements": _encode_reqs(it.requirements),
        "offerings": [
            {
                "requirements": _encode_reqs(o.requirements),
                "price": o.price,
                "available": o.available,
            }
            for o in it.offerings
        ],
        "capacity": dict(it.capacity),
        "overhead": dict(it.overhead),
    }


def _decode_instance_type(d: dict):
    from karpenter_core_tpu.cloudprovider.types import (
        InstanceType,
        Offering,
        Offerings,
    )

    return InstanceType(
        name=d["name"],
        requirements=_decode_reqs(d["requirements"]),
        offerings=Offerings(
            Offering(
                requirements=_decode_reqs(o["requirements"]),
                price=o["price"],
                available=o["available"],
            )
            for o in d["offerings"]
        ),
        capacity=dict(d["capacity"]),
        overhead=dict(d["overhead"]),
    )


def _encode_it_table(instance_types: Dict[str, list]) -> Tuple[list, dict]:
    """(table, per-pool index lists). Instance-type OBJECT IDENTITY is part
    of the solve input (catalog union dedupes by id), so objects shared
    across pools encode once and decode back to one shared object."""
    table: List[dict] = []
    index: Dict[int, int] = {}
    pools: Dict[str, List[int]] = {}
    # pool-sorted so the table's row order (a wire LIST, which the problem
    # fingerprint hashes positionally) is canonical per logical catalog
    for pool, its in sorted(instance_types.items()):
        rows = []
        for it in its:
            ti = index.get(id(it))
            if ti is None:
                ti = index[id(it)] = len(table)
                table.append(_encode_instance_type(it))
            rows.append(ti)
        pools[pool] = rows
    return table, pools


def _decode_it_table(table: list, pools: dict) -> Dict[str, list]:
    objs = [_decode_instance_type(d) for d in table]
    return {pool: [objs[i] for i in rows] for pool, rows in pools.items()}


def _encode_volume_usage(vu) -> Optional[dict]:
    if vu is None:
        return None
    return {
        "limits": dict(vu.limits),
        "volumes": {k: sorted(v) for k, v in sorted(vu.volumes.items())},
    }


def _decode_volume_usage(d: Optional[dict]):
    if d is None:
        return None
    from karpenter_core_tpu.scheduling.volumeusage import VolumeUsage

    vu = VolumeUsage()
    vu.limits = dict(d["limits"])
    vu.volumes = {k: set(v) for k, v in d["volumes"].items()}
    return vu


def _encode_sim_node(n) -> dict:
    from karpenter_core_tpu.kube import serial

    out = {
        "name": n.name,
        "labels": dict(n.labels),
        "taints": [serial.encode(t) for t in n.taints],
        "available": dict(n.available),
        "capacity": dict(n.capacity),
        "daemon_requests": dict(n.daemon_requests),
        "initialized": n.initialized,
        "nodeclaim_name": n.nodeclaim_name,
        "nodepool_name": n.nodepool_name,
        "volume_usage": _encode_volume_usage(n.volume_usage),
    }
    # evictable bound pods (gangsched, ISSUE 10): the capacity views a
    # priority-preemptive solve may claim as victims. Key omitted when
    # empty — a node with nothing evictable encodes exactly like a
    # pre-gang one, and the canonical (cost, uid) order keeps the
    # problem fingerprint stable across operator relist order.
    ev = getattr(n, "evictable", ()) or ()
    if ev:
        out.update({
            "evictable": [
                {
                    "uid": e.uid,
                    "priority": e.priority,
                    "requests": dict(e.requests),
                    "cost": e.cost,
                }
                for e in sorted(ev, key=lambda e: (e.cost, e.uid))
            ],
        })
    return out


def _decode_sim_node(d: dict):
    from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
        SimNode,
    )
    from karpenter_core_tpu.kube import serial

    from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
        EvictablePod,
    )
    from karpenter_core_tpu.utils.disruption import priority_tier

    return SimNode(
        name=d["name"],
        labels=dict(d["labels"]),
        taints=[serial.decode(t) for t in d["taints"]],
        available=dict(d["available"]),
        capacity=dict(d["capacity"]),
        daemon_requests=dict(d["daemon_requests"]),
        initialized=d["initialized"],
        nodeclaim_name=d["nodeclaim_name"],
        nodepool_name=d["nodepool_name"],
        volume_usage=_decode_volume_usage(d["volume_usage"]),
        # absent from pre-gangsched encoders -> nothing evictable. The
        # priority clamps through priority_tier at the decode net: the
        # legitimate path (state/cluster._evictable_on) already ships a
        # tier, and an unclamped hostile value would overflow the int32
        # EvPlanes tensor INSIDE the exclusive device window — a crash
        # charged as poison where a cheap corrupt-wire rejection belongs.
        evictable=tuple(
            EvictablePod(
                uid=e["uid"],
                priority=priority_tier(int(e["priority"])),
                requests=dict(e["requests"]),
                cost=float(e["cost"]),
            )
            for e in d.get("evictable", ())
        ),
    )


def _pod_sort_key(p):
    return (p.metadata.namespace or "", p.metadata.name or "", p.uid)


def _encode_topology(topo) -> Optional[dict]:
    from karpenter_core_tpu.kube import serial

    if topo is None:
        return None
    return {
        "domains": {k: sorted(v) for k, v in sorted(topo.domains.items())},
        # canonical (node, pod) order: domain counting on decode is
        # order-insensitive, and this list rides the problem fingerprint
        "existing_pods": [
            [serial.encode(p), dict(labels), name]
            for p, labels, name in sorted(
                topo.existing_pods,
                key=lambda t: (t[2], _pod_sort_key(t[0])),
            )
        ],
        "excluded": sorted(topo.excluded_pods),
    }


def _decode_topology(d: Optional[dict]):
    if d is None:
        return None
    from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
        Topology,
    )
    from karpenter_core_tpu.kube import serial

    return Topology(
        domains={k: set(v) for k, v in d["domains"].items()},
        existing_pods=[
            (serial.decode(p), dict(labels), name)
            for p, labels, name in d["existing_pods"]
        ],
        excluded_pod_uids=d["excluded"],
    )


# graftlint: disable=GL401 -- encode_solve_request delegates its whole
# header to _encode_solve_header (whose field set GL401 checks against
# _decode_solve_header directly, including "version"); "kind" and
# "wire_kind" are decode_solve_request's FORM-dispatch surface shared
# with encode_manifest_request — the one-level twin pairing cannot see
# either relationship, and the twins it cannot pair are each locked by
# GL403 at SOLVE_WIRE_VERSION
def encode_solve_request(
    nodepools,
    instance_types: Dict[str, list],
    existing_nodes,
    daemonset_pods,
    pods,
    topology=None,
    max_slots: int = 256,
    unavailable_offerings=(),
    tenant: str = "default",
    solver_mode: str = "ffd",
    prev_fingerprint: str = "",
) -> bytes:
    """Serialize a full scheduler input for the solverd sidecar.
    ``unavailable_offerings`` is the control plane's ICE-cache snapshot
    (instance-type×zone×capacity-type triples); it rides the wire so the
    sidecar's DeviceScheduler masks the same offerings the client would.
    ``tenant`` identifies the sending operator to the fleet gateway
    (solver/fleet.py) for fair queueing and per-tenant accounting; it
    defaults to the single-tenant id so a pre-fleet client stays valid on
    the same wire version (an old sidecar ignoring it loses only
    accounting, never placements — unlike the load-bearing ICE mask).
    ``solver_mode`` selects the solve backend behind the Solver seam
    (relaxsolve, ISSUE 13): "ffd" (first-fit-decreasing, the classic
    path) or "relax" (convex-relaxation optimizer with the FFD result as
    the scored/anytime fallback); it also rides the X-Solver-Mode header
    so the gateway can route pre-decode.
    ``prev_fingerprint`` names the problem fingerprint of the CLIENT's
    last verified solve against this sidecar (incsolve, ISSUE 16): the
    serving daemon may replay the unchanged half of that packing from
    its ledger. Non-load-bearing like ``tenant`` — a sidecar that drops
    or predates it solves fresh, never wrongly — so it rides the same
    wire version, omitted when empty (the evictions idiom)."""
    return _json_payload(_encode_solve_header(
        nodepools,
        instance_types,
        existing_nodes,
        daemonset_pods,
        pods,
        topology=topology,
        max_slots=max_slots,
        unavailable_offerings=unavailable_offerings,
        tenant=tenant,
        solver_mode=solver_mode,
        prev_fingerprint=prev_fingerprint,
    ))


def _encode_solve_header(
    nodepools,
    instance_types: Dict[str, list],
    existing_nodes,
    daemonset_pods,
    pods,
    topology=None,
    max_slots: int = 256,
    unavailable_offerings=(),
    tenant: str = "default",
    solver_mode: str = "ffd",
    prev_fingerprint: str = "",
) -> dict:
    """The full solve header as a dict — encode_solve_request's payload
    before the npz container, shared by the full wire (v1..v5 shape) and
    the delta wire (solver/segments.py splits this exact dict into
    content-addressed segments, so the manifest path is wire-equivalent
    by construction)."""
    if solver_mode not in SOLVER_MODES:
        raise ValueError(f"unknown solver mode {solver_mode!r}")
    from karpenter_core_tpu.kube import serial

    table, pools = _encode_it_table(instance_types)
    # every PROBLEM-half list is hashed positionally by problem_fingerprint,
    # so each gets a canonical order: a restarted operator (or a second
    # replica) relisting the same cluster in a different order must produce
    # the same fingerprint, or the sidecar's warm scheduler cache misses on
    # every solve. Safe because the decode side is order-insensitive: the
    # DeviceScheduler re-sorts nodepools/existing nodes itself and daemon
    # overhead is a sum. The pending pods keep caller order — it is the
    # queue order the solve lifts to classes, and it is excluded from the
    # fingerprint anyway.
    header = {
        "version": SOLVE_WIRE_VERSION,
        "nodepools": [
            serial.encode(np_)
            for np_ in sorted(nodepools, key=lambda n: n.metadata.name)
        ],
        "it_table": table,
        "it_pools": pools,
        "existing_nodes": [
            _encode_sim_node(n)
            for n in sorted(existing_nodes, key=lambda n: n.name)
        ],
        "daemonset_pods": [
            serial.encode(p)
            for p in sorted(daemonset_pods, key=_pod_sort_key)
        ],
        "pods": [serial.encode(p) for p in pods],
        "topology": _encode_topology(topology),
        "max_slots": max_slots,
        "unavailable_offerings": sorted(
            list(k) for k in unavailable_offerings
        ),
        "tenant": tenant,
        "solver_mode": solver_mode,
    }
    # prior-solve reference (incsolve, ISSUE 16 / wire v6): key omitted
    # when empty so a non-incremental request's header carries no trace
    # of the feature — and the fingerprint probes (solver/segments.py)
    # never see it either way, so naming a predecessor cannot churn the
    # scheduler-cache key it warms
    if prev_fingerprint:
        header.update({"prev_fingerprint": prev_fingerprint})
    return header


def problem_fingerprint(header: dict) -> str:
    """Stable content hash of a solve request's PROBLEM half — everything
    except the pending pods (nodepools, catalog, existing nodes, daemonset
    pods, topology context, limits, ICE snapshot). Two requests with equal
    fingerprints describe the same cluster, so the sidecar can reuse one
    DeviceScheduler — and with it the prepared-state caches — across RPC
    calls, re-solving only the pod mix.

    v5: derived from the manifest's problem-half SEGMENT DIGESTS
    (solver/segments.py splits the header canonically and hashes the
    sorted (kind, digest) pairs), so a manifest request computes the
    identical fingerprint from its digest listing alone — the PR 3
    prepared-state cache and the PR 5 scheduler cache key off digests and
    hit across restarts of either side and across wire forms.

    The exclusions carry over from v4 unchanged: the tenant is routing
    metadata, not problem content (the cache is content-addressed,
    isolation is the gateway's job); solver_mode is excluded because the
    serving daemon appends the RESOLVED mode itself; and the topology
    context's excluded-uid list is derived from the PENDING pods, so
    hashing it would churn the scheduler cache on every reconcile (the
    solve side re-reads the live context on every cache hit)."""
    from karpenter_core_tpu.solver import segments

    return segments.fingerprint_of_header(header)


# decode-net clamp for the wire's slot ceiling: max_slots sizes every
# device plane's slot axis, so a hostile (or fat-fingered) huge value
# would allocate unbounded device memory INSIDE the exclusive device
# window — a crash charged as poison where a cheap decode clamp belongs.
# 1 << 20 mirrors models/provisioner._SLOT_HARD_CAP (one slot per pod at
# 1M pods, far past any real solve; the adaptive regrow loop refuses to
# cross it anyway, so clamping here never changes a solvable problem).
_MAX_SLOTS_CAP = 1 << 20


def _clamp_slots(n) -> int:
    """Normalize a wire-decoded slot ceiling to [1, _MAX_SLOTS_CAP]."""
    try:
        n = int(n)
    except (TypeError, ValueError):
        raise ValueError(f"malformed max_slots on the wire: {n!r}")
    return max(1, min(n, _MAX_SLOTS_CAP))


def _pow2_bucket(n: int, lo: int = 8) -> int:
    """Next power of two >= lo — the same axis-bucketing rule the device
    planes use (models/provisioner._bucket), duplicated here so the wire
    layer stays import-light."""
    return max(lo, 1 << max(n - 1, 1).bit_length())


def problem_bucket(header: dict) -> str:
    """Shape-bucket key for cross-tenant solve coalescing (fleet gateway).

    Two requests in the same bucket are PREDICTED to compile to the same
    padded kernel shapes, so the gateway may dispatch them as one vmapped
    multi-problem device batch. Derived from the problem_fingerprint
    components that drive compile shapes — catalog/nodepool/existing-node/
    daemonset cardinalities, the slot ceiling, the pod-count bucket, and
    topology presence — NOT from their content: two tenants with
    different catalogs of the same shape share a bucket (that is the whole
    point), while the exact-shape check lives one layer down
    (models/provisioner.solve_batch groups by real compile shapes and
    splits any batch the predictor got wrong, so a bucket collision can
    cost a missed coalesce but never a wrong result).

    Gangsched (ISSUE 10) shape components: tiers-active, the tier-count
    bucket, gang presence, and evictable-capacity presence join the key,
    because a gang/priority problem dispatches DIFFERENT kernels
    (gang_solve / preempt_pass) with extra tensor arguments — its compile
    shapes can never match a plain problem's, so coalescing them into one
    PR 9 vmap batch would split every batch at the shape_key check.
    Tiers-ACTIVE (any non-zero tier) is the shape-relevant bit: the
    prepared step-tier/step-gang rows attach exactly when it holds, so an
    all-default problem and an all-tier-100 problem can never share
    kernel shapes even though both have one distinct tier. Tier COUNT
    (not values) additionally rides the bucket for the step-axis layout;
    two active-tier problems with the same count may still coalesce."""
    import hashlib

    from karpenter_core_tpu.solver.gangs import GANG_ANNOTATION

    tiers = set()
    has_gangs = False
    for p in header.get("pods", ()):
        if isinstance(p, dict):
            tiers.add(int(p.get("priority") or 0))
            md = p.get("metadata") or {}
            ann = md.get("annotations") or {}
            if ann.get(GANG_ANNOTATION):
                has_gangs = True
    has_evictable = any(
        n.get("evictable") for n in header.get("existing_nodes", ())
        if isinstance(n, dict)
    )
    parts = (
        SOLVE_WIRE_VERSION,
        _pow2_bucket(len(header.get("it_table", ())), lo=1),
        len(header.get("nodepools", ())),
        _pow2_bucket(len(header.get("existing_nodes", ())) + 1, lo=1),
        _pow2_bucket(len(header.get("daemonset_pods", ())) + 1, lo=1),
        _pow2_bucket(len(header.get("pods", ())), lo=8),
        header.get("max_slots", 0),
        bool(header.get("topology")),
        any(t != 0 for t in tiers),
        _pow2_bucket(len(tiers), lo=1),
        has_gangs,
        has_evictable,
        # solver mode (relaxsolve, ISSUE 13): a relax problem's dispatch
        # stream interleaves assignment kernels and candidate re-solves
        # an ffd problem never issues, so the two modes must never
        # coalesce into one vmapped batch — the bucket splits here and
        # _KernelRequest.shape_key (mode component) backstops one layer
        # down for anything that slips past the predictor. Normalized
        # (absent == the ffd default) so a mode-less client and an
        # explicit-default one still coalesce; the serving daemon
        # additionally suffixes the ticket bucket with the RESOLVED mode,
        # which is what a non-default daemon default rides on.
        str(header.get("solver_mode") or "ffd"),
    )
    return hashlib.sha256(repr(parts).encode()).hexdigest()[:16]


def encode_manifest_request(plan, include=None, base=None) -> bytes:
    """Serialize a delta-wire solve request from a SegmentPlan
    (solver/segments.split_solve_header): the digest listing + inline
    remainder + pod-layout arrays, plus the segment BODIES named by
    ``include`` (None ships everything — the cold-start / full-repair
    form; an empty list ships a pure manifest). Same npz container as
    every other payload; the uploads ride as ``seg_<digest>`` byte
    arrays so one request carries the whole miss repair.

    ``base`` = (previous listing digest, previous rows): the steady-state
    form — instead of the full digest listing (hundreds of rows, hex is
    incompressible), ship ``listing_base`` + the row EDITS against it.
    The daemon holds recent listings content-addressed in its segment
    store; a lost base is a typed miss like any segment, answered by
    resending the full listing."""
    # uploads pack into ONE byte blob (indexed by digest+length in the
    # header): deflate then compresses ACROSS segments — changed node
    # buckets share most of their structure, and per-entry zip overhead
    # would otherwise dominate small repairs
    blobs: List[bytes] = []
    index: List[List] = []
    for dg in (plan.all_digests() if include is None else include):
        data = plan.segments.get(dg)
        if data is not None:
            blobs.append(data)
            index.append([dg, len(data)])
    if base is not None and base[0] != plan.listing_digest:
        prev_set = {tuple(r) for r in base[1]}
        cur_set = {tuple(r) for r in plan.listing}
        header = {
            "version": SOLVE_WIRE_VERSION,
            "kind": "manifest",
            "listing_base": base[0],
            "segments_add": sorted(
                [list(r) for r in cur_set - prev_set]
            ),
            "segments_drop": sorted(
                [list(r) for r in prev_set - cur_set]
            ),
            # integrity pin: the daemon verifies its reconstruction
            # hashes to the listing the pod layout was computed over
            "listing_digest": plan.listing_digest,
            "upload_index": index,
            "inline": plan.inline,
        }
    elif base is not None:
        # unchanged problem half AND pod batches: the smallest wire form
        header = {
            "version": SOLVE_WIRE_VERSION,
            "kind": "manifest",
            "listing_base": base[0],
            "segments_add": [],
            "segments_drop": [],
            "listing_digest": plan.listing_digest,
            "upload_index": index,
            "inline": plan.inline,
        }
    else:
        header = {
            "version": SOLVE_WIRE_VERSION,
            "kind": "manifest",
            "segments": plan.listing,
            "upload_index": index,
            "inline": plan.inline,
        }
    arrays: Dict[str, np.ndarray] = {
        _HEADER_KEY: np.frombuffer(
            json.dumps(header).encode(), dtype=np.uint8
        ),
        "pod_batch": np.asarray(plan.pod_batch, dtype=np.int32),
        "pod_member": np.asarray(plan.pod_member, dtype=np.int32),
        "uploads": np.frombuffer(b"".join(blobs), dtype=np.uint8),
    }
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def _encode_manifest_inline(header: dict) -> dict:
    """The manifest's non-content-addressed remainder: pod-half scalars
    and presence flags. Everything here either changes per solve (tenant
    routing, the pod-derived topology exclusions) or is too small to be
    worth a digest round trip (the ICE snapshot, the slot ceiling). The
    field set is frozen in the GL403 wire lock like every encoder's."""
    topo = header.get("topology")
    # .get with the decoders' back-compat defaults: a header a foreign or
    # older client built without the optional fields must still split
    # (and fingerprint) — absent folds to the same value as an explicit
    # default, exactly as decode_solve_request resolves it
    return {
        "max_slots": header.get("max_slots", 256),
        "tenant": header.get("tenant", "default"),
        "solver_mode": header.get("solver_mode", ""),
        "unavailable_offerings": header.get("unavailable_offerings", []),
        "has_topology": topo is not None,
        "topo_excluded": None if topo is None else topo.get("excluded"),
        "prev_fingerprint": header.get("prev_fingerprint", ""),
    }


def decode_solve_request(data: bytes, segment_store=None) -> dict:
    """Inverse of encode_solve_request; returns a kwargs-style dict (plus
    ``fingerprint``, the problem-half content hash for scheduler reuse,
    ``bucket``, the coalescing shape-bucket key, and ``wire_kind`` —
    ``full`` | ``manifest``). A v5 manifest body resolves through
    ``segment_store`` (solver/segments.py); a store miss raises
    segments.SegmentMissError naming the digests, which the HTTP layer
    turns into the typed 409 answer — never a wrong solve."""
    h = _json_header(data)
    if h["version"] != SOLVE_WIRE_VERSION:
        raise ValueError(f"unsupported solve wire version {h['version']}")
    if h.get("kind") == "manifest":
        return decode_manifest_request(data, segment_store, header=h)
    out = _decode_solve_header(h)
    out["wire_kind"] = "full"
    # the scheduler cache's entry-weight proxy: for the full wire the
    # body IS the problem's byte scale
    out["approx_bytes"] = len(data)
    return out


def decode_manifest_request(
    data: bytes, segment_store=None, header: dict = None
) -> dict:
    """Inverse of encode_manifest_request: store any segment uploads
    riding the body (content-verified — an upload that does not hash to
    its claimed digest is corrupt wire, so a hostile tenant can never
    poison another tenant's manifest through the shared store), assemble
    the full header from the store, and decode it exactly like the full
    wire. The fingerprint is computed from the manifest's digest listing
    alone — the derivability the scheduler caches key on."""
    from karpenter_core_tpu.solver import segments

    h = header if header is not None else _json_header(data)
    if h.get("version") != SOLVE_WIRE_VERSION:
        raise ValueError(f"unsupported solve wire version {h.get('version')}")
    if h.get("kind") != "manifest":
        raise ValueError(f"not a manifest request: kind={h.get('kind')!r}")
    if segment_store is None:
        raise ValueError(
            "manifest solve request but no segment store is configured"
        )
    inline = _decode_manifest_inline(h.get("inline"))
    z = _load_npz(data)
    index = h.get("upload_index", [])
    if not isinstance(index, list):
        raise ValueError(f"malformed upload index: {index!r}")
    if index:
        from karpenter_core_tpu.solver.segments import digest_of

        blob = z["uploads"].tobytes()
        offset = 0
        for row in index:
            if (
                not isinstance(row, list) or len(row) != 2
                or not isinstance(row[0], str)
                or not isinstance(row[1], int) or row[1] < 0
            ):
                raise ValueError(f"malformed upload index row: {row!r}")
            dg, length = row
            piece = blob[offset:offset + length]
            offset += length
            if len(piece) != length or digest_of(piece) != dg:
                # content addressing is verified at the door: a hostile
                # or torn upload can never poison another tenant's
                # manifest through the shared store
                raise ValueError(
                    f"segment upload {dg[:12]} does not hash to its"
                    " claimed digest"
                )
            segment_store.put(dg, piece)
        if offset != len(blob):
            raise ValueError("upload blob length disagrees with its index")
    listing = _resolve_listing(
        h.get("segments"), h.get("listing_base"), h.get("segments_add"),
        h.get("segments_drop"), h.get("listing_digest"), segment_store,
    )
    segments.check_manifest_parts(listing, inline)
    if "pod_batch" not in z.files or "pod_member" not in z.files:
        raise ValueError("manifest body lost its pod layout arrays")
    # track the PROBLEM's real byte scale while assembling: a steady-state
    # manifest body is a few hundred bytes, so the scheduler cache's
    # byte-bound weight proxy must come from the resolved segments, not
    # from len(body) — or N delta-wire tenants would pin N full
    # schedulers the --cache-mib bound accounts as ~0
    fetched = [0]

    def fetch(dg):
        blob = segment_store.get(dg)
        if blob is not None:
            fetched[0] += len(blob)
        return blob

    assembled = segments.assemble_solve_header(
        listing, inline, z["pod_batch"], z["pod_member"], fetch,
    )
    # remember THIS listing content-addressed: the client's next manifest
    # names it as ``listing_base`` and ships only the row edits
    segment_store.put(
        segments.listing_digest_of(listing),
        segments.listing_bytes(listing),
    )
    return {
        # derivability is the point: the fingerprint comes from the
        # digest listing without re-canonicalizing the assembled content
        # (it equals the full-wire fingerprint of the same problem by
        # construction)
        **_decode_solve_header(
            assembled,
            fingerprint=segments.fingerprint_of_parts(listing, inline),
        ),
        "wire_kind": "manifest",
        "approx_bytes": fetched[0],
    }


def _resolve_listing(
    explicit, base, add, drop, want, segment_store
) -> list:
    """The manifest's digest listing: ``explicit`` (the full ``segments``
    rows) or reconstructed from ``listing_base`` + row edits against a
    listing the store holds from an earlier solve. A missing or DRIFTED
    base (the reconstruction's digest must match ``want`` — the listing
    the client computed its pod layout over) raises SegmentMissError for
    the base digest — the client answers by resending the full listing,
    so staleness self-heals in one round instead of mis-indexing a pod
    batch."""
    import json as _json

    from karpenter_core_tpu.solver import segments

    if explicit is not None:
        segments.check_manifest_parts(explicit, {})
        return segments.sort_listing(explicit)
    if not isinstance(base, str) or not base:
        raise ValueError("manifest names neither segments nor a base")
    raw = segment_store.get(base)
    if raw is None:
        raise segments.SegmentMissError([base])
    try:
        rows = _json.loads(raw.decode())
    except (UnicodeDecodeError, ValueError) as e:
        raise ValueError(f"stored base listing is malformed: {e}") from e
    for edits in (add, drop):
        if not isinstance(edits, list) or not all(
            isinstance(r, list) and len(r) == 2
            and all(isinstance(x, str) for x in r)
            for r in edits
        ):
            raise ValueError(f"malformed listing edits: {edits!r}")
    merged = (
        {tuple(r) for r in rows} - {tuple(r) for r in drop}
    ) | {tuple(r) for r in add}
    listing = segments.sort_listing(merged)
    if want and segments.listing_digest_of(listing) != want:
        # drift (evicted-and-readded base collision, corrupt edit set):
        # a typed miss, never a silently mis-assembled problem
        raise segments.SegmentMissError([base])
    return listing


def _decode_manifest_inline(inline) -> dict:
    """Twin of _encode_manifest_inline: shape-check and normalize the
    manifest's non-addressed remainder at the decode net (absent keys
    fold to the encoders' back-compat defaults, like the full wire's)."""
    if not isinstance(inline, dict):
        raise ValueError(f"manifest inline is not a dict: {inline!r}")
    return {
        "max_slots": inline.get("max_slots", 256),
        "tenant": inline.get("tenant", "default"),
        "solver_mode": inline.get("solver_mode", ""),
        "unavailable_offerings": inline.get("unavailable_offerings", []),
        "has_topology": bool(inline.get("has_topology")),
        "topo_excluded": inline.get("topo_excluded"),
        "prev_fingerprint": inline.get("prev_fingerprint", ""),
    }


def request_digest(data: bytes, segment_store=None) -> str:
    """Quarantine/poison key of a request body, stable per logical
    problem across wire forms: full-wire bodies hash their (canonical,
    PR 4) bytes; manifest bodies hash their CORE — digest listing +
    inline + pod layout — so the same problem keys identically whether
    or not segment uploads ride along (the miss/re-upload handshake must
    not split one poison problem into several strike streaks). A
    base+edits manifest reconstructs its listing through
    ``segment_store`` first. Any parse failure (or an unresolvable base)
    degrades to the raw-bytes hash, never a raise — this runs PRE-decode
    as the cheap refusal gate."""
    import hashlib

    from karpenter_core_tpu.solver import segments

    try:
        z = _load_npz(data)
        if "pod_batch" not in z.files:
            return hashlib.sha256(data).hexdigest()
        h = json.loads(bytes(z[_HEADER_KEY]).decode())
        if h.get("kind") != "manifest":
            return hashlib.sha256(data).hexdigest()
        if h.get("segments") is None and segment_store is None:
            return hashlib.sha256(data).hexdigest()
        listing = _resolve_listing(
            h.get("segments"), h.get("listing_base"),
            h.get("segments_add"), h.get("segments_drop"),
            h.get("listing_digest"), segment_store,
        )
        segments.check_manifest_parts(listing, h.get("inline"))
        return segments.core_digest_of(
            listing, h.get("inline"),
            z["pod_batch"], z["pod_member"],
        )
    except (
        ValueError, KeyError, TypeError, UnicodeDecodeError,
        segments.SegmentMissError,
    ):
        return hashlib.sha256(data).hexdigest()


def _decode_solve_header(h: dict, fingerprint: str = None) -> dict:
    """Twin of _encode_solve_header: the full-shape header dict (native
    or assembled from a manifest) to the kwargs-style problem dict. The
    version re-check is deliberate — assembled headers pass through here
    too, and a version skew must never surface as a shape mismatch.
    ``fingerprint`` lets the manifest path hand in its digest-derived
    value instead of re-canonicalizing the whole assembled header."""
    from karpenter_core_tpu.kube import serial

    from karpenter_core_tpu.cloudprovider.types import OfferingKey

    if h.get("version") != SOLVE_WIRE_VERSION:
        raise ValueError(f"unsupported solve wire version {h.get('version')}")
    return {
        "fingerprint": fingerprint or problem_fingerprint(h),
        "bucket": problem_bucket(h),
        "nodepools": [serial.decode(d) for d in h["nodepools"]],
        "instance_types": _decode_it_table(h["it_table"], h["it_pools"]),
        "existing_nodes": [_decode_sim_node(d) for d in h["existing_nodes"]],
        "daemonset_pods": [serial.decode(d) for d in h["daemonset_pods"]],
        "pods": [serial.decode(d) for d in h["pods"]],
        "topology": _decode_topology(h["topology"]),
        "max_slots": _clamp_slots(h["max_slots"]),
        # absent from pre-ICE-cache encoders -> empty set, same semantics
        "unavailable_offerings": frozenset(
            OfferingKey(*k) for k in h.get("unavailable_offerings", [])
        ),
        # absent from a pre-fleet encoder -> the single-tenant id
        "tenant": h.get("tenant", "default"),
        # back-compat default: absent/empty means "unspecified" and the
        # serving daemon's configured default applies (solverd
        # --solver-mode, "ffd" out of the box). Unknown values reject at
        # the decode net — an invalid mode must not surface as a
        # DeviceScheduler constructor raise inside the device window.
        "solver_mode": _check_mode(h.get("solver_mode", "")),
        # prior-solve reference (incsolve, ISSUE 16): absent/empty means
        # no predecessor — the daemon solves fresh, exactly as pre-16
        "prev_fingerprint": str(h.get("prev_fingerprint", "") or ""),
    }


def _check_mode(mode) -> str:
    if mode in SOLVER_MODES or mode == "":
        return mode
    raise ValueError(f"unknown solver mode on the wire: {mode!r}")


def encode_solve_results(results, solve_seconds: float) -> bytes:
    """Serialize a Results: placements by pod uid, instance types by name,
    nodepool by name — the client re-binds them to its live objects."""
    header = {
        "version": SOLVE_WIRE_VERSION,
        "claims": [
            {
                "nodepool": c.template.nodepool_name,
                "instance_types": [it.name for it in c.instance_type_options],
                "requirements": _encode_reqs(c.requirements),
                "requests": dict(c.requests),
                "pod_uids": [p.uid for p in c.pods],
            }
            for c in results.new_node_claims
        ],
        "existing": [
            {"node": sim.name, "pod_uids": [p.uid for p in sim.pods]}
            for sim in results.existing_nodes
        ],
        "errors": dict(results.pod_errors),
        "solve_seconds": solve_seconds,
    }
    # eviction claims (gangsched, ISSUE 10): node name -> victim uids the
    # operator drains before binding. Key omitted when empty, so every
    # non-preemptive solve's result wire is byte-identical to a pre-gang
    # build's at the same wire version (the off-by-default parity the
    # acceptance battery pins).
    evictions = getattr(results, "evictions", None)
    if evictions:
        header.update({
            "evictions": {
                node: list(uids) for node, uids in sorted(evictions.items())
            },
        })
    return _json_payload(header)


def decode_solve_results(data: bytes) -> dict:
    """Plain-data view of a solve response; solver/remote.py materializes
    Results from it against the caller's local objects (requirements decode
    here — they carry no identity)."""
    h = _json_header(data)
    if h.get("version") != SOLVE_WIRE_VERSION:
        # same explicit skew error as the request decoders — an external
        # sidecar on a different code version must not surface as a
        # mysterious per-solve fallback
        raise ValueError(
            f"unsupported solve wire version {h.get('version')}"
        )
    for claim in h["claims"]:
        claim["requirements"] = _decode_reqs(claim["requirements"])
    return h


def encode_frontier_request(
    nodepools,
    instance_types: Dict[str, list],
    cand_nodes,
    keep_nodes,
    daemonset_pods,
    base_pods,
    candidate_pods,
    max_slots: int = 1024,
    tenant: str = "default",
) -> bytes:
    """Serialize a consolidation-frontier sweep (models/consolidation.py)
    for the sidecar: candidate nodes FIRST (prefix p masks slots [0, p)).
    ``tenant`` as in encode_solve_request — gateway accounting only; the
    sweep rides the gateway's NORMAL lane, behind provisioning solves."""
    from karpenter_core_tpu.kube import serial

    table, pools = _encode_it_table(instance_types)
    header = {
        "version": SOLVE_WIRE_VERSION,
        "nodepools": [serial.encode(np_) for np_ in nodepools],
        "it_table": table,
        "it_pools": pools,
        "cand_nodes": [_encode_sim_node(n) for n in cand_nodes],
        "keep_nodes": [_encode_sim_node(n) for n in keep_nodes],
        "daemonset_pods": [serial.encode(p) for p in daemonset_pods],
        "base_pods": [serial.encode(p) for p in base_pods],
        "candidate_pods": [
            [serial.encode(p) for p in pods] for pods in candidate_pods
        ],
        "max_slots": max_slots,
        "tenant": tenant,
    }
    return _json_payload(header)


def decode_frontier_request(data: bytes) -> dict:
    from karpenter_core_tpu.kube import serial

    h = _json_header(data)
    if h["version"] != SOLVE_WIRE_VERSION:
        raise ValueError(f"unsupported solve wire version {h['version']}")
    return {
        "nodepools": [serial.decode(d) for d in h["nodepools"]],
        "instance_types": _decode_it_table(h["it_table"], h["it_pools"]),
        "cand_nodes": [_decode_sim_node(d) for d in h["cand_nodes"]],
        "keep_nodes": [_decode_sim_node(d) for d in h["keep_nodes"]],
        "daemonset_pods": [serial.decode(d) for d in h["daemonset_pods"]],
        "base_pods": [serial.decode(d) for d in h["base_pods"]],
        "candidate_pods": [
            [serial.decode(d) for d in pods] for pods in h["candidate_pods"]
        ],
        "max_slots": _clamp_slots(h["max_slots"]),
        "tenant": h.get("tenant", "default"),
    }


def encode_frontier_response(frontier) -> bytes:
    """frontier: list of (schedulable, new_nodes, price_lb) or None (the
    sweep could not represent the problem — caller binary-searches)."""
    if frontier is None:
        return _json_payload({"version": SOLVE_WIRE_VERSION, "available": False})
    arrays = {
        _HEADER_KEY: np.frombuffer(
            json.dumps(
                {"version": SOLVE_WIRE_VERSION, "available": True}
            ).encode(),
            dtype=np.uint8,
        ),
        "ok": np.array([ok for ok, _, _ in frontier], dtype=bool),
        "n_new": np.array([n for _, n, _ in frontier], dtype=np.int64),
        "price_lb": np.array([p for _, _, p in frontier], dtype=np.float64),
    }
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def decode_frontier_response(data: bytes):
    z = _load_npz(data)
    header = json.loads(bytes(z[_HEADER_KEY]).decode())
    if header.get("version") != SOLVE_WIRE_VERSION:
        raise ValueError(
            f"unsupported solve wire version {header.get('version')}"
        )
    if not header["available"]:
        return None
    return [
        (bool(ok), int(n), float(p))
        for ok, n, p in zip(z["ok"], z["n_new"], z["price_lb"])
    ]
