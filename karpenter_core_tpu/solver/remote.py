"""RemoteSolver: the control-plane client of the solverd sidecar.

``RemoteScheduler`` presents the exact surface the provisioner consumes
(``solve(pods) -> Results``, the Scheduler/DeviceScheduler contract) while
the device work happens in another process (solver/service.py). Fault
tolerance is the point of the seam:

* per-request deadline (the HTTP timeout covers connect AND read, so a
  hung sidecar surfaces as ``socket.timeout`` within the budget);
* bounded retry with exponential backoff;
* a circuit breaker that trips after consecutive failures and half-opens
  after a cooldown, so a dead sidecar costs one fast-failed call per solve
  instead of retries×timeout (exported per tenant on the
  ``solver_circuit_breaker_state`` gauge, so a fleet dashboard sees WHICH
  operators are degraded);
* overload cooperation — the fleet gateway's 429 sheds carry a
  ``Retry-After`` estimate, which replaces the fixed exponential backoff
  for the next attempt; a Retry-After past the solve budget degrades
  immediately, and a shed never charges the breaker (the sidecar answered
  — it is regulating, not dead);
* graceful degradation — any RPC failure falls back to the host greedy
  Scheduler over the SAME inputs, so the cluster degrades to greedy parity
  instead of stalling provisioning (the in-solver twin of the device
  solver's own ``_host_fallback_add`` repair path).

Every request ships the client's tenant id (``X-Solver-Tenant`` + the wire
field) and its remaining deadline (``X-Solver-Deadline``), which is what
lets the gateway shed hopeless work instead of timing it out.

``FaultInjector`` scripts deterministic timeout/error/slow schedules into
the client (the cloudprovider/fake.py error-injection pattern) so every
degradation path is testable without real process failures.
"""
from __future__ import annotations

import hashlib
import http.client
import socket
import threading
import time
from typing import Dict, List, Optional

from karpenter_core_tpu.solver import codec

STATE_CLOSED = 0
STATE_HALF_OPEN = 1
STATE_OPEN = 2

_STATE_NAMES = {0: "closed", 1: "half-open", 2: "open"}

# causes where the sidecar ANSWERED — alive and regulating/restarting/
# refusing — so the breaker is never charged and retries are pointless
# (segment_miss is the delta wire's typed miss: the sidecar is alive and
# asking for bytes, the caller re-uploads — PR 5's shed contract, ISSUE 14)
_ANSWERED_CAUSES = ("shed", "drain", "poisoned", "segment_miss")


class RemoteSolverError(Exception):
    """An RPC abandoned after retries (or short-circuited)."""

    def __init__(
        self, cause: str, message: str = "",
        retry_after: Optional[float] = None,
    ):
        super().__init__(message or cause)
        # timeout | error | circuit_open | injected | shed | drain |
        # poisoned | segment_miss | corrupt (a result wire whose FIELDS
        # decoded but whose content is malformed — raised by
        # RemoteScheduler._materialize)
        self.cause = cause
        # server-estimated seconds until a retry would be admitted (429
        # sheds only); honored by call()'s backoff in place of the fixed
        # exponential schedule
        self.retry_after = retry_after
        # segment_miss payload: the digests the sidecar's store cannot
        # produce, and the answering daemon's instance id (what the
        # client's sent-cache keys on)
        self.need: List[str] = []
        self.instance: str = ""


class FaultInjector:
    """Scripted per-call faults, consumed in order; exhausted -> healthy.

    Entries: ``"ok"``, ``"error"`` (injected exception before transport),
    ``"timeout"`` (simulated deadline miss), ``"hang"`` (sleeps the client's
    full timeout, then times out — the slow-sidecar shape), ``"slow:<s>"``
    (adds latency, call still succeeds)."""

    def __init__(self, schedule: Optional[List[str]] = None):
        self.schedule = list(schedule or [])
        self.calls = 0

    def next_fault(self) -> str:
        self.calls += 1
        if self.schedule:
            return self.schedule.pop(0)
        return "ok"


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing."""

    def __init__(
        self,
        failure_threshold: int = 3,
        cooldown: float = 15.0,
        time_fn=time.monotonic,
        on_state_change=None,
        tenant: str = "default",
        member: str = "",
    ):
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        self.time_fn = time_fn
        self.on_state_change = on_state_change
        self.tenant = tenant
        # fleet-member identity ("" outside fleet mode): per-member
        # breakers are what let the router keep serving from healthy
        # members while ONE member is dark
        self.member = member
        self.state = STATE_CLOSED
        self.failures = 0
        self.opened_at = 0.0
        self._export()

    def _export(self) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        # tenant-labeled: each operator in the fleet owns its own breaker
        # series, so "tenant-b is on greedy" is one dashboard cell; in
        # fleet mode the member index joins the labels so "member 2 of
        # tenant-b's fleet is dark" is one cell too
        labels = {"tenant": self.tenant}
        if self.member:
            labels["member"] = self.member
        m.SOLVER_CIRCUIT_STATE.set(float(self.state), labels)

    def _transition(self, state: int) -> None:
        if state == self.state:
            return
        self.state = state
        self._export()
        if self.on_state_change is not None:
            self.on_state_change(_STATE_NAMES[state])

    def allow(self) -> bool:
        """May a call proceed right now? Open trips to half-open (one probe
        allowed) once the cooldown has elapsed."""
        if self.state == STATE_OPEN:
            if self.time_fn() - self.opened_at >= self.cooldown:
                self._transition(STATE_HALF_OPEN)
                return True
            return False
        return True

    def probeable(self) -> bool:
        """Read-only allow(): would a call be admitted now? The fleet
        router ranks members with this — allow() itself transitions
        open -> half-open, and ranking must not consume the probe slot."""
        return (
            self.state != STATE_OPEN
            or self.time_fn() - self.opened_at >= self.cooldown
        )

    def record_success(self) -> None:
        self.failures = 0
        self._transition(STATE_CLOSED)

    def record_failure(self) -> None:
        self.failures += 1
        if (
            self.state == STATE_HALF_OPEN
            or self.failures >= self.failure_threshold
        ):
            self.opened_at = self.time_fn()
            self._transition(STATE_OPEN)


class SolverClient:
    """Shared transport + fault-tolerance state for one sidecar address.

    One instance lives on the provisioner for the operator's lifetime (the
    breaker must remember failures ACROSS solves); RemoteScheduler instances
    are per-solve and borrow it."""

    def __init__(
        self,
        addr: str,
        timeout: float = 30.0,
        max_retries: int = 2,
        backoff: float = 0.1,
        breaker: Optional[CircuitBreaker] = None,
        fault_injector: Optional[FaultInjector] = None,
        sleep=time.sleep,
        on_state_change=None,
        tenant: str = "default",
        quarantine=None,
        wire_mode: str = "delta",
        member: str = "",
    ):
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.timeout = timeout
        self.max_retries = max_retries
        self.backoff = backoff
        self.tenant = tenant
        # delta = manifest-of-digests solve requests with miss repair and
        # full-wire fallback (ISSUE 14); full = every request ships the
        # whole problem (the v4-and-earlier behavior, and the escape
        # hatch when the far side predates the segment store)
        if wire_mode not in ("delta", "full"):
            raise ValueError(f"unknown wire mode {wire_mode!r}")
        self.wire_mode = wire_mode
        self.member = member
        self.breaker = breaker or CircuitBreaker(
            on_state_change=on_state_change, tenant=tenant, member=member
        )
        if on_state_change is not None and breaker is not None:
            breaker.on_state_change = on_state_change
        self.fault_injector = fault_injector
        self.sleep = sleep
        # delta-wire sent-cache: which segment digests the CURRENT far
        # instance has confirmed (solver/segments.SentCache) — rebound
        # whenever the X-Solverd-Instance response header changes, so a
        # respawned sidecar costs one re-upload round, not a stale elision
        from karpenter_core_tpu.solver.segments import SentCache

        self.segcache = SentCache()
        self._seen_instance = ""
        # incsolve predecessor reference (ISSUE 16): the fingerprint of
        # this client's last verified solve, sent as prev_fingerprint by
        # an incremental-opted RemoteScheduler. Lives here (not on the
        # per-solve facade) for the same reason the quarantine does; a
        # respawned sidecar's empty ledger just misses it — amnesia is a
        # full solve, never a wrong bind.
        self.prev_fingerprint = ""
        # client-side poison quarantine, keyed on the request-body digest:
        # lives HERE (not on the per-solve RemoteScheduler) because the
        # strike streak must survive across solves, like the breaker. A
        # problem that times out, errors, corrupts, or fails verification
        # N times inside the TTL routes straight to greedy without an RPC.
        if quarantine is None:
            from karpenter_core_tpu.solver.fleet import PoisonQuarantine

            quarantine = PoisonQuarantine(site="client")
        self.quarantine = quarantine

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def set_addr(self, addr: str) -> None:
        """Follow a respawned sidecar to its new port (supervisor restarts
        with port 0 pick a fresh one)."""
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)

    # -- transport ---------------------------------------------------------

    def _apply_fault(self) -> None:
        if self.fault_injector is None:
            return
        fault = self.fault_injector.next_fault()
        if fault == "ok":
            return
        if fault == "error":
            raise RemoteSolverError("injected", "injected error")
        if fault == "timeout":
            raise socket.timeout("injected timeout")
        if fault == "hang":
            # a hung sidecar holds the socket until the client deadline
            self.sleep(self.timeout)
            raise socket.timeout("injected hang past deadline")
        if fault.startswith("slow:"):
            self.sleep(float(fault.split(":", 1)[1]))
            return
        raise ValueError(f"unknown fault {fault!r}")

    def _once(self, path: str, body: bytes, headers: dict = None):
        self._apply_fault()
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout
        )
        try:
            conn.request(
                "POST", path, body,
                headers={
                    "Content-Type": "application/octet-stream",
                    # fleet-gateway identity: who is asking, and how much
                    # budget remains — what admission sheds against
                    "X-Solver-Tenant": self.tenant,
                    "X-Solver-Deadline": f"{self.timeout:.3f}",
                    # per-request extras (e.g. X-Solver-Mode, the solver
                    # backend selector) layer on top of the identity set
                    **(headers or {}),
                },
            )
            resp = conn.getresponse()
            data = resp.read()
            # the daemon's boot identity rides every answer; the delta
            # path keys its sent-cache on it (a changed id = a respawn =
            # the far store is empty)
            inst = resp.getheader("X-Solverd-Instance")
            if inst:
                self._seen_instance = inst
            if resp.status == 409:
                # delta-wire typed miss: the sidecar cannot assemble the
                # manifest and names exactly the digests it needs — an
                # ANSWER, not a fault (solve_delta re-uploads once)
                import json as _json

                try:
                    miss = _json.loads(data.decode())
                    need = [
                        d for d in miss.get("need", [])
                        if isinstance(d, str)
                    ]
                    instance = str(miss.get("instance", "") or "")
                except (ValueError, UnicodeDecodeError, AttributeError):
                    need, instance = [], ""
                e = RemoteSolverError(
                    "segment_miss",
                    f"sidecar {path} missing {len(need)} segment(s)",
                )
                e.need = need
                e.instance = instance
                raise e
            if resp.status == 429:
                # admission shed: the gateway answered with its estimate
                # of when a retry would be admitted
                raw = resp.getheader("Retry-After", "") or ""
                try:
                    retry_after = max(float(raw), 0.0)
                except ValueError:
                    retry_after = self.backoff
                raise RemoteSolverError(
                    "shed",
                    f"sidecar {path} shed the request: {data[:200]!r}",
                    retry_after=retry_after,
                )
            if resp.status == 503:
                # drain: the gateway is flushing its queue ahead of a
                # clean restart — degrade this solve, never the breaker
                raise RemoteSolverError(
                    "drain",
                    f"sidecar {path} draining: {data[:200]!r}",
                )
            if resp.status == 422:
                # poison-pill refusal: the gateway quarantined this
                # problem digest; quarantine it locally too
                raise RemoteSolverError(
                    "poisoned",
                    f"sidecar {path} quarantined the problem: "
                    f"{data[:200]!r}",
                )
            if resp.status != 200:
                raise RemoteSolverError(
                    "error",
                    f"sidecar {path} -> {resp.status}: {data[:200]!r}",
                )
            kernel = float(resp.getheader("X-Solver-Seconds", "0") or 0.0)
            return data, kernel
        finally:
            conn.close()

    def call(self, path: str, body: bytes, headers: dict = None,
             routing_key: str = None):
        """(response bytes, sidecar-reported kernel seconds), or raises
        RemoteSolverError after the retry budget / on an open circuit.
        ``routing_key`` is accepted (and ignored) so FleetRouter and the
        single client duck-type one call surface."""
        from karpenter_core_tpu.metrics import wiring as m

        if not self.breaker.allow():
            m.SOLVER_RPC_FAILURES.inc({"cause": "circuit_open"})
            raise RemoteSolverError("circuit_open", "circuit breaker open")
        cause, detail = "error", ""
        retry_after: Optional[float] = None
        need: List[str] = []
        instance = ""
        for attempt in range(self.max_retries + 1):
            if attempt:
                m.SOLVER_RPC_RETRIES.inc()
                # a server-sent Retry-After replaces the fixed exponential
                # schedule — the gateway knows its own drain rate
                self.sleep(
                    retry_after
                    if retry_after is not None
                    else self.backoff * (2 ** (attempt - 1))
                )
            retry_after = None
            try:
                data, kernel = self._once(path, body, headers)
            except RemoteSolverError as e:
                cause, detail, retry_after = e.cause, str(e), e.retry_after
                need, instance = e.need, e.instance
                if e.cause in ("drain", "poisoned", "segment_miss"):
                    # the sidecar ANSWERED with a definitive refusal:
                    # draining (it is about to restart), a quarantined
                    # poison digest, or a segment miss (retrying the SAME
                    # body cannot succeed — the repair is a different
                    # body, solve_delta's job) — retrying is pointless
                    # and the breaker stays untouched (a live answer is
                    # not a dead sidecar)
                    self.breaker.record_success()
                    break
                if e.cause == "shed":
                    # the sidecar ANSWERED — alive and regulating: reset
                    # the breaker's failure streak, and if waiting out the
                    # Retry-After would blow this solve's budget anyway,
                    # stop burning attempts and degrade to greedy now
                    self.breaker.record_success()
                    if retry_after is not None and retry_after >= self.timeout:
                        break
                    continue
                if self.breaker.state == STATE_HALF_OPEN:
                    break  # one probe only — don't burn retries while open
                continue
            except socket.timeout as e:
                cause, detail = "timeout", str(e)
                if self.breaker.state == STATE_HALF_OPEN:
                    break
                continue
            except OSError as e:
                cause, detail = "error", str(e)
                if self.breaker.state == STATE_HALF_OPEN:
                    break
                continue
            self.breaker.record_success()
            return data, kernel
        if cause not in _ANSWERED_CAUSES:
            # a shed/drain/poison refusal is an ANSWER, not a fault — it
            # must never push the breaker toward open (that would turn a
            # load spike or a clean restart into a blanket greedy
            # degradation past its end)
            self.breaker.record_failure()
        m.SOLVER_RPC_FAILURES.inc({"cause": cause})
        err = RemoteSolverError(cause, detail, retry_after=retry_after)
        err.need, err.instance = need, instance
        raise err

    # -- delta wire (segmentstore, ISSUE 14) -------------------------------

    def solve_delta(self, plan, headers: dict = None):
        """One delta-wire solve: ship a manifest eliding every segment
        the sent-cache says the far instance holds; on the typed miss,
        re-upload exactly the named digests and retry ONCE. Raises
        RemoteSolverError("segment_miss") only when the repair round
        ALSO missed — the caller falls back to the full wire (degraded
        bytes, never a wrong solve and never a greedy fallback: the
        sidecar is alive and answering, so the breaker stays untouched).

        ``plan`` is solver/segments.split_solve_header's SegmentPlan; a
        fleet-member restart surfaces here as exactly one miss round —
        the new instance id on the answer rebinds the sent-cache."""
        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.solver import codec

        include = [
            dg for dg in plan.segments if not self.segcache.known(dg)
        ]
        body = codec.encode_manifest_request(
            plan, include, base=self.segcache.base()
        )
        m.SOLVER_SEGMENT_WIRE_BYTES.inc(
            {"kind": "segment" if include else "manifest"}, by=len(body)
        )
        try:
            data, kernel = self.call("/solve", body, headers)
        except RemoteSolverError as e:
            if e.cause != "segment_miss":
                raise
            # miss: the far store lost segments and/or the base listing
            # (respawn, TTL, LRU, drift) — the answer names them; drop
            # them from the ledger, rebind to the answering instance (a
            # NEW id clears everything including the base), and repair
            # with one upload round
            self.segcache.forget(e.need)
            if e.instance:
                self.segcache.rebind(e.instance)
            repair = {dg for dg in e.need if dg in plan.segments}
            if any(dg not in plan.segments for dg in e.need):
                # the base listing itself (or something we never held)
                # is what's missing: resend the FULL listing
                self.segcache.drop_base()
            if not repair and self.segcache.base() is not None:
                # the miss names nothing we hold AND the base survived —
                # a malformed answer; nothing to repair, full-wire
                # fallback (the caller's job)
                raise
            repair |= {
                dg for dg in plan.segments
                if not self.segcache.known(dg)
            }
            body = codec.encode_manifest_request(
                plan, sorted(repair), base=self.segcache.base()
            )
            m.SOLVER_SEGMENT_WIRE_BYTES.inc(
                {"kind": "segment" if repair else "manifest"},
                by=len(body),
            )
            data, kernel = self.call("/solve", body, headers)
        self.segcache.rebind(self._seen_instance)
        self.segcache.mark(plan.all_digests())
        self.segcache.set_base(plan.listing_digest, plan.listing)
        return data, kernel


class RemoteScheduler:
    """Per-solve scheduler facade over a SolverClient.

    Holds the same constructor inputs as Scheduler/DeviceScheduler so the
    greedy fallback is built from the identical world the sidecar saw."""

    def __init__(
        self,
        client: SolverClient,
        nodepools,
        instance_types: Dict[str, list],
        existing_nodes=None,
        daemonset_pods=None,
        topology=None,
        device_scheduler_opts: Optional[dict] = None,
        unavailable_offerings: "frozenset | set" = frozenset(),
        verify: bool = True,
        recorder=None,
    ):
        self.client = client
        self.nodepools = list(nodepools)
        self.instance_types = instance_types
        self.existing_nodes = list(existing_nodes or [])
        self.daemonset_pods = list(daemonset_pods or [])
        self.topology = topology
        self.max_slots = (device_scheduler_opts or {}).get("max_slots", 256)
        # the solver backend this client requests per solve (relaxsolve,
        # ISSUE 13): rides the wire (codec solver_mode field) AND the
        # X-Solver-Mode header; the greedy degradation below is the
        # anytime answer either way
        self.solver_mode = (device_scheduler_opts or {}).get(
            "solver_mode", "ffd"
        )
        # incremental re-solve opt-in (incsolve, ISSUE 16): when set, each
        # request names the fingerprint of this client's last VERIFIED
        # solve so the sidecar may replay the unchanged half of that
        # packing from its ledger. The memory lives on the CLIENT (the
        # durable object — this facade is rebuilt per solve, the SentCache
        # lesson) and is cleared on every degradation below: a fallback
        # round must never advertise a predecessor the operator did not
        # actually bind. Off by default — the wire is byte-identical to a
        # pre-incsolve client's unless the operator opts in.
        self.incremental = bool(
            (device_scheduler_opts or {}).get("incremental", False)
        )
        # the ICE-cache snapshot ships on the wire so the sidecar masks the
        # same offerings; the greedy fallback applies it locally too
        self.unavailable_offerings = frozenset(unavailable_offerings)
        # host-side result verification (solver/verify.py): the trust
        # anchor between a sidecar result and NodeClaim creation — a
        # result that fails the independent constraint re-check degrades
        # to greedy exactly like an unreachable sidecar
        self.verify = verify
        self.recorder = recorder

    # -- the solve ---------------------------------------------------------

    def solve(self, pods: List):
        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.solver import gangs as gangmod

        # one O(pods) annotation/priority scan per solve, shared by the
        # decode backstop and every degradation exit below
        gangsched = gangmod.has_gangsched(pods)
        digest = None
        quarantine = self.client.quarantine
        try:
            plan = None
            wire_mode = getattr(self.client, "wire_mode", "full")
            with m.SOLVER_RPC_PHASE_DURATION.time({"phase": "encode"}):
                header = codec._encode_solve_header(
                    self.nodepools,
                    self.instance_types,
                    self.existing_nodes,
                    self.daemonset_pods,
                    pods,
                    topology=self.topology,
                    max_slots=self.max_slots,
                    unavailable_offerings=self.unavailable_offerings,
                    tenant=self.client.tenant,
                    solver_mode=self.solver_mode,
                    prev_fingerprint=(
                        getattr(self.client, "prev_fingerprint", "")
                        if self.incremental
                        else ""
                    ),
                )
                if wire_mode == "delta":
                    # delta wire (ISSUE 14): split into content-addressed
                    # segments; the quarantine key is the manifest CORE
                    # (digests + inline + pod layout), stable whether or
                    # not uploads ride along — the same key the gateway
                    # computes via codec.request_digest
                    from karpenter_core_tpu.solver import segments as segmod

                    plan = segmod.split_solve_header(header)
                    digest = plan.core_digest
                else:
                    body = codec._json_payload(header)
                    digest = hashlib.sha256(body).hexdigest()
            # poison check AFTER encode (the digest IS the canonical
            # content) but BEFORE any transport: a quarantined problem
            # costs zero RPCs, device grants, or sidecar respawns
            if quarantine is not None and quarantine.quarantined(digest):
                m.SOLVER_QUARANTINE_ROUTED.inc({"site": "client"})
                m.SOLVER_RPC_FALLBACKS.inc({"endpoint": "solve"})
                return self._fallback_solve(pods, gangsched)
            t0 = time.perf_counter()
            rpc_headers = {"X-Solver-Mode": self.solver_mode}
            if plan is not None:
                try:
                    data, kernel = self.client.solve_delta(
                        plan, rpc_headers
                    )
                except RemoteSolverError as e:
                    if e.cause != "segment_miss":
                        raise
                    # the manifest could not be resolved even after the
                    # re-upload round: ship the WHOLE problem — degraded
                    # bytes, never a wrong solve and never greedy (the
                    # sidecar is alive; full-wire v5 is first-class)
                    body = codec._json_payload(header)
                    m.SOLVER_SEGMENT_WIRE_BYTES.inc(
                        {"kind": "full"}, by=len(body)
                    )
                    data, kernel = self.client.call(
                        "/solve", body, rpc_headers,
                        routing_key=plan.catalog_digest,
                    )
            else:
                m.SOLVER_SEGMENT_WIRE_BYTES.inc(
                    {"kind": "full"}, by=len(body)
                )
                data, kernel = self.client.call(
                    "/solve", body, rpc_headers
                )
            total = time.perf_counter() - t0
            m.SOLVER_RPC_PHASE_DURATION.observe(kernel, {"phase": "kernel"})
            m.SOLVER_RPC_PHASE_DURATION.observe(
                max(total - kernel, 0.0), {"phase": "transit"}
            )
            with m.SOLVER_RPC_PHASE_DURATION.time({"phase": "decode"}):
                wire = codec.decode_solve_results(data)
                results = self._materialize(wire, pods)
            if gangsched:
                # decode-seam atomicity backstop (gangsched, ISSUE 10): a
                # wire uid that no longer resolves to a live pod can
                # materialize a gang partially — strip it BEFORE
                # verification, which treats partial gangs as violations
                gangmod.enforce_atomicity(results, pods)
                # topoaware backstops (ISSUE 20), same ordering as the
                # in-proc seam: distance stripping before eviction pruning
                # and before verification; rank re-assignment last (a pure
                # within-class permutation of the final packing)
                node_labels = {
                    n.name: getattr(n, "labels", None) or {}
                    for n in self.existing_nodes
                }
                gangmod.enforce_distance(results, pods, node_labels)
                gangmod.prune_evictions(results)
                gangmod.rank_order_pods(results, pods, node_labels)
        except RemoteSolverError as e:
            self._note_rpc_failure(e, digest)
            m.SOLVER_RPC_FALLBACKS.inc({"endpoint": "solve"})
            return self._fallback_solve(pods, gangsched)
        except (ValueError, KeyError):
            # malformed response (wire-version skew, truncated body):
            # degrade like an unreachable sidecar, but count the cause so
            # persistent skew is distinguishable from a dead process
            m.SOLVER_RPC_FAILURES.inc({"cause": "decode"})
            if quarantine is not None and digest is not None:
                quarantine.strike(digest, "decode")
            m.SOLVER_RPC_FALLBACKS.inc({"endpoint": "solve"})
            return self._fallback_solve(pods, gangsched)
        if self.verify:
            from karpenter_core_tpu.solver import verify as verifymod

            with m.SOLVER_RPC_PHASE_DURATION.time({"phase": "verify"}):
                violations = verifymod.ResultVerifier(
                    self.nodepools,
                    self.instance_types,
                    existing_nodes=self.existing_nodes,
                    daemonset_pods=self.daemonset_pods,
                    topology=self.topology,
                    unavailable_offerings=self.unavailable_offerings,
                ).verify(results, pods)
            if violations:
                verifymod.reject(violations, "sidecar", self.recorder)
                if quarantine is not None and digest is not None:
                    quarantine.strike(digest, "verify")
                m.SOLVER_RPC_FALLBACKS.inc({"endpoint": "solve"})
                return self._fallback_solve(pods, gangsched)
        if quarantine is not None and digest is not None:
            quarantine.clear(digest)
        if self.incremental:
            # remember the VERIFIED solve as the next request's
            # predecessor: the manifest path derives the fingerprint from
            # the plan it already split; the full wire re-canonicalizes
            from karpenter_core_tpu.solver import segments as segmod

            self.client.prev_fingerprint = (
                segmod.fingerprint_of_parts(plan.listing, plan.inline)
                if plan is not None
                else codec.problem_fingerprint(header)
            )
        return results

    def _note_rpc_failure(self, e: RemoteSolverError, digest) -> None:
        """Quarantine/breaker bookkeeping for one failed RPC round trip.
        Transport failures already charged the breaker inside call();
        ``corrupt`` (malformed result content, raised by _materialize)
        never crossed call()'s accounting, so it charges here — a sidecar
        producing garbage should open the breaker like a dead one."""
        from karpenter_core_tpu.metrics import wiring as m

        if e.cause == "corrupt":
            self.client.breaker.record_failure()
            m.SOLVER_RPC_FAILURES.inc({"cause": "corrupt"})
        quarantine = self.client.quarantine
        if quarantine is None or digest is None:
            return
        if e.cause == "poisoned":
            # the gateway already counted its strikes: mirror its verdict
            # locally so the NEXT solve skips the RPC entirely
            quarantine.poison(digest)
        elif e.cause in ("timeout", "error", "corrupt", "injected"):
            quarantine.strike(digest, e.cause)

    def _fallback_solve(self, pods: List, gangsched: Optional[bool] = None):
        """Greedy degradation: the host Scheduler over the same inputs —
        the cluster keeps provisioning at greedy parity, with gangsched
        problems routed through solver/gangs.degraded_solve's tiered
        wrapper. ``gangsched`` carries solve()'s already-computed
        has_gangsched verdict; None rescans."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
            Scheduler,
        )
        from karpenter_core_tpu.solver import gangs as gangmod

        # incsolve fallback contract (ISSUE 16): a greedy round's packing
        # was never remembered by any sidecar ledger, so the next request
        # must not name it as a predecessor — clearing routes that solve
        # down the full path (a stale reference would only miss anyway;
        # this keeps the reference honest and the miss accounting clean)
        if getattr(self, "incremental", False):
            self.client.prev_fingerprint = ""

        def make_scheduler():
            return Scheduler(
                self.nodepools,
                self.instance_types,
                existing_nodes=self.existing_nodes,
                daemonset_pods=self.daemonset_pods,
                topology=self.topology,
                unavailable_offerings=self.unavailable_offerings,
            )

        return gangmod.degraded_solve(
            make_scheduler, pods, self.existing_nodes, gangsched
        )

    # -- response materialization -----------------------------------------

    def _materialize(self, wire: dict, pods: List):
        """Re-bind a wire response to the caller's live objects: pods by
        uid, instance types by name, nodepools by name. The rebuilt
        InFlightNodeClaims are indistinguishable from locally-solved ones
        (provision() and the disruption price filters mutate them).

        Hardened against truncated/corrupt result wire: every field is
        type-checked before use and any malformation raises
        ``RemoteSolverError("corrupt")`` — the NORMAL degradation path
        (greedy fallback, breaker charged) — instead of a TypeError
        escaping into the reconciler. The subtle shapes matter: a
        ``pod_uids`` field that decodes as a *string* iterates as
        characters and would silently materialize an empty claim."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
            ExistingNodeSim,
            InFlightNodeClaim,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.nodeclaimtemplate import (
            NodeClaimTemplate,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
            Results,
            _daemon_compatible,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
            Topology,
        )
        from karpenter_core_tpu.scheduling import Requirements
        from karpenter_core_tpu.utils import resources as resutil

        def corrupt(detail: str):
            raise RemoteSolverError(
                "corrupt", f"malformed solve result: {detail}"
            )

        def str_list(v, field: str) -> List[str]:
            if not isinstance(v, list) or not all(
                isinstance(x, str) for x in v
            ):
                corrupt(f"{field} is not a list of strings: {v!r}")
            return v

        pods_by_uid = {p.uid: p for p in pods}
        it_by_name: Dict[str, object] = {}
        for its in self.instance_types.values():
            for it in its:
                it_by_name.setdefault(it.name, it)
        templates: Dict[str, NodeClaimTemplate] = {}
        overhead: Dict[str, dict] = {}
        for np_ in self.nodepools:
            nct = NodeClaimTemplate.from_nodepool(np_)
            templates[np_.name] = nct
            overhead[np_.name] = resutil.requests_for_pods(
                *[p for p in self.daemonset_pods if _daemon_compatible(nct, p)]
            )

        if not isinstance(wire.get("errors"), dict) or not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in wire["errors"].items()
        ):
            corrupt(f"errors is not a str->str dict: {wire.get('errors')!r}")
        if not isinstance(wire.get("claims"), list):
            corrupt(f"claims is not a list: {wire.get('claims')!r}")
        if not isinstance(wire.get("existing"), list):
            corrupt(f"existing is not a list: {wire.get('existing')!r}")

        errors = dict(wire["errors"])
        claims = []
        for c in wire["claims"]:
            if not isinstance(c, dict):
                corrupt(f"claim entry is not a dict: {c!r}")
            if not isinstance(c.get("nodepool"), str):
                corrupt(f"claim nodepool is not a string: {c!r}")
            if not isinstance(c.get("requirements"), Requirements):
                corrupt(f"claim requirements did not decode: {c!r}")
            if not isinstance(c.get("requests"), dict) or not all(
                isinstance(k, str) and isinstance(v, (int, float))
                and not isinstance(v, bool)
                for k, v in c["requests"].items()
            ):
                corrupt(f"claim requests is not a resource list: {c!r}")
            uids = str_list(c.get("pod_uids"), "claim pod_uids")
            options_names = str_list(
                c.get("instance_types"), "claim instance_types"
            )
            template = templates.get(c["nodepool"])
            if template is None:  # pool vanished between encode and decode
                for uid in uids:
                    errors[uid] = f"nodepool {c['nodepool']!r} no longer exists"
                continue
            options = [
                it_by_name[n] for n in options_names if n in it_by_name
            ]
            claim = InFlightNodeClaim(
                template, Topology(), overhead[c["nodepool"]], options
            )
            claim.requirements = c["requirements"]
            claim.requests = dict(c["requests"])
            claim.pods = [
                pods_by_uid[u] for u in uids if u in pods_by_uid
            ]
            claims.append(claim)

        node_by_name = {n.name: n for n in self.existing_nodes}
        sims = []
        for e in wire["existing"]:
            if not isinstance(e, dict) or not isinstance(
                e.get("node"), str
            ):
                corrupt(f"existing entry is malformed: {e!r}")
            uids = str_list(e.get("pod_uids"), "existing pod_uids")
            node = node_by_name.get(e["node"])
            if node is None:
                continue
            sim = ExistingNodeSim(node, Topology(), {})
            sim.pods = [
                pods_by_uid[u] for u in uids if u in pods_by_uid
            ]
            sims.append(sim)
        # eviction claims (gangsched, ISSUE 10): absent on every
        # non-preemptive wire (the byte-parity contract), a str->List[str]
        # map when present. A claim on a node that vanished locally is
        # dropped with its sim — nothing to drain, nothing placed there.
        evictions: Dict[str, List[str]] = {}
        ev_wire = wire.get("evictions", {})
        if not isinstance(ev_wire, dict):
            corrupt(f"evictions is not a dict: {ev_wire!r}")
        for node_name, uids in ev_wire.items():
            if not isinstance(node_name, str):
                corrupt(f"eviction node name is not a string: {node_name!r}")
            uids = str_list(uids, "eviction uids")
            if node_name in node_by_name:
                evictions[node_name] = list(uids)
        return Results(
            new_node_claims=claims,
            existing_nodes=sims,
            pod_errors=errors,
            evictions=evictions,
        )


class FleetRouter:
    """Client-side routing over N solverd fleet members (ISSUE 14).

    Duck-types the SolverClient surface RemoteScheduler consumes
    (``call``/``solve_delta``/``tenant``/``quarantine``/``breaker``/
    ``wire_mode``) while placing each solve on one of N member clients:

    * **digest affinity** — rendezvous (highest-random-weight) hashing of
      the manifest's CATALOG digest over member INDICES, so every solve
      of one cluster keeps landing on the member whose prepared-state
      and scheduler caches are already warm for it. Keying on the index
      (not the address) keeps the mapping stable across respawns, and
      rendezvous keeps it stable under member churn: removing one member
      remaps only that member's keys, never the survivors';
    * **spill-over** — an ANSWERED refusal (shed/drain/quarantine) from
      the affinity member re-routes once to the least-loaded healthy
      other member (the refusal never charged a breaker, so spilling is
      free); with affinity off (the bench's negative control) every
      placement is least-loaded;
    * **per-member breakers** — each member client owns its breaker
      (member-labeled on the gauge), and a member whose breaker is open
      is skipped at placement (``reason=degraded``) so one dark member
      costs routing, not greedy degradation;
    * **aggregate health** — ``health()`` polls every member's /healthz
      into one fleet view (ready = any member ready).

    The client-side poison quarantine is SHARED across members (a poison
    problem is poison everywhere), as is the tenant identity. Placement
    counters ride ``solver_fleet_routed_total{reason}``.
    """

    def __init__(
        self,
        members: List[SolverClient],
        tenant: str = "default",
        affinity: bool = True,
        quarantine=None,
    ):
        if not members:
            raise ValueError("FleetRouter needs at least one member")
        self.members = list(members)
        self.tenant = tenant
        self.affinity = affinity
        if quarantine is None:
            from karpenter_core_tpu.solver.fleet import PoisonQuarantine

            quarantine = PoisonQuarantine(site="client")
        self.quarantine = quarantine
        for c in self.members:
            c.quarantine = quarantine  # one verdict ledger, N transports
        self._lock = threading.RLock()
        # stable member identities: the rendezvous hash runs over THESE,
        # not list positions, so dynamic membership (elastic resize,
        # ISSUE 17) remaps only the departing/arriving member's keys.
        # The defaults reproduce the founding indices, keeping the hash
        # byte-identical to the static fleet's for unchanged membership.
        ids = [getattr(c, "member", "") or str(i)
               for i, c in enumerate(self.members)]
        if len(set(ids)) != len(ids):
            ids = [str(i) for i in range(len(self.members))]
        self._ids: List[str] = ids
        self._next_id = len(self.members)
        self._inflight: Dict[str, int] = {mid: 0 for mid in self._ids}
        # members currently serving a SPILL on this thread's behalf: the
        # autoscaler must never drain the tier's active safety valve
        self._spilling: Dict[str, int] = {mid: 0 for mid in self._ids}
        self._tl = threading.local()
        self.routed: Dict[str, int] = {}
        # incsolve predecessor reference (ISSUE 16): one slot suffices —
        # digest affinity pins a snapshot's lineage to one member, whose
        # ledger is the one this fingerprint can hit; a spill/degraded
        # re-route lands on a member that simply misses (full solve)
        self.prev_fingerprint = ""
        # the routing key of the last /solve placed: a membership change
        # compares its affinity winner before/after, and a remapped
        # lineage clears prev_fingerprint proactively (a guaranteed
        # ledger miss becomes a PLANNED full solve, not daemon amnesia)
        self._lineage_key: Optional[str] = None

    # -- SolverClient surface ---------------------------------------------

    @property
    def wire_mode(self) -> str:
        return self.members[0].wire_mode

    @property
    def breaker(self):
        """The breaker of the member that served THIS thread's last call
        — what RemoteScheduler charges on a corrupt result. Falls back to
        member 0 before any call has routed. Holds the serving CLIENT
        (not its index), so the charge still lands on the right breaker
        when membership shifted underneath a long solve."""
        client = getattr(self._tl, "last", None)
        return (client if client is not None else self.members[0]).breaker

    @property
    def addr(self) -> str:
        return ",".join(c.addr for c in self.members)

    def _check_index(self, i: int, site: str) -> None:
        if not 0 <= i < len(self.members):
            from karpenter_core_tpu.solver.fleet import UnknownMemberError

            raise UnknownMemberError(i, len(self.members), site)

    def set_member_addr(self, i: int, addr: str) -> None:
        """Follow a respawned fleet member to its new port (the operator
        calls this after FleetSupervisor.poll reports a restart)."""
        with self._lock:
            self._check_index(i, "set_member_addr")
            self.members[i].set_addr(addr)

    def set_addr(self, addr: str) -> None:
        """SolverClient duck-typing for the single-member router: a bare
        address re-points member 0."""
        self.set_member_addr(0, addr)

    # -- placement ---------------------------------------------------------

    def _healthy_locked(self) -> List[int]:
        with self._lock:
            up = [
                i for i, c in enumerate(self.members)
                if c.breaker.probeable()
            ]
            # every breaker open: fall through to all members — the
            # breakers themselves fast-fail, and a blanket empty set
            # would turn "all cooling down" into an unroutable error
            return up or list(range(len(self.members)))

    def _count_routed_locked(self, reason: str) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        with self._lock:
            self.routed[reason] = self.routed.get(reason, 0) + 1
        m.SOLVER_FLEET_ROUTED.inc({"reason": reason})

    def _least_loaded_locked(self, candidates: List[int]) -> int:
        with self._lock:
            return min(
                candidates,
                key=lambda i: (self._inflight[self._ids[i]], i),
            )

    def _rank_locked(self, i: int, routing_key: str) -> bytes:
        with self._lock:
            return hashlib.sha256(
                f"{self._ids[i]}|{routing_key}".encode()
            ).digest()

    def _pick(self, routing_key: Optional[str]) -> int:
        with self._lock:
            healthy = self._healthy_locked()
            if self.affinity and routing_key:
                ranked = max(
                    healthy,
                    key=lambda i: self._rank_locked(i, routing_key),
                )
                degraded = len(healthy) < len(self.members) and (
                    ranked != max(
                        range(len(self.members)),
                        key=lambda i: self._rank_locked(i, routing_key),
                    )
                )
                reason = "degraded" if degraded else "affinity"
                member = ranked
            else:
                member = self._least_loaded_locked(healthy)
                reason = "spill"
        self._count_routed_locked(reason)
        return member

    def _run(self, client: SolverClient, mid: str, fn, spill: bool = False):
        with self._lock:
            if mid in self._inflight:
                self._inflight[mid] += 1
                if spill:
                    self._spilling[mid] += 1
        self._tl.last = client
        try:
            return fn(client)
        finally:
            with self._lock:
                # the member may have been removed mid-call: its
                # counters left with it
                if mid in self._inflight:
                    self._inflight[mid] -= 1
                    if spill:
                        self._spilling[mid] = max(
                            0, self._spilling[mid] - 1
                        )

    def _routed(self, fn, routing_key: Optional[str]):
        """Place fn on the affinity pick; spill ONCE to the least-loaded
        healthy other member when the pick answers with a refusal (shed/
        drain/poisoned — it is regulating or restarting, not dead; a
        transport FAULT does not spill, the breaker machinery owns it)."""
        with self._lock:
            first = self._pick(routing_key)
            first_client, first_mid = self.members[first], self._ids[first]
        try:
            return self._run(first_client, first_mid, fn)
        except RemoteSolverError as e:
            if (
                e.cause not in ("shed", "drain", "poisoned")
                or len(self.members) < 2
            ):
                raise
            with self._lock:
                # exclude the refusing member by IDENTITY, not index —
                # membership may have shifted under the first call
                others = [
                    i for i in self._healthy_locked()
                    if self.members[i] is not first_client
                ]
                if not others:
                    raise
                spill = self._least_loaded_locked(others)
                spill_client, spill_mid = (
                    self.members[spill], self._ids[spill]
                )
            self._count_routed_locked("spill")
            return self._run(spill_client, spill_mid, fn, spill=True)

    def call(self, path: str, body: bytes, headers: dict = None,
             routing_key: str = None):
        if routing_key is None:
            # no explicit affinity key (frontier sweeps, fallback bodies
            # from callers that did not thread one): derive a stable one
            # from the body so repeat traffic still lands warm
            routing_key = hashlib.sha256(body).hexdigest()
        if path == "/solve":
            with self._lock:
                self._lineage_key = routing_key
        return self._routed(
            lambda c: c.call(path, body, headers), routing_key
        )

    def solve_delta(self, plan, headers: dict = None):
        with self._lock:
            self._lineage_key = plan.catalog_digest
        return self._routed(
            lambda c: c.solve_delta(plan, headers), plan.catalog_digest
        )

    # -- dynamic membership (elastic resize, ISSUE 17) ---------------------

    def member_loads(self) -> Dict[str, tuple]:
        """member id -> (inflight, spilling): the autoscaler's view of
        who is busy and who is answering a spill right now."""
        with self._lock:
            return {
                mid: (self._inflight[mid], self._spilling[mid])
                for mid in self._ids
            }

    def _lineage_winner_locked(self) -> Optional[str]:
        with self._lock:
            key = self._lineage_key
            if not key or not self.affinity or not self.members:
                return None
            win = max(
                range(len(self.members)),
                key=lambda i: self._rank_locked(i, key),
            )
            return self._ids[win]

    def _lineage_remap_locked(self, before: Optional[str]) -> None:
        with self._lock:
            after = self._lineage_winner_locked()
            if before is not None and before != after:
                # the lineage's routing key now ranks a different member:
                # its predecessor entry lives in the old member's ledger,
                # so the reference is a guaranteed miss. Clear it — the
                # next round is a PLANNED full solve, not an incremental
                # attempt the metrics would count as daemon amnesia.
                self.prev_fingerprint = ""

    def add_member(
        self, client: SolverClient, member_id: Optional[str] = None
    ) -> int:
        """Grow the live member set (autoscaler scale-up). Rendezvous
        hashing means the new member takes ONLY the keys it now wins —
        every survivor keeps its warm-cache keys. Returns the new
        member's index."""
        with self._lock:
            mid = member_id or getattr(client, "member", "") or ""
            while not mid or mid in self._ids:
                mid = str(self._next_id)
                self._next_id += 1
            before = self._lineage_winner_locked()
            client.quarantine = self.quarantine
            self.members.append(client)
            self._ids.append(mid)
            self._inflight[mid] = 0
            self._spilling[mid] = 0
            self._lineage_remap_locked(before)
            return len(self.members) - 1

    def remove_member(self, i: int) -> SolverClient:
        """Shrink the live member set (autoscaler scale-down): retiring
        member k remaps only k's digests — each costs one miss/re-upload
        round on its next solve, breakers untouched, fallbacks unmoved
        (the PR 13 respawn contract extended to resize). Returns the
        removed client (the caller owns its teardown)."""
        with self._lock:
            self._check_index(i, "remove_member")
            if len(self.members) < 2:
                raise ValueError("cannot remove the last fleet member")
            before = self._lineage_winner_locked()
            client = self.members.pop(i)
            mid = self._ids.pop(i)
            self._inflight.pop(mid, None)
            self._spilling.pop(mid, None)
            self._lineage_remap_locked(before)
            return client

    # -- observability -----------------------------------------------------

    def health(self, timeout: float = 2.0) -> dict:
        """Aggregate fleet /healthz: one member view per row, fleet-level
        ready when ANY member is ready (the router can place around the
        rest). An unreachable member reports ok:false, reachable:false —
        a fleet dashboard tells 'member down' from 'member overloaded'."""
        import json as _json
        from urllib.request import urlopen

        rows = []
        ready = 0
        for c in self.members:
            row = {"addr": c.addr, "ok": False, "reachable": False}
            try:
                with urlopen(
                    f"http://{c.addr}/healthz", timeout=timeout
                ) as resp:
                    row.update(_json.loads(resp.read().decode()))
                    row["reachable"] = True
            except (OSError, ValueError):
                pass
            if row.get("ready"):
                ready += 1
            rows.append(row)
        return {
            "ok": any(r.get("ok") for r in rows),
            "ready": ready > 0,
            "ready_members": ready,
            "size": len(self.members),
            "members": rows,
        }

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "routed": dict(sorted(self.routed.items())),
                "members": [
                    {
                        "addr": c.addr,
                        "member": self._ids[i],
                        "breaker": _STATE_NAMES[c.breaker.state],
                        "inflight": self._inflight[self._ids[i]],
                        "spilling": self._spilling[self._ids[i]],
                    }
                    for i, c in enumerate(self.members)
                ],
            }


def remote_frontier(
    client: SolverClient,
    nodepools,
    instance_types,
    cand_nodes,
    keep_nodes,
    daemonset_pods,
    base_pods,
    candidate_pods,
    max_slots: int = 1024,
):
    """Consolidation prefix sweep over the sidecar seam. Any RPC failure
    returns None — the caller's host binary search, i.e. greedy-parity
    degradation for disruption too."""
    from karpenter_core_tpu.metrics import wiring as m

    digest = None
    quarantine = client.quarantine
    try:
        with m.SOLVER_RPC_PHASE_DURATION.time({"phase": "encode"}):
            body = codec.encode_frontier_request(
                nodepools,
                instance_types,
                cand_nodes,
                keep_nodes,
                daemonset_pods,
                base_pods,
                candidate_pods,
                max_slots=max_slots,
                tenant=client.tenant,
            )
        # same poison contract as the solve path: a quarantined frontier
        # problem goes straight to the host binary search, zero RPCs
        digest = hashlib.sha256(body).hexdigest()
        if quarantine is not None and quarantine.quarantined(digest):
            m.SOLVER_QUARANTINE_ROUTED.inc({"site": "client"})
            m.SOLVER_RPC_FALLBACKS.inc({"endpoint": "consolidate"})
            return None
        t0 = time.perf_counter()
        data, kernel = client.call("/consolidate", body)
        total = time.perf_counter() - t0
        m.SOLVER_RPC_PHASE_DURATION.observe(kernel, {"phase": "kernel"})
        m.SOLVER_RPC_PHASE_DURATION.observe(
            max(total - kernel, 0.0), {"phase": "transit"}
        )
        with m.SOLVER_RPC_PHASE_DURATION.time({"phase": "decode"}):
            frontier = codec.decode_frontier_response(data)
    except RemoteSolverError as e:
        if quarantine is not None and digest is not None:
            if e.cause == "poisoned":
                quarantine.poison(digest)
            elif e.cause in ("timeout", "error", "injected"):
                quarantine.strike(digest, e.cause)
        m.SOLVER_RPC_FALLBACKS.inc({"endpoint": "consolidate"})
        return None
    except (ValueError, KeyError):
        m.SOLVER_RPC_FAILURES.inc({"cause": "decode"})
        if quarantine is not None and digest is not None:
            quarantine.strike(digest, "decode")
        m.SOLVER_RPC_FALLBACKS.inc({"endpoint": "consolidate"})
        return None
    # structural verification: the (ok, n_new, price_lb) triples feed
    # binary disruption decisions directly — garbage here silently
    # mis-sizes a consolidation command, so a defective frontier degrades
    # to the caller's host binary search like any RPC failure
    from karpenter_core_tpu.solver.verify import verify_frontier

    defect = verify_frontier(frontier)
    if defect is not None:
        m.SOLVER_RESULT_REJECTED.inc(
            {"reason": "structure", "path": "frontier"}
        )
        m.SOLVER_RPC_FALLBACKS.inc({"endpoint": "consolidate"})
        return None
    if quarantine is not None and digest is not None:
        # success forgives the streak, exactly like the solve path —
        # transient faults spread across a healthy week must never
        # accumulate into a quarantine
        quarantine.clear(digest)
    return frontier
