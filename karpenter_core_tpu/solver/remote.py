"""RemoteSolver: the control-plane client of the solverd sidecar.

``RemoteScheduler`` presents the exact surface the provisioner consumes
(``solve(pods) -> Results``, the Scheduler/DeviceScheduler contract) while
the device work happens in another process (solver/service.py). Fault
tolerance is the point of the seam:

* per-request deadline (the HTTP timeout covers connect AND read, so a
  hung sidecar surfaces as ``socket.timeout`` within the budget);
* bounded retry with exponential backoff;
* a circuit breaker that trips after consecutive failures and half-opens
  after a cooldown, so a dead sidecar costs one fast-failed call per solve
  instead of retries×timeout (exported per tenant on the
  ``solver_circuit_breaker_state`` gauge, so a fleet dashboard sees WHICH
  operators are degraded);
* overload cooperation — the fleet gateway's 429 sheds carry a
  ``Retry-After`` estimate, which replaces the fixed exponential backoff
  for the next attempt; a Retry-After past the solve budget degrades
  immediately, and a shed never charges the breaker (the sidecar answered
  — it is regulating, not dead);
* graceful degradation — any RPC failure falls back to the host greedy
  Scheduler over the SAME inputs, so the cluster degrades to greedy parity
  instead of stalling provisioning (the in-solver twin of the device
  solver's own ``_host_fallback_add`` repair path).

Every request ships the client's tenant id (``X-Solver-Tenant`` + the wire
field) and its remaining deadline (``X-Solver-Deadline``), which is what
lets the gateway shed hopeless work instead of timing it out.

``FaultInjector`` scripts deterministic timeout/error/slow schedules into
the client (the cloudprovider/fake.py error-injection pattern) so every
degradation path is testable without real process failures.
"""
from __future__ import annotations

import http.client
import socket
import time
from typing import Dict, List, Optional

from karpenter_core_tpu.solver import codec

STATE_CLOSED = 0
STATE_HALF_OPEN = 1
STATE_OPEN = 2

_STATE_NAMES = {0: "closed", 1: "half-open", 2: "open"}


class RemoteSolverError(Exception):
    """An RPC abandoned after retries (or short-circuited)."""

    def __init__(
        self, cause: str, message: str = "",
        retry_after: Optional[float] = None,
    ):
        super().__init__(message or cause)
        self.cause = cause  # timeout | error | circuit_open | injected | shed
        # server-estimated seconds until a retry would be admitted (429
        # sheds only); honored by call()'s backoff in place of the fixed
        # exponential schedule
        self.retry_after = retry_after


class FaultInjector:
    """Scripted per-call faults, consumed in order; exhausted -> healthy.

    Entries: ``"ok"``, ``"error"`` (injected exception before transport),
    ``"timeout"`` (simulated deadline miss), ``"hang"`` (sleeps the client's
    full timeout, then times out — the slow-sidecar shape), ``"slow:<s>"``
    (adds latency, call still succeeds)."""

    def __init__(self, schedule: Optional[List[str]] = None):
        self.schedule = list(schedule or [])
        self.calls = 0

    def next_fault(self) -> str:
        self.calls += 1
        if self.schedule:
            return self.schedule.pop(0)
        return "ok"


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing."""

    def __init__(
        self,
        failure_threshold: int = 3,
        cooldown: float = 15.0,
        time_fn=time.monotonic,
        on_state_change=None,
        tenant: str = "default",
    ):
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        self.time_fn = time_fn
        self.on_state_change = on_state_change
        self.tenant = tenant
        self.state = STATE_CLOSED
        self.failures = 0
        self.opened_at = 0.0
        self._export()

    def _export(self) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        # tenant-labeled: each operator in the fleet owns its own breaker
        # series, so "tenant-b is on greedy" is one dashboard cell
        m.SOLVER_CIRCUIT_STATE.set(
            float(self.state), {"tenant": self.tenant}
        )

    def _transition(self, state: int) -> None:
        if state == self.state:
            return
        self.state = state
        self._export()
        if self.on_state_change is not None:
            self.on_state_change(_STATE_NAMES[state])

    def allow(self) -> bool:
        """May a call proceed right now? Open trips to half-open (one probe
        allowed) once the cooldown has elapsed."""
        if self.state == STATE_OPEN:
            if self.time_fn() - self.opened_at >= self.cooldown:
                self._transition(STATE_HALF_OPEN)
                return True
            return False
        return True

    def record_success(self) -> None:
        self.failures = 0
        self._transition(STATE_CLOSED)

    def record_failure(self) -> None:
        self.failures += 1
        if (
            self.state == STATE_HALF_OPEN
            or self.failures >= self.failure_threshold
        ):
            self.opened_at = self.time_fn()
            self._transition(STATE_OPEN)


class SolverClient:
    """Shared transport + fault-tolerance state for one sidecar address.

    One instance lives on the provisioner for the operator's lifetime (the
    breaker must remember failures ACROSS solves); RemoteScheduler instances
    are per-solve and borrow it."""

    def __init__(
        self,
        addr: str,
        timeout: float = 30.0,
        max_retries: int = 2,
        backoff: float = 0.1,
        breaker: Optional[CircuitBreaker] = None,
        fault_injector: Optional[FaultInjector] = None,
        sleep=time.sleep,
        on_state_change=None,
        tenant: str = "default",
    ):
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.timeout = timeout
        self.max_retries = max_retries
        self.backoff = backoff
        self.tenant = tenant
        self.breaker = breaker or CircuitBreaker(
            on_state_change=on_state_change, tenant=tenant
        )
        if on_state_change is not None and breaker is not None:
            breaker.on_state_change = on_state_change
        self.fault_injector = fault_injector
        self.sleep = sleep

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def set_addr(self, addr: str) -> None:
        """Follow a respawned sidecar to its new port (supervisor restarts
        with port 0 pick a fresh one)."""
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)

    # -- transport ---------------------------------------------------------

    def _apply_fault(self) -> None:
        if self.fault_injector is None:
            return
        fault = self.fault_injector.next_fault()
        if fault == "ok":
            return
        if fault == "error":
            raise RemoteSolverError("injected", "injected error")
        if fault == "timeout":
            raise socket.timeout("injected timeout")
        if fault == "hang":
            # a hung sidecar holds the socket until the client deadline
            self.sleep(self.timeout)
            raise socket.timeout("injected hang past deadline")
        if fault.startswith("slow:"):
            self.sleep(float(fault.split(":", 1)[1]))
            return
        raise ValueError(f"unknown fault {fault!r}")

    def _once(self, path: str, body: bytes):
        self._apply_fault()
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout
        )
        try:
            conn.request(
                "POST", path, body,
                headers={
                    "Content-Type": "application/octet-stream",
                    # fleet-gateway identity: who is asking, and how much
                    # budget remains — what admission sheds against
                    "X-Solver-Tenant": self.tenant,
                    "X-Solver-Deadline": f"{self.timeout:.3f}",
                },
            )
            resp = conn.getresponse()
            data = resp.read()
            if resp.status == 429:
                # admission shed: the gateway answered with its estimate
                # of when a retry would be admitted
                raw = resp.getheader("Retry-After", "") or ""
                try:
                    retry_after = max(float(raw), 0.0)
                except ValueError:
                    retry_after = self.backoff
                raise RemoteSolverError(
                    "shed",
                    f"sidecar {path} shed the request: {data[:200]!r}",
                    retry_after=retry_after,
                )
            if resp.status != 200:
                raise RemoteSolverError(
                    "error",
                    f"sidecar {path} -> {resp.status}: {data[:200]!r}",
                )
            kernel = float(resp.getheader("X-Solver-Seconds", "0") or 0.0)
            return data, kernel
        finally:
            conn.close()

    def call(self, path: str, body: bytes):
        """(response bytes, sidecar-reported kernel seconds), or raises
        RemoteSolverError after the retry budget / on an open circuit."""
        from karpenter_core_tpu.metrics import wiring as m

        if not self.breaker.allow():
            m.SOLVER_RPC_FAILURES.inc({"cause": "circuit_open"})
            raise RemoteSolverError("circuit_open", "circuit breaker open")
        cause, detail = "error", ""
        retry_after: Optional[float] = None
        for attempt in range(self.max_retries + 1):
            if attempt:
                m.SOLVER_RPC_RETRIES.inc()
                # a server-sent Retry-After replaces the fixed exponential
                # schedule — the gateway knows its own drain rate
                self.sleep(
                    retry_after
                    if retry_after is not None
                    else self.backoff * (2 ** (attempt - 1))
                )
            retry_after = None
            try:
                data, kernel = self._once(path, body)
            except RemoteSolverError as e:
                cause, detail, retry_after = e.cause, str(e), e.retry_after
                if e.cause == "shed":
                    # the sidecar ANSWERED — alive and regulating: reset
                    # the breaker's failure streak, and if waiting out the
                    # Retry-After would blow this solve's budget anyway,
                    # stop burning attempts and degrade to greedy now
                    self.breaker.record_success()
                    if retry_after is not None and retry_after >= self.timeout:
                        break
                    continue
                if self.breaker.state == STATE_HALF_OPEN:
                    break  # one probe only — don't burn retries while open
                continue
            except socket.timeout as e:
                cause, detail = "timeout", str(e)
                if self.breaker.state == STATE_HALF_OPEN:
                    break
                continue
            except OSError as e:
                cause, detail = "error", str(e)
                if self.breaker.state == STATE_HALF_OPEN:
                    break
                continue
            self.breaker.record_success()
            return data, kernel
        if cause != "shed":
            # a shed is an admission decision, not a fault — it must never
            # push the breaker toward open (that would turn a load spike
            # into a blanket greedy degradation past the spike's end)
            self.breaker.record_failure()
        m.SOLVER_RPC_FAILURES.inc({"cause": cause})
        raise RemoteSolverError(cause, detail, retry_after=retry_after)


class RemoteScheduler:
    """Per-solve scheduler facade over a SolverClient.

    Holds the same constructor inputs as Scheduler/DeviceScheduler so the
    greedy fallback is built from the identical world the sidecar saw."""

    def __init__(
        self,
        client: SolverClient,
        nodepools,
        instance_types: Dict[str, list],
        existing_nodes=None,
        daemonset_pods=None,
        topology=None,
        device_scheduler_opts: Optional[dict] = None,
        unavailable_offerings: "frozenset | set" = frozenset(),
    ):
        self.client = client
        self.nodepools = list(nodepools)
        self.instance_types = instance_types
        self.existing_nodes = list(existing_nodes or [])
        self.daemonset_pods = list(daemonset_pods or [])
        self.topology = topology
        self.max_slots = (device_scheduler_opts or {}).get("max_slots", 256)
        # the ICE-cache snapshot ships on the wire so the sidecar masks the
        # same offerings; the greedy fallback applies it locally too
        self.unavailable_offerings = frozenset(unavailable_offerings)

    # -- the solve ---------------------------------------------------------

    def solve(self, pods: List):
        from karpenter_core_tpu.metrics import wiring as m

        try:
            with m.SOLVER_RPC_PHASE_DURATION.time({"phase": "encode"}):
                body = codec.encode_solve_request(
                    self.nodepools,
                    self.instance_types,
                    self.existing_nodes,
                    self.daemonset_pods,
                    pods,
                    topology=self.topology,
                    max_slots=self.max_slots,
                    unavailable_offerings=self.unavailable_offerings,
                    tenant=self.client.tenant,
                )
            t0 = time.perf_counter()
            data, kernel = self.client.call("/solve", body)
            total = time.perf_counter() - t0
            m.SOLVER_RPC_PHASE_DURATION.observe(kernel, {"phase": "kernel"})
            m.SOLVER_RPC_PHASE_DURATION.observe(
                max(total - kernel, 0.0), {"phase": "transit"}
            )
            with m.SOLVER_RPC_PHASE_DURATION.time({"phase": "decode"}):
                wire = codec.decode_solve_results(data)
                return self._materialize(wire, pods)
        except RemoteSolverError:
            m.SOLVER_RPC_FALLBACKS.inc({"endpoint": "solve"})
            return self._fallback_solve(pods)
        except (ValueError, KeyError):
            # malformed response (wire-version skew, truncated body):
            # degrade like an unreachable sidecar, but count the cause so
            # persistent skew is distinguishable from a dead process
            m.SOLVER_RPC_FAILURES.inc({"cause": "decode"})
            m.SOLVER_RPC_FALLBACKS.inc({"endpoint": "solve"})
            return self._fallback_solve(pods)

    def _fallback_solve(self, pods: List):
        """Greedy degradation: the host Scheduler over the same inputs —
        the cluster keeps provisioning at greedy parity."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
            Scheduler,
        )

        return Scheduler(
            self.nodepools,
            self.instance_types,
            existing_nodes=self.existing_nodes,
            daemonset_pods=self.daemonset_pods,
            topology=self.topology,
            unavailable_offerings=self.unavailable_offerings,
        ).solve(pods)

    # -- response materialization -----------------------------------------

    def _materialize(self, wire: dict, pods: List):
        """Re-bind a wire response to the caller's live objects: pods by
        uid, instance types by name, nodepools by name. The rebuilt
        InFlightNodeClaims are indistinguishable from locally-solved ones
        (provision() and the disruption price filters mutate them)."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (
            ExistingNodeSim,
            InFlightNodeClaim,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.nodeclaimtemplate import (
            NodeClaimTemplate,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
            Results,
            _daemon_compatible,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
            Topology,
        )
        from karpenter_core_tpu.utils import resources as resutil

        pods_by_uid = {p.uid: p for p in pods}
        it_by_name: Dict[str, object] = {}
        for its in self.instance_types.values():
            for it in its:
                it_by_name.setdefault(it.name, it)
        templates: Dict[str, NodeClaimTemplate] = {}
        overhead: Dict[str, dict] = {}
        for np_ in self.nodepools:
            nct = NodeClaimTemplate.from_nodepool(np_)
            templates[np_.name] = nct
            overhead[np_.name] = resutil.requests_for_pods(
                *[p for p in self.daemonset_pods if _daemon_compatible(nct, p)]
            )

        errors = dict(wire["errors"])
        claims = []
        for c in wire["claims"]:
            template = templates.get(c["nodepool"])
            if template is None:  # pool vanished between encode and decode
                for uid in c["pod_uids"]:
                    errors[uid] = f"nodepool {c['nodepool']!r} no longer exists"
                continue
            options = [
                it_by_name[n] for n in c["instance_types"] if n in it_by_name
            ]
            claim = InFlightNodeClaim(
                template, Topology(), overhead[c["nodepool"]], options
            )
            claim.requirements = c["requirements"]
            claim.requests = dict(c["requests"])
            claim.pods = [
                pods_by_uid[u] for u in c["pod_uids"] if u in pods_by_uid
            ]
            claims.append(claim)

        node_by_name = {n.name: n for n in self.existing_nodes}
        sims = []
        for e in wire["existing"]:
            node = node_by_name.get(e["node"])
            if node is None:
                continue
            sim = ExistingNodeSim(node, Topology(), {})
            sim.pods = [
                pods_by_uid[u] for u in e["pod_uids"] if u in pods_by_uid
            ]
            sims.append(sim)
        return Results(
            new_node_claims=claims, existing_nodes=sims, pod_errors=errors
        )


def remote_frontier(
    client: SolverClient,
    nodepools,
    instance_types,
    cand_nodes,
    keep_nodes,
    daemonset_pods,
    base_pods,
    candidate_pods,
    max_slots: int = 1024,
):
    """Consolidation prefix sweep over the sidecar seam. Any RPC failure
    returns None — the caller's host binary search, i.e. greedy-parity
    degradation for disruption too."""
    from karpenter_core_tpu.metrics import wiring as m

    try:
        with m.SOLVER_RPC_PHASE_DURATION.time({"phase": "encode"}):
            body = codec.encode_frontier_request(
                nodepools,
                instance_types,
                cand_nodes,
                keep_nodes,
                daemonset_pods,
                base_pods,
                candidate_pods,
                max_slots=max_slots,
                tenant=client.tenant,
            )
        t0 = time.perf_counter()
        data, kernel = client.call("/consolidate", body)
        total = time.perf_counter() - t0
        m.SOLVER_RPC_PHASE_DURATION.observe(kernel, {"phase": "kernel"})
        m.SOLVER_RPC_PHASE_DURATION.observe(
            max(total - kernel, 0.0), {"phase": "transit"}
        )
        with m.SOLVER_RPC_PHASE_DURATION.time({"phase": "decode"}):
            return codec.decode_frontier_response(data)
    except RemoteSolverError:
        m.SOLVER_RPC_FALLBACKS.inc({"endpoint": "consolidate"})
        return None
    except (ValueError, KeyError):
        m.SOLVER_RPC_FAILURES.inc({"cause": "decode"})
        m.SOLVER_RPC_FALLBACKS.inc({"endpoint": "consolidate"})
        return None
