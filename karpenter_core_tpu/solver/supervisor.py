"""Sidecar lifecycle supervision: spawn, monitor, restart with backoff.

The operator owns one SolverSupervisor when ``--solver-mode=sidecar`` runs
without an external ``--solver-addr``: it spawns
``python -m karpenter_core_tpu.solver.service`` as a child process, learns
the bound address from the child's ``listening on host:port`` handshake
line (the kube/httpserver.py pattern), and on every reconcile pass checks
the child is alive — a dead child respawns under exponential backoff so a
crash-looping solver cannot busy-spin the operator, and every respawn is
surfaced through the ``on_event`` hook (the operator wires it to the event
recorder as a "sidecar unavailable"/"restarted" condition) plus the
``solverd_restarts_total`` counter (``cause=crash`` charges the backoff;
``cause=drain`` — the child flushed its queue via POST /drain and exited
with DRAIN_EXIT_CODE — respawns immediately without one).

The command is injectable so tests supervise a stub child; the default
spawns the real solverd module.
"""
from __future__ import annotations

import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

# exit-code contract with solverd (solver/service.py): a drain-initiated
# exit (POST /drain flushed the queue and asked to be restarted) uses
# DRAIN_EXIT_CODE so the supervisor can tell a CLEAN restart request from
# a crash — drain exits respawn immediately and never charge crash-loop
# backoff. A watchdog trip (wedged device step) exits with
# WATCHDOG_EXIT_CODE: deliberate, but still a fault — it charges backoff
# like any crash so a poison problem cannot hot-loop the respawn.
DRAIN_EXIT_CODE = 64
WATCHDOG_EXIT_CODE = 86
# consecutive drain exits (no stable run between) tolerated before the
# supervisor stops believing them and escalates to crash-cause backoff
DRAIN_STREAK_CAP = 3
# how long a draining child waits for its in-flight device step before
# exiting anyway (solver/service.py _exit_after_idle reads this); the
# supervisor's drain() wait is sized PAST it + the exit grace, so a drain
# that succeeds at the deadline is never misreported as a failure
DRAIN_EXIT_DEADLINE_SECONDS = 30.0
# respawn-storm alarm: a member that respawns more than STORM_THRESHOLD
# times inside a sliding STORM_WINDOW is MELTING, not crash-only-churning
# — the backoff keeps the operator responsive, but readyz must say the
# tier is degraded (the digital twin and production probes both key on
# it: routine churn is a counter, a storm is an alarm)
RESPAWN_STORM_WINDOW = 600.0
RESPAWN_STORM_THRESHOLD = 5


def default_command(
    port: int,
    prewarm: bool = False,
    profile_dir: Optional[str] = None,
    queue_depth: Optional[int] = None,
    tenant_weights: str = "",
    cache_entries: Optional[int] = None,
    cache_mib: Optional[int] = None,
    max_batch: Optional[int] = None,
    batch_window_ms: Optional[float] = None,
    devices: Optional[int] = None,
    watchdog_seconds: Optional[float] = None,
    quarantine_journal: Optional[str] = None,
    solve_mode: Optional[str] = None,
    kernel: Optional[str] = None,
) -> List[str]:
    cmd = [
        sys.executable,
        "-m",
        "karpenter_core_tpu.solver.service",
        "--port",
        str(port),
    ]
    if prewarm:
        cmd.append("--prewarm")
    if profile_dir:
        # the sidecar arms jax.profiler capture lazily (POST /profile), so
        # passing the directory at spawn time costs nothing until toggled
        cmd.extend(["--profile-dir", profile_dir])
    # fleet-gateway sizing (solver/fleet.py): only non-defaults ride the
    # command line, so a respawned child always re-reads the operator's
    # configuration rather than a stale frozen argv default
    if queue_depth is not None:
        cmd.extend(["--queue-depth", str(queue_depth)])
    if tenant_weights:
        cmd.extend(["--tenant-weights", tenant_weights])
    if cache_entries is not None:
        cmd.extend(["--cache-entries", str(cache_entries)])
    if cache_mib is not None:
        cmd.extend(["--cache-mib", str(cache_mib)])
    # continuous-batching shape for the child's gateway (solverd
    # --max-batch / --batch-window-ms): rides the argv so a respawned
    # sidecar keeps the operator's coalescing policy
    if max_batch is not None:
        cmd.extend(["--max-batch", str(max_batch)])
    if batch_window_ms is not None:
        cmd.extend(["--batch-window-ms", str(batch_window_ms)])
    # the child owns the chips: the operator's --solver-devices rides the
    # spawn command so a respawned sidecar re-shards over the same slice
    if devices is not None:
        cmd.extend(["--devices", str(devices)])
    if watchdog_seconds is not None:
        cmd.extend(["--watchdog-seconds", str(watchdog_seconds)])
    # the quarantine journal is what makes poison protection survive the
    # very crash the poison causes: the respawned child reads back the
    # fingerprint that was in flight when its predecessor died
    if quarantine_journal:
        cmd.extend(["--quarantine-journal", quarantine_journal])
    # the child's default solve backend (relaxsolve, ISSUE 13): only a
    # non-default rides the argv, so a respawned sidecar keeps serving
    # the operator's --solver-backend choice to mode-less requests
    if solve_mode:
        cmd.extend(["--solver-mode", solve_mode])
    # the FFD-scan kernel implementation (ISSUE 18, --kernel=xla|pallas):
    # only a non-default rides the argv, so a respawned sidecar keeps
    # answering scans with the operator's fused-kernel choice
    if kernel:
        cmd.extend(["--kernel", kernel])
    return cmd


class SolverSupervisor:
    def __init__(
        self,
        command: Optional[List[str]] = None,
        port: int = 0,
        prewarm: bool = False,
        profile_dir: Optional[str] = None,
        queue_depth: Optional[int] = None,
        tenant_weights: str = "",
        cache_entries: Optional[int] = None,
        cache_mib: Optional[int] = None,
        max_batch: Optional[int] = None,
        batch_window_ms: Optional[float] = None,
        devices: Optional[int] = None,
        watchdog_seconds: Optional[float] = None,
        quarantine_journal: Optional[str] = None,
        solve_mode: Optional[str] = None,
        kernel: Optional[str] = None,
        backoff_initial: float = 1.0,
        backoff_max: float = 30.0,
        stable_window: float = 60.0,
        spawn_timeout: float = 60.0,
        time_fn=time.monotonic,
        on_event: Optional[Callable[[str, str], None]] = None,
        storm_window: float = RESPAWN_STORM_WINDOW,
        storm_threshold: int = RESPAWN_STORM_THRESHOLD,
        member: str = "0",
    ):
        self.command = command or default_command(
            port, prewarm, profile_dir,
            queue_depth=queue_depth,
            tenant_weights=tenant_weights,
            cache_entries=cache_entries,
            cache_mib=cache_mib,
            max_batch=max_batch,
            batch_window_ms=batch_window_ms,
            devices=devices,
            watchdog_seconds=watchdog_seconds,
            quarantine_journal=quarantine_journal,
            solve_mode=solve_mode,
            kernel=kernel,
        )
        self.backoff_initial = backoff_initial
        self.backoff_max = backoff_max
        # deadline on the handshake line: a child that wedges before
        # printing it must not hang the operator's reconcile loop
        self.spawn_timeout = spawn_timeout
        # a child must stay up this long before the backoff resets — a
        # crash-looping sidecar (spawns fine, dies seconds later) must not
        # re-earn an immediate respawn on every death
        self.stable_window = stable_window
        self.time_fn = time_fn
        self.on_event = on_event
        self.proc: Optional[subprocess.Popen] = None
        self.addr: str = ""
        self.restarts = 0
        # delay before the NEXT respawn attempt: 0 after a stable run (the
        # first restart is immediate), then backoff_initial doubling per
        # attempt while the child keeps dying, capped at backoff_max
        self._delay = 0.0
        self._next_spawn_at = 0.0
        self._down_since: Optional[float] = None
        self._last_spawn_at = 0.0
        # how the current down child exited: "crash" (charges backoff) or
        # "drain" (clean restart request — respawn immediately)
        self._exit_cause = "crash"
        # consecutive drain exits without an intervening stable run: a
        # drain-LOOPING child (a misfiring preStop hook POSTing /drain
        # every probe, or anything else exiting DRAIN_EXIT_CODE at boot —
        # it collides with sysexits EX_USAGE) must not ride the
        # no-backoff path into a respawn storm; past the streak cap it is
        # treated as a crash
        self._drain_streak = 0
        # respawn-storm alarm state: timestamps of recent respawns inside
        # the sliding window; `member` labels the gauge so a fleet
        # dashboard sees WHICH member is melting
        self.storm_window = storm_window
        self.storm_threshold = storm_threshold
        self.member = member
        self._respawn_times: List[float] = []

    # -- lifecycle ---------------------------------------------------------

    def _emit(self, reason: str, message: str) -> None:
        if self.on_event is not None:
            self.on_event(reason, message)

    def _spawn(self) -> str:
        self._last_spawn_at = self.time_fn()
        self.proc = subprocess.Popen(
            self.command,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        # handshake: the child prints "listening on host:port" once bound
        # (before any heavy warm-up, so this resolves in import time, not
        # compile time). The read runs under a deadline — a child that
        # wedges pre-handshake (stuck import, held compile-cache lock)
        # raises here instead of hanging reconcile; poll() turns that into
        # backoff + an event, and provisioning keeps degrading to greedy.
        got: List[str] = []
        reader = threading.Thread(
            target=lambda: got.append(self.proc.stdout.readline()),
            daemon=True,
        )
        reader.start()
        reader.join(self.spawn_timeout)
        line = got[0] if got else ""
        if "listening on" not in line:
            self.proc.kill()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
            raise RuntimeError(
                "sidecar failed to start ("
                + (f"got {line!r}" if got else
                   f"no handshake within {self.spawn_timeout}s")
                + f" from {self.command!r})"
            )
        self.addr = line.strip().rsplit(" ", 1)[-1]
        return self.addr

    def start(self) -> str:
        """Spawn the sidecar; returns its host:port address."""
        return self._spawn()

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def poll(self) -> bool:
        """One supervision pass: respawn a dead child once its backoff
        window has elapsed. Returns True when a restart happened (the
        caller re-points its SolverClient at the possibly-new address)."""
        if self.proc is None:
            return False
        now = self.time_fn()
        if self.alive():
            if now - self._last_spawn_at >= self.stable_window:
                self._delay = 0.0
                self._drain_streak = 0
            return False
        if self._down_since is None:
            self._down_since = now
            rc = self.proc.returncode
            if rc == DRAIN_EXIT_CODE and self._drain_streak < DRAIN_STREAK_CAP:
                # clean drain-exit: the child flushed its queue and ASKED
                # to be restarted — respawn immediately, charge nothing
                # (a drain must never look like a crash loop). The streak
                # cap is the exception: N consecutive drains with no
                # stable run in between is a drain LOOP, and it earns
                # crash-cause backoff like any other respawn storm.
                self._exit_cause = "drain"
                self._drain_streak += 1
                self._next_spawn_at = now
                self._emit(
                    "SidecarDrained",
                    f"solver sidecar drained and exited cleanly (code {rc})",
                )
            else:
                # the accumulated delay survives a "successful" spawn that
                # dies again seconds later — only stability resets it
                self._exit_cause = "crash"
                self._next_spawn_at = now + self._delay
                self._emit(
                    "SidecarUnavailable",
                    "solver sidecar exited with code "
                    + (f"{rc} (watchdog)" if rc == WATCHDOG_EXIT_CODE
                       else f"{rc}"),
                )
        if now < self._next_spawn_at:
            return False
        if self._exit_cause == "crash":
            self._delay = min(
                max(self._delay * 2, self.backoff_initial), self.backoff_max
            )
        try:
            self._spawn()
        except (OSError, RuntimeError) as e:
            if self._exit_cause == "drain":
                # the clean path failed to come back — escalate like a crash
                self._exit_cause = "crash"
                self._delay = min(
                    max(self._delay * 2, self.backoff_initial),
                    self.backoff_max,
                )
            self._next_spawn_at = now + self._delay
            self._emit("SidecarRestartFailed", str(e))
            return False
        from karpenter_core_tpu.metrics import wiring as m

        m.SOLVERD_RESTARTS.inc({"cause": self._exit_cause})
        self.restarts += 1
        self._note_respawn(self.time_fn())
        self._down_since = None
        self._emit(
            "SidecarRestarted", f"solver sidecar respawned on {self.addr}"
        )
        return True

    # -- respawn-storm alarm ----------------------------------------------

    def _note_respawn(self, now: float) -> None:
        """Record one respawn in the sliding storm window and export the
        alarm gauge; the accounting is separate from _spawn so a fake
        clock can drive it without subprocesses."""
        self._respawn_times.append(now)
        self._prune_storm(now)
        self._export_storm()

    def _prune_storm(self, now: float) -> None:
        cutoff = now - self.storm_window
        self._respawn_times = [t for t in self._respawn_times if t > cutoff]

    def respawn_storm(self) -> bool:
        """True while this member exceeded storm_threshold respawns inside
        the sliding storm_window — the tier is melting, not churning;
        readyz() degrades on it and solverd_respawn_storm exports it."""
        self._prune_storm(self.time_fn())
        self._export_storm()
        return len(self._respawn_times) > self.storm_threshold

    def _export_storm(self) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        m.SOLVERD_RESPAWN_STORM.set(
            1.0 if len(self._respawn_times) > self.storm_threshold else 0.0,
            {"member": self.member},
        )

    def drain(
        self, timeout: float = DRAIN_EXIT_DEADLINE_SECONDS + 15.0
    ) -> bool:
        """Ask the child to drain and restart cleanly: POST /drain stops
        admission, flushes queued requests with 503s, and exits with
        DRAIN_EXIT_CODE once the in-flight device step finishes. Returns
        True when the child exited within the timeout — the next poll()
        then respawns it immediately (cause=drain, no backoff charge).
        The default timeout sits PAST the child's own in-flight wait
        deadline + exit grace, so a drain that completes at the wire is
        reported as the success it is."""
        import http.client

        if not self.alive():
            return False
        host, _, port = self.addr.rpartition(":")
        try:
            conn = http.client.HTTPConnection(
                host or "127.0.0.1", int(port), timeout=min(timeout, 5.0)
            )
            try:
                conn.request("POST", "/drain", b"")
                conn.getresponse().read()
            finally:
                conn.close()
        except (OSError, ValueError):
            return False
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return False
        return True

    def stop(self) -> None:
        if self.proc is None:
            return
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)
        if self.proc.stdout is not None:
            self.proc.stdout.close()
        self.proc = None


class FleetSupervisor:
    """--solver-fleet=N: N supervised solverd children on distinct ports.

    Composes N SolverSupervisors — each member keeps the FULL single-child
    contract (handshake deadline, crash-vs-drain exit classification,
    crash-loop backoff, the drain streak cap) unchanged; this class only
    adds the fleet-shaped surface the operator and the client-side router
    (solver/remote.FleetRouter) consume: start-all, per-pass poll-all
    (returning WHICH members respawned, so the router re-points exactly
    those addresses), per-member drain, stop-all. PR 8's crash-only
    drain/respawn already made each member replaceable; the fleet tier is
    routing + cache warmth, not new lifecycle machinery.

    Every child spawns with ``port=0`` (each picks its own free port), so
    members can never collide, and member events carry their index so the
    operator's event stream says WHICH sidecar restarted."""

    def __init__(
        self,
        n: int,
        on_event: Optional[Callable[[str, str], None]] = None,
        supervisor_factory=None,
        **child_kwargs,
    ):
        if n < 1:
            raise ValueError(f"fleet size must be >= 1, got {n}")
        self.on_event = on_event
        # retained for elastic growth (TierAutoscaler scale-up): a member
        # added later spawns with exactly the same child configuration as
        # the founding set
        self._factory = supervisor_factory or SolverSupervisor
        self._child_kwargs = dict(child_kwargs)
        # monotonic member-label source: labels are never reused after a
        # retirement, so the router's rendezvous hash (keyed on the label)
        # and the member-labeled metric series never alias a successor to
        # a retired member
        self._next_member = n
        self.members: List[SolverSupervisor] = [
            self._factory(
                on_event=self._member_event(str(i)),
                member=str(i),
                **self._child_kwargs,
            )
            for i in range(n)
        ]

    def _member_event(self, member: str) -> Callable[[str, str], None]:
        def emit(reason: str, message: str) -> None:
            if self.on_event is not None:
                self.on_event(reason, f"[member {member}] {message}")

        return emit

    def _check_index(self, i: int, site: str) -> None:
        if not 0 <= i < len(self.members):
            from karpenter_core_tpu.solver.fleet import UnknownMemberError

            raise UnknownMemberError(i, len(self.members), site)

    def start(self) -> List[str]:
        """Spawn every member; returns their host:port addresses in
        member order (the router's stable member indices)."""
        return [m.start() for m in self.members]

    @property
    def addrs(self) -> List[str]:
        return [m.addr for m in self.members]

    def alive_count(self) -> int:
        return sum(1 for m in self.members if m.alive())

    def poll(self) -> List[int]:
        """One supervision pass over every member; returns the indices
        that respawned this pass (the caller re-points its router at
        those members' possibly-new addresses). A member still inside
        its crash backoff simply stays down this pass — the router keeps
        serving from the rest."""
        return [i for i, m in enumerate(self.members) if m.poll()]

    def respawn_storm(self) -> bool:
        """True while ANY member is inside a respawn storm (the operator's
        readyz degrades on it; per-member detail rides the member-labeled
        solverd_respawn_storm gauge). Short-circuits: each member's gauge
        series stays current through its own _note_respawn/respawn_storm
        calls, so the aggregate need not touch every member on every
        probe."""
        return any(m.respawn_storm() for m in self.members)

    def add_member(self, start: bool = True) -> int:
        """Grow the fleet by one member (TierAutoscaler scale-up): spawn a
        child with the retained configuration under a fresh, never-reused
        member label. Returns the new member's index; its address is at
        ``self.members[index].addr``."""
        member = str(self._next_member)
        self._next_member += 1
        sup = self._factory(
            on_event=self._member_event(member),
            member=member,
            **self._child_kwargs,
        )
        self.members.append(sup)
        if start:
            sup.start()
        return len(self.members) - 1

    def retire_member(
        self, i: int, timeout: float = DRAIN_EXIT_DEADLINE_SECONDS + 15.0
    ) -> bool:
        """Scale-down = the faultless drain path: POST /drain closes the
        member's admission, flushes its queue with 503s (answered
        refusals — no breaker charge for callers), and the child exits
        ``DRAIN_EXIT_CODE``; instead of respawning, the supervisor reaps
        it and drops it from the fleet. Returns True when the child
        exited through the drain contract (False = it had to be
        terminated, which ``stop()`` does regardless)."""
        self._check_index(i, "retire_member")
        if len(self.members) <= 1:
            raise ValueError("cannot retire the last fleet member")
        sup = self.members[i]
        clean = sup.drain(timeout=timeout)
        sup.stop()
        self.members.pop(i)
        return clean

    def drain(self, i: int, **kwargs) -> bool:
        """Drain ONE member (rolling restarts: drain, poll-respawn,
        next) — the fleet keeps serving from the others meanwhile."""
        self._check_index(i, "drain")
        return self.members[i].drain(**kwargs)

    def stop(self) -> None:
        for m in self.members:
            m.stop()
