"""segmentstore: content-addressed solve-request segments (the delta wire).

Every sidecar solve used to re-encode and ship the FULL problem across the
gRPC/DCN boundary; at production snapshot sizes the encode+wire+decode of
an essentially unchanged cluster dominates the RPC and defeats the
fingerprint-keyed caches across the hop. This module turns a solve request
into a *manifest* of content-addressed segments:

* the v5 wire splits a solve header into canonically-encoded segments —
  nodepool/template tables, the instance-type catalog, existing-node
  views (hash-bucketed by node name so 1% node churn re-ships ~1% of node
  bytes, not a positional avalanche), daemonset pods, topology context
  (domains + node-bucketed existing-pod triples), and per-class pending
  pod batches (grouped by a spec key that strips pod identity, so a
  deployment's worth of identical pods is one segment) — each segment's
  sha256 over its canonical JSON bytes IS its wire identity (PR 4 made
  every encoder canonical per logical content, which is what makes the
  digests stable across operators, restarts, and relist order);
* the client sends digests; the sidecar answers a TYPED miss
  (``need: [digests]``, HTTP 409) for anything its ``SegmentStore`` does
  not hold; the client uploads exactly those and retries once — a
  respawned sidecar costs one re-upload round, never a wrong solve and
  never a greedy fallback (solver/remote.py treats the miss as
  degradation-not-fault, mirroring the PR 5 shed/drain contract);
* ``problem_fingerprint`` is derived from the manifest's problem-half
  segment digests, so the full-wire and manifest paths key the SAME
  cached DeviceScheduler, and the prepared-state caches hit across
  restarts of either side;
* ``SegmentStore`` (daemon side) is TTL'd and LRU-bounded in entries AND
  bytes, with eviction metrics, so N tenants' snapshots cannot grow the
  sidecar without bound; ``SentCache`` (client side) remembers which
  digests a given sidecar INSTANCE has confirmed, so an unchanged catalog
  never re-uploads — and a respawned instance (fresh id on the response
  header) invalidates exactly that member's sent-set.

The manifest/inline FIELD SETS are frozen in the GL403 wire-schema lock
via solver/codec.py (``encode_manifest_request`` / ``_encode_manifest_
inline``); this module owns the splitting, digests, and stores — no new
wire field is ever minted here.
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

# segment kinds on the manifest listing; "nodes"/"topo_pods"/"pods" may
# appear many times (bucketed / per-class batches), the rest exactly once
KIND_NODEPOOLS = "nodepools"
KIND_CATALOG = "catalog"
KIND_NODES = "nodes"
KIND_DSPODS = "dspods"
KIND_TOPO_DOMAINS = "topo_domains"
KIND_TOPO_PODS = "topo_pods"
KIND_PODS = "pods"
SEGMENT_KINDS = (
    KIND_NODEPOOLS, KIND_CATALOG, KIND_NODES, KIND_DSPODS,
    KIND_TOPO_DOMAINS, KIND_TOPO_PODS, KIND_PODS,
)
# canonical listing order: rows sort by (kind rank, digest), which makes
# the listing itself content-addressed — the SAME problem always yields
# the SAME listing bytes, so a manifest can name its previous listing by
# digest and ship only the row edits (the steady-state delta wire's
# biggest win: hundreds of unchanged digests stop riding every request)
_KIND_RANK = {k: i for i, k in enumerate(SEGMENT_KINDS)}

# bucket sizing: mean entities per hash bucket. Small buckets keep the
# churn amplification low (a changed entity re-ships ~target neighbors,
# so the re-shipped fraction at churn c is ~c x target) at the cost of
# more manifest digests; the node target is the aggressive one because
# existing-node views dominate production snapshots.
NODE_BUCKET_TARGET = 4
TOPO_POD_BUCKET_TARGET = 8
_MAX_BUCKETS = 4096
# pending-pod batches: spec-key grouping keeps a deployment's replicas in
# one segment, but a diverse pod mix would shatter into per-pod batches
# whose tiny compression windows cost more than they save — spec keys
# hash-fold into at most this many batches (identical specs still always
# share one)
POD_BATCH_CAP = 32

# daemon-side store bounds (solverd --segment-cache-mib/--segment-ttl
# override). The TTL is idle-based: a segment re-referenced by any
# manifest stays resident, one no manifest names for a full TTL expires
# even if the store never fills.
DEFAULT_STORE_BYTES = 256 << 20
DEFAULT_STORE_ENTRIES = 1 << 16
DEFAULT_STORE_TTL = 3600.0

# client-side sent-cache bound (digests per sidecar instance)
DEFAULT_SENT_DIGESTS = 1 << 16

# pod metadata fields stripped when grouping pending pods into per-class
# batches: identity only — everything that makes two replicas of one
# deployment DIFFERENT pods, nothing that changes where they can schedule
_POD_IDENTITY_FIELDS = (
    "name", "uid", "resource_version", "creation_timestamp", "generation",
)


class SegmentMissError(Exception):
    """The daemon cannot assemble a manifest: ``need`` names the segment
    digests its store does not hold. The HTTP layer answers 409 with the
    list (+ the daemon's instance id) and the client uploads exactly
    those — a typed miss, never a wrong solve."""

    def __init__(self, need: List[str]):
        super().__init__(f"missing {len(need)} segment(s)")
        self.need = list(need)


def canonical_bytes(value) -> bytes:
    """The segment encoding: compact JSON with recursively sorted keys —
    one byte string per logical value regardless of host dict order (list
    order IS content; every list in the solve header is already canonical
    per PR 4's encoder sweep)."""
    return json.dumps(
        value, sort_keys=True, separators=(",", ":")
    ).encode()


# segment digests are sha256 truncated to 24 hex chars (96 bits): digest
# rows ride EVERY manifest (and hex is incompressible), so length is wire
# cost — 96 bits keeps accidental collisions out of reach (~2^48 birthday
# over a store that holds ~2^16 entries) and an adversarial collision
# still cannot corrupt a solve silently: the upload site verifies content
# against the digest, and the CLIENT-side ResultVerifier independently
# re-checks every packing, so the worst case is a verification reject +
# greedy degradation, never a wrong bind. Full-body quarantine digests
# (codec.request_digest) stay full sha256.
DIGEST_HEX = 24


def digest_of(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:DIGEST_HEX]


def _bucket_count(n: int, target: int) -> int:
    """Power-of-two bucket count for ~``target`` entities per bucket.
    Pow2 so the count (and therefore every unchanged entity's bucket
    membership) is stable until the population roughly doubles."""
    if n <= target:
        return 1
    return min(_MAX_BUCKETS, 1 << ((n + target - 1) // target - 1).bit_length())


def _bucket_of(name: str, n_buckets: int) -> int:
    if n_buckets <= 1:
        return 0
    h = hashlib.sha256(name.encode()).digest()
    return int.from_bytes(h[:4], "big") % n_buckets


def _enc_pod_sort_key(enc) -> Tuple[str, str, str]:
    """codec._pod_sort_key over an already-serialized pod dict."""
    md = enc.get("metadata") if isinstance(enc, dict) else None
    md = md if isinstance(md, dict) else {}
    return (
        md.get("namespace") or "", md.get("name") or "", md.get("uid") or ""
    )


def _topo_sort_key(triple) -> tuple:
    """codec._encode_topology's canonical (node, pod) order, recomputed
    from the encoded triple so bucket reassembly reproduces the exact
    full-wire list."""
    return (triple[2], _enc_pod_sort_key(triple[0]))


def _pod_spec_key(enc: dict) -> str:
    """Per-class grouping key for pending pods: the serialized pod with
    identity metadata stripped. Replicas of one deployment share a key,
    so their batch segment is stable while only membership churns."""
    if isinstance(enc, dict) and isinstance(enc.get("metadata"), dict):
        md = {
            k: v
            for k, v in enc["metadata"].items()
            if k not in _POD_IDENTITY_FIELDS
        }
        enc = {**enc, "metadata": md}
    return digest_of(canonical_bytes(enc))[:16]


class SegmentPlan:
    """One solve header split into content-addressed segments.

    ``listing`` is the manifest's ``[kind, digest]`` rows in canonical
    order; ``segments`` maps digest -> canonical bytes; ``inline`` is the
    non-addressed remainder (codec._encode_manifest_inline — the pod-half
    scalars plus presence flags); ``pod_batch``/``pod_member`` rebuild
    the caller's exact pending-pod order from the per-class batches.
    ``fingerprint`` is the digest-derived problem fingerprint (equal to
    codec.problem_fingerprint of the same header by construction) and
    ``core_digest`` the quarantine/poison key — stable whether or not
    segment uploads ride along with the manifest."""

    __slots__ = (
        "listing", "segments", "inline", "pod_batch", "pod_member",
        "catalog_digest", "fingerprint", "core_digest", "listing_digest",
    )

    def __init__(self, listing, segments, inline, pod_batch, pod_member,
                 catalog_digest):
        self.listing = listing
        self.segments = segments
        self.inline = inline
        self.pod_batch = pod_batch
        self.pod_member = pod_member
        self.catalog_digest = catalog_digest
        self.fingerprint = fingerprint_of_parts(listing, inline)
        self.core_digest = core_digest_of(
            listing, inline, pod_batch, pod_member
        )
        # the listing's own content address: what a follow-up manifest
        # names as its base to ship row EDITS instead of every digest
        self.listing_digest = listing_digest_of(listing)

    def all_digests(self) -> List[str]:
        return list(self.segments)

    def raw_bytes(self, digests=None) -> int:
        ds = self.segments if digests is None else digests
        return sum(len(self.segments[d]) for d in ds if d in self.segments)


def _problem_listing(header: dict, keep: Optional[Dict[str, bytes]]):
    """The PROBLEM-half listing (everything the fingerprint hashes).
    ``keep`` collects digest -> bytes when the caller needs the segment
    data (the client split); None computes digests only (the full-wire
    fingerprint path)."""
    listing: List[List[str]] = []

    def add(kind: str, value) -> str:
        data = canonical_bytes(value)
        dg = digest_of(data)
        if keep is not None:
            keep[dg] = data
        listing.append([kind, dg])
        return dg

    add(KIND_NODEPOOLS, header["nodepools"])
    catalog_digest = add(
        KIND_CATALOG,
        {"it_table": header["it_table"], "it_pools": header["it_pools"]},
    )
    nodes = header["existing_nodes"]
    nb = _bucket_count(len(nodes), NODE_BUCKET_TARGET)
    node_buckets: List[list] = [[] for _ in range(nb)]
    for nd in nodes:
        node_buckets[_bucket_of(nd["name"], nb)].append(nd)
    for bucket in node_buckets:
        if bucket:  # empty buckets carry nothing and would only dup digests
            add(KIND_NODES, bucket)
    add(KIND_DSPODS, header["daemonset_pods"])
    topo = header.get("topology")
    if topo is not None:
        add(KIND_TOPO_DOMAINS, topo["domains"])
        tpods = topo["existing_pods"]
        tb = _bucket_count(len(tpods), TOPO_POD_BUCKET_TARGET)
        topo_buckets: List[list] = [[] for _ in range(tb)]
        for triple in tpods:
            topo_buckets[_bucket_of(str(triple[2]), tb)].append(triple)
        for bucket in topo_buckets:
            if bucket:
                add(KIND_TOPO_PODS, bucket)
    return listing, catalog_digest, add


def sort_listing(rows) -> List[List[str]]:
    """The canonical listing order: (kind rank, digest). Both sides sort
    with THIS, so a listing reconstructed from base+edits is row-for-row
    the client's — which the pod layout arrays (indices into the pods
    rows) depend on."""
    return sorted(
        ([str(k), str(d)] for k, d in rows),
        key=lambda r: (_KIND_RANK.get(r[0], len(_KIND_RANK)), r[1]),
    )


def listing_bytes(rows) -> bytes:
    return canonical_bytes(sort_listing(rows))


def listing_digest_of(rows) -> str:
    return digest_of(listing_bytes(rows))


def split_solve_header(header: dict) -> SegmentPlan:
    """Split a full solve header (codec._encode_solve_header's dict) into
    a SegmentPlan. The inverse is ``assemble_solve_header``; the pair is
    exact — assembly reproduces the original header value-for-value, so
    manifest-path solves are wire-identical to full-path ones. The
    listing comes back canonically sorted (sort_listing), making it
    content-addressed for the base+edits manifest form."""
    from karpenter_core_tpu.solver import codec

    segments: Dict[str, bytes] = {}
    rows, catalog_digest, add = _problem_listing(header, segments)

    # pending pods: per-class batches (spec key strips identity, keys
    # hash-fold to at most POD_BATCH_CAP batches), members canonically
    # ordered within each batch; the layout arrays rebuild the caller's
    # exact queue order on the far side
    pods_enc = header["pods"]
    # ~8 pods per batch, capped: small pending sets stay in a few
    # well-compressing segments instead of shattering per-pod
    nb = min(POD_BATCH_CAP, max(len(pods_enc) // 8, 1))
    by_bucket: Dict[int, List[int]] = {}
    for i, enc in enumerate(pods_enc):
        by_bucket.setdefault(
            _bucket_of(_pod_spec_key(enc), nb), []
        ).append(i)
    pod_batch = [0] * len(pods_enc)
    pod_member = [0] * len(pods_enc)
    placed: Dict[str, List[tuple]] = {}  # batch digest -> [(i, m), ...]
    for bucket in by_bucket.values():
        order = sorted(
            bucket, key=lambda i: _enc_pod_sort_key(pods_enc[i])
        )
        dg = add(KIND_PODS, [pods_enc[i] for i in order])
        placed[dg] = [(i, m) for m, i in enumerate(order)]

    # canonical row order; pods batch indices follow the SORTED order so
    # the daemon's reconstruction (which only ever sees sorted rows)
    # indexes identically
    listing = sort_listing(rows)
    batch_index = {
        dg: b
        for b, dg in enumerate(
            dg for kind, dg in listing if kind == KIND_PODS
        )
    }
    for dg, members in placed.items():
        for i, m in members:
            pod_batch[i] = batch_index[dg]
            pod_member[i] = m

    return SegmentPlan(
        listing, segments, codec._encode_manifest_inline(header),
        pod_batch, pod_member, catalog_digest,
    )


def fingerprint_of_header(header: dict) -> str:
    """codec.problem_fingerprint's v5 implementation: the digest-derived
    fingerprint computed from a FULL header (the manifest path computes
    the identical value from its listing without reassembling)."""
    from karpenter_core_tpu.solver import codec

    listing, _catalog, _add = _problem_listing(header, None)
    return fingerprint_of_parts(
        listing, codec._encode_manifest_inline(header)
    )


def fingerprint_of_parts(listing, inline) -> str:
    """The problem fingerprint from manifest parts alone: the sorted
    problem-half (kind, digest) pairs plus the problem-half inline
    scalars. Pod batches, the pod layout, tenant, solver_mode, and the
    pod-derived topology exclusions are all pod-half — excluded exactly
    as the v4 JSON-hash fingerprint excluded them, so the scheduler cache
    keeps its churn profile while becoming derivable from digests."""
    from karpenter_core_tpu.solver import codec

    probe = {
        "version": codec.SOLVE_WIRE_VERSION,
        "segments": sorted(
            [str(k), str(d)] for k, d in listing if k != KIND_PODS
        ),
        "max_slots": inline.get("max_slots"),
        "unavailable_offerings": inline.get("unavailable_offerings"),
        "has_topology": bool(inline.get("has_topology")),
    }
    return digest_of(canonical_bytes(probe))


def core_digest_of(listing, inline, pod_batch, pod_member) -> str:
    """The quarantine/poison key of a manifest request: digests + inline
    + pod layout — the request's CONTENT, independent of which segment
    uploads happen to ride along, so the strike ledger sees one key per
    logical problem across the miss/re-upload handshake."""
    from karpenter_core_tpu.solver import codec

    probe = {
        "version": codec.SOLVE_WIRE_VERSION,
        "segments": [[str(k), str(d)] for k, d in listing],
        "inline": inline,
        "pod_batch": [int(x) for x in pod_batch],
        "pod_member": [int(x) for x in pod_member],
    }
    return digest_of(canonical_bytes(probe))


def check_manifest_parts(listing, inline) -> None:
    """Decode-net validation of a manifest's listing + inline shapes: a
    malformed manifest must be a ValueError (the client's decode-failure
    degradation), never a TypeError three layers into assembly."""
    if not isinstance(listing, list):
        raise ValueError(f"manifest segments is not a list: {listing!r}")
    for row in listing:
        if (
            not isinstance(row, list)
            or len(row) != 2
            or not all(isinstance(x, str) for x in row)
        ):
            raise ValueError(f"malformed manifest segment row: {row!r}")
        if row[0] not in SEGMENT_KINDS:
            raise ValueError(f"unknown segment kind on the wire: {row[0]!r}")
    if not isinstance(inline, dict):
        raise ValueError(f"manifest inline is not a dict: {inline!r}")


def assemble_solve_header(
    listing, inline, pod_batch, pod_member,
    fetch: Callable[[str], Optional[bytes]],
) -> dict:
    """Rebuild the full solve header from a manifest. ``fetch`` is the
    SegmentStore lookup; any digest it cannot produce raises
    SegmentMissError with the complete missing set (ONE round trip
    repairs everything, not one per segment). Bucketed kinds re-sort into
    the encoders' canonical orders, so the assembled header is
    value-identical to the full-wire one."""
    from karpenter_core_tpu.solver import codec

    check_manifest_parts(listing, inline)
    missing: List[str] = []
    groups: Dict[str, List] = {}
    for kind, dg in listing:
        data = fetch(dg)
        if data is None:
            missing.append(dg)
            continue
        try:
            groups.setdefault(kind, []).append(json.loads(data.decode()))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ValueError(f"malformed segment {dg[:12]}: {e}") from e
    if missing:
        raise SegmentMissError(sorted(set(missing)))

    for kind in (KIND_NODEPOOLS, KIND_CATALOG, KIND_DSPODS):
        if len(groups.get(kind, [])) != 1:
            raise ValueError(
                f"manifest needs exactly one {kind} segment, got"
                f" {len(groups.get(kind, []))}"
            )
    catalog = groups[KIND_CATALOG][0]
    if not isinstance(catalog, dict) or not {
        "it_table", "it_pools"
    } <= set(catalog):
        raise ValueError(f"malformed catalog segment: {type(catalog)}")
    nodes = [nd for bucket in groups.get(KIND_NODES, []) for nd in bucket]
    nodes.sort(key=lambda d: d.get("name") or "")

    topology = None
    if inline.get("has_topology"):
        if len(groups.get(KIND_TOPO_DOMAINS, [])) != 1:
            raise ValueError("manifest topology lost its domains segment")
        tpods = [
            t for bucket in groups.get(KIND_TOPO_PODS, []) for t in bucket
        ]
        tpods.sort(key=_topo_sort_key)
        topology = {
            "domains": groups[KIND_TOPO_DOMAINS][0],
            "existing_pods": tpods,
            "excluded": inline.get("topo_excluded") or [],
        }

    batches = groups.get(KIND_PODS, [])
    if len(pod_batch) != len(pod_member):
        raise ValueError("pod layout arrays disagree on length")
    pods = []
    for b, m in zip(pod_batch, pod_member):
        b, m = int(b), int(m)
        if not (0 <= b < len(batches)) or not (0 <= m < len(batches[b])):
            raise ValueError(f"pod layout entry ({b},{m}) out of range")
        pods.append(batches[b][m])

    header = {
        "version": codec.SOLVE_WIRE_VERSION,
        "nodepools": groups[KIND_NODEPOOLS][0],
        "it_table": catalog["it_table"],
        "it_pools": catalog["it_pools"],
        "existing_nodes": nodes,
        "daemonset_pods": groups[KIND_DSPODS][0],
        "pods": pods,
        "topology": topology,
        "max_slots": inline.get("max_slots"),
        "unavailable_offerings": inline.get("unavailable_offerings"),
        "tenant": inline.get("tenant", "default"),
        "solver_mode": inline.get("solver_mode", ""),
    }
    # prior-solve reference (incsolve, ISSUE 16): pod-half inline —
    # deliberately OUTSIDE fingerprint_of_parts' probe, so a request
    # naming its predecessor fingerprints identically to one that
    # doesn't (it must, or the reference could never name a hit). Key
    # omitted when empty, mirroring _encode_solve_header — assembly must
    # stay byte-exact against the full wire either way.
    if inline.get("prev_fingerprint"):
        header["prev_fingerprint"] = inline["prev_fingerprint"]
    return header


class SegmentStore:
    """TTL'd + LRU-bounded content-addressed byte store (daemon side).

    Bounded in entries AND bytes like the scheduler cache — segment
    bodies arrive from N tenants' snapshots, so an unbounded store is an
    OOM with extra steps. The TTL is idle-based and refreshed on every
    reference (``get``), so the working set of an active fleet never
    expires mid-conversation while a tenant that left takes its snapshot
    bytes with it one TTL later. Content addressing is verified at the
    upload site (codec checks sha256(body) == claimed digest), so a
    mismatched upload can never poison another tenant's manifest.

    All shared state is mutated under ``self._lock`` (the ``_locked``
    helper discipline graftlint GL302/GL303 checks). Purely in-memory:
    no disk or journal I/O, so the GL304 grant-region audit over the
    solver tree holds by construction — store puts/gets run in the
    request's pre-grant host phase anyway."""

    def __init__(
        self,
        max_bytes: int = DEFAULT_STORE_BYTES,
        max_entries: int = DEFAULT_STORE_ENTRIES,
        ttl: float = DEFAULT_STORE_TTL,
        time_fn=time.monotonic,
    ):
        if max_entries <= 0:
            raise ValueError(
                f"max_entries must be positive, got {max_entries}"
            )
        if ttl <= 0:
            raise ValueError(f"ttl must be positive, got {ttl}")
        self.max_bytes = max_bytes
        self.max_entries = max_entries
        self.ttl = ttl
        self.time_fn = time_fn
        self._lock = threading.RLock()
        # digest -> [data, expires_at]; OrderedDict tail = most recent
        self._entries: "OrderedDict[str, list]" = OrderedDict()
        self._bytes = 0
        self.evictions: Dict[str, int] = {}

    def get(self, digest: str) -> Optional[bytes]:
        with self._lock:
            ent = self._entries.get(digest)
            if ent is None:
                return None
            now = self.time_fn()
            if now >= ent[1]:
                self._drop_locked(digest, "ttl")
                self._export_locked()
                return None
            ent[1] = now + self.ttl  # idle TTL: references keep it warm
            self._entries.move_to_end(digest)
            return ent[0]

    def put(self, digest: str, data: bytes) -> None:
        with self._lock:
            now = self.time_fn()
            old = self._entries.pop(digest, None)
            if old is not None:
                self._bytes -= len(old[0])
            self._entries[digest] = [data, now + self.ttl]
            self._bytes += len(data)
            self._sweep_expired_locked(now)
            while len(self._entries) > self.max_entries:
                self._evict_lru_locked("entries")
            # strict byte bound, scheduler-cache policy: even one
            # oversized snapshot may not pin more than the budget (the
            # solve still serves — the segment just re-uploads next time)
            while self._bytes > self.max_bytes and self._entries:
                self._evict_lru_locked("bytes")
            self._export_locked()

    def _sweep_expired_locked(self, now: float) -> None:
        with self._lock:
            for dg in [
                dg for dg, ent in self._entries.items() if now >= ent[1]
            ]:
                self._drop_locked(dg, "ttl")

    def _evict_lru_locked(self, reason: str) -> None:
        with self._lock:
            dg = next(iter(self._entries))
            self._drop_locked(dg, reason)

    def _drop_locked(self, digest: str, reason: str) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        with self._lock:
            data, _exp = self._entries.pop(digest)
            self._bytes -= len(data)
            self.evictions[reason] = self.evictions.get(reason, 0) + 1
        m.SOLVERD_SEGSTORE_EVICTIONS.inc({"reason": reason})

    def _export_locked(self) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        with self._lock:
            m.SOLVERD_SEGSTORE_ENTRIES.set(float(len(self._entries)))
            m.SOLVERD_SEGSTORE_BYTES.set(float(self._bytes))

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "evictions": dict(self.evictions),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, digest: str) -> bool:
        with self._lock:
            ent = self._entries.get(digest)
            return ent is not None and self.time_fn() < ent[1]

    def total_bytes(self) -> int:
        with self._lock:
            return self._bytes


class SentCache:
    """Client-side ledger of segments a sidecar INSTANCE has confirmed.

    Keyed per sidecar identity: every solverd boot mints an instance id
    (rode back on the ``X-Solverd-Instance`` response header and on miss
    answers), and ``rebind`` to a NEW id drops the whole sent-set — a
    respawned member starts cold, and the next manifest's optimistic
    elision is repaired by exactly one typed-miss re-upload. Bounded in
    digests (LRU) so a long-lived operator cannot leak one entry per
    historical segment forever."""

    def __init__(self, max_digests: int = DEFAULT_SENT_DIGESTS):
        if max_digests <= 0:
            raise ValueError(
                f"max_digests must be positive, got {max_digests}"
            )
        self.max_digests = max_digests
        self._lock = threading.RLock()
        self._instance: str = ""
        self._known: "OrderedDict[str, None]" = OrderedDict()
        # the last listing this instance resolved (digest + rows): the
        # base the next manifest ships row EDITS against
        self._base_digest: str = ""
        self._base_rows: List[List[str]] = []

    def instance(self) -> str:
        with self._lock:
            return self._instance

    def rebind(self, instance: str) -> bool:
        """Point the ledger at a sidecar instance; a CHANGED id clears it
        (the old process's store died with it). Returns True on a clear."""
        with self._lock:
            if instance == self._instance:
                return False
            self._instance = instance
            self._known.clear()
            self._base_digest = ""
            self._base_rows = []
            return True

    def base(self):
        """(listing digest, rows) of the last confirmed listing, or None
        before any solve / after a rebind."""
        with self._lock:
            if not self._base_digest:
                return None
            return self._base_digest, self._base_rows

    def set_base(self, digest: str, rows) -> None:
        with self._lock:
            self._base_digest = digest
            self._base_rows = [list(r) for r in rows]

    def drop_base(self) -> None:
        """The far side reported the base listing missing: stop naming it
        (the next manifest ships its full listing)."""
        with self._lock:
            self._base_digest = ""
            self._base_rows = []

    def known(self, digest: str) -> bool:
        with self._lock:
            return digest in self._known

    def mark(self, digests) -> None:
        with self._lock:
            for dg in digests:
                self._known[dg] = None
                self._known.move_to_end(dg)
            while len(self._known) > self.max_digests:
                self._known.popitem(last=False)

    def forget(self, digests) -> None:
        """Drop specific digests (a miss answer proved the far side lost
        them — e.g. TTL/LRU eviction on a live instance)."""
        with self._lock:
            for dg in digests:
                self._known.pop(dg, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._known)
