"""Closed-world vocabulary for one solve.

The reference's Requirement algebra works over unbounded string sets with
complement representation (requirement.go:33-40). On device, every solve
runs against a closed world: the union of label keys/values mentioned by any
pod requirement, NodePool/template requirement, instance type, offering, or
live node in the snapshot (the domain universe the reference provisioner
assembles at provisioner.go:251-283). Under that closed world every
requirement lowers exactly to a boolean mask over the key's value list plus
(concrete?, negative?, gt, lt) scalars — see ops/masks.py for the exactness
argument.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

import numpy as np

from karpenter_core_tpu.scheduling.requirement import (
    NEGATIVE_OPERATORS,
    Requirement,
)
from karpenter_core_tpu.scheduling.requirements import Requirements

# Sentinel integer bounds (ops compare with >=; values are label-value ints,
# well inside these).
GT_NONE = -(2**30)
LT_NONE = 2**30


class Vocab:
    """Interner for label keys and per-key value domains."""

    def __init__(self):
        self.keys: Dict[str, int] = {}
        self.key_names: List[str] = []
        self.values: List[Dict[str, int]] = []  # per key: value -> vid
        self.value_names: List[List[str]] = []

    def key_id(self, key: str) -> int:
        kid = self.keys.get(key)
        if kid is None:
            kid = len(self.key_names)
            self.keys[key] = kid
            self.key_names.append(key)
            self.values.append({})
            self.value_names.append([])
        return kid

    def value_id(self, key: str, value: str) -> int:
        kid = self.key_id(key)
        vocab = self.values[kid]
        vid = vocab.get(value)
        if vid is None:
            vid = len(self.value_names[kid])
            vocab[value] = vid
            self.value_names[kid].append(value)
        return vid

    def observe_requirements(self, reqs: Requirements) -> None:
        # canonical observation order: key/value ids are POSITIONAL, so two
        # observers walking the same logical requirements in different dict/
        # set orders would otherwise mint different id assignments — and
        # with them different FrozenVocab.fingerprint()s for the same
        # closed world (the prepared-state cache key)
        for key, req in sorted(reqs.items()):
            self.key_id(key)
            for v in sorted(req.values):
                self.value_id(key, v)

    def observe_labels(self, labels: dict) -> None:
        for k, v in sorted(labels.items()):
            self.value_id(k, v)

    @property
    def num_keys(self) -> int:
        return len(self.key_names)

    @property
    def max_values(self) -> int:
        return max((len(v) for v in self.value_names), default=1)

    def finalize(self) -> "FrozenVocab":
        K = self.num_keys
        V = max(self.max_values, 1)
        # integer value of each vocab entry (for Gt/Lt masks); NaN-free:
        # non-integer values get LT_NONE so no bound ever admits them.
        int_values = np.full((K, V), LT_NONE, dtype=np.int64)
        valid = np.zeros((K, V), dtype=bool)
        for kid, names in enumerate(self.value_names):
            for vid, name in enumerate(names):
                valid[kid, vid] = True
                try:
                    int_values[kid, vid] = int(name)
                except ValueError:
                    pass
        return FrozenVocab(
            keys=dict(self.keys),
            key_names=list(self.key_names),
            values=[dict(v) for v in self.values],
            value_names=[list(v) for v in self.value_names],
            K=K,
            V=V,
            int_values=int_values,
            valid=valid,
        )


@dataclass
class FrozenVocab:
    keys: Dict[str, int]
    key_names: List[str]
    values: List[Dict[str, int]]
    value_names: List[List[str]]
    K: int
    V: int
    int_values: np.ndarray  # [K, V] int64 (LT_NONE for non-integer values)
    valid: np.ndarray  # [K, V] bool — padded slots are False
    well_known_mask: np.ndarray = field(default=None)  # [K] set by encoder

    def fingerprint(self) -> tuple:
        """Structural identity of the closed world: same keys, same values,
        same id assignment. Two solves whose vocabs share a fingerprint can
        share every tensor encoded over the vocab (the prepared-state cache
        key in models/provisioner); building vocabs in canonical sorted
        order (see models/provisioner._build_vocab) makes the fingerprint
        stable across drifting pod mixes with the same label universe."""
        return (
            tuple(self.key_names),
            tuple(tuple(names) for names in self.value_names),
        )


@dataclass
class EntityMasks:
    """Requirement tensors for N entities over a FrozenVocab.

    mask[n,k,v]   — entity n allows value v for key k (Requirement.has under
                    the closed world; includes own Gt/Lt filtering)
    defines[n,k]  — key k present in the entity's Requirements map
    concrete[n,k] — non-complement representation (op In / DoesNotExist)
    negative[n,k] — operator() ∈ {NotIn, DoesNotExist}
    gt/lt[n,k]    — integer bounds with GT_NONE/LT_NONE sentinels
    """

    mask: np.ndarray  # [N, K, V] bool
    defines: np.ndarray  # [N, K] bool
    concrete: np.ndarray  # [N, K] bool
    negative: np.ndarray  # [N, K] bool
    gt: np.ndarray  # [N, K] int32
    lt: np.ndarray  # [N, K] int32

    @property
    def n(self) -> int:
        return self.mask.shape[0]


def encode_requirements_batch(
    vocab: FrozenVocab, batch: List[Requirements]
) -> EntityMasks:
    """Lower a batch of Requirements to mask tensors. The vocab must already
    have observed every requirement in the batch."""
    N, K, V = len(batch), vocab.K, vocab.V
    mask = np.zeros((N, K, V), dtype=bool)
    defines = np.zeros((N, K), dtype=bool)
    concrete = np.zeros((N, K), dtype=bool)
    negative = np.zeros((N, K), dtype=bool)
    gt = np.full((N, K), GT_NONE, dtype=np.int64)
    lt = np.full((N, K), LT_NONE, dtype=np.int64)

    for n, reqs in enumerate(batch):
        # graftlint: disable=GL201 -- writes land at vocab-assigned kid
        # indices, so iteration order cannot affect the tensors
        for key, req in reqs.items():
            kid = vocab.keys[key]
            defines[n, kid] = True
            concrete[n, kid] = not req.complement
            negative[n, kid] = req.operator() in NEGATIVE_OPERATORS
            if req.greater_than is not None:
                gt[n, kid] = req.greater_than
            if req.less_than is not None:
                lt[n, kid] = req.less_than
            mask[n, kid] = _requirement_mask(vocab, kid, req)
    return EntityMasks(
        mask=mask,
        defines=defines,
        concrete=concrete,
        negative=negative,
        # clamp to the sentinel bounds before narrowing: Gt/Lt bounds come
        # off the solve wire (codec._decode_req) as arbitrary ints, and an
        # unclamped astype WRAPS — a hostile 2**40 bound would flip sign
        # inside the int32 device planes. Within the closed world the
        # clamp is exact: every integer vocab value lies strictly inside
        # (GT_NONE, LT_NONE), so a bound at/beyond a sentinel admits (or
        # excludes) exactly the same values the raw bound would, and the
        # host-side mask above already folded the raw bound exactly.
        gt=np.clip(gt, GT_NONE, LT_NONE).astype(np.int32),
        lt=np.clip(lt, GT_NONE, LT_NONE).astype(np.int32),
    )


def decode_requirements(
    vocab: FrozenVocab,
    valmask_row: np.ndarray,  # [K, V] bool
    defines_row: np.ndarray,  # [K] bool
    complement_row: np.ndarray,  # [K] bool
    gt_row: np.ndarray,  # [K] int32
    lt_row: np.ndarray,  # [K] int32
) -> "Requirements":
    """Inverse of encode_requirements_batch for one entity row.

    Rebuilds host Requirements from the device slot planes — used by the
    decode path to materialize a fresh claim's joined requirements (template
    ∧ joined classes ∧ topology tightenings) without replaying the host
    algebra per add. Exact within the closed world: a complement row's
    excluded set is reconstructed as the vocab values the mask rejects that
    the Gt/Lt bounds alone would admit, so ``has()`` agrees with the
    original for every value any solve entity can mention."""
    from karpenter_core_tpu.scheduling.requirement import _within

    reqs = Requirements()
    for kid in np.nonzero(defines_row)[0]:
        key = vocab.key_names[kid]
        names = vocab.value_names[kid]
        gt = int(gt_row[kid])
        lt = int(lt_row[kid])
        gt_o = gt if gt != GT_NONE else None
        lt_o = lt if lt != LT_NONE else None
        mask = valmask_row[kid]
        if not complement_row[kid]:
            vals = {names[v] for v in np.nonzero(mask[: len(names)])[0]}
            reqs.add(Requirement(key, values=vals))
        else:
            excl = {
                names[v]
                for v in range(len(names))
                if not mask[v] and _within(names[v], gt_o, lt_o)
            }
            reqs.add(
                Requirement(
                    key,
                    complement=True,
                    values=excl,
                    greater_than=gt_o,
                    less_than=lt_o,
                )
            )
    return reqs


def _requirement_mask(vocab: FrozenVocab, kid: int, req: Requirement) -> np.ndarray:
    """mask[v] = req.has(value_names[kid][v]) vectorized."""
    V = vocab.V
    out = np.zeros((V,), dtype=bool)
    names = vocab.value_names[kid]
    if req.complement:
        out[: len(names)] = True
        for v in req.values:
            vid = vocab.values[kid].get(v)
            if vid is not None:
                out[vid] = False
    else:
        for v in req.values:
            vid = vocab.values[kid].get(v)
            if vid is not None:
                out[vid] = True
    if req.greater_than is not None or req.less_than is not None:
        ints = vocab.int_values[kid]
        bound_ok = np.ones((V,), dtype=bool)
        if req.greater_than is not None:
            bound_ok &= ints > req.greater_than
        if req.less_than is not None:
            bound_ok &= ints < req.less_than
        # non-integer vocab entries carry LT_NONE and fail any gt bound /
        # pass lt trivially — force them out explicitly
        bound_ok &= ints != LT_NONE
        out &= bound_ok
    out &= vocab.valid[kid]
    return out
