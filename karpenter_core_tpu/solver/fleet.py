"""fleetd: the multi-tenant solve gateway inside solverd.

One solverd used to serve exactly one operator: every request serialized
on a single FIFO lock, with no admission control and an unbounded
per-fingerprint scheduler cache. This module is the gateway that turns
the sidecar into a shared service for N operators (CvxCluster's "one fast
centralized allocator, many granular problems"; Tesserae's placement
serving that stays fair under many concurrent tenants):

* ``FleetGateway`` — a bounded admission queue with deadline-aware
  shedding (a request whose remaining client deadline cannot cover the
  observed p50 device time is rejected immediately, and the HTTP layer
  turns that into ``429 + Retry-After`` so solver/remote.py degrades the
  solve to the host greedy path), weighted fair scheduling across
  tenants, and a priority lane (provisioning solves dispatch ahead of
  consolidation sweeps) so one chatty or hung tenant cannot starve the
  rest;
* the host/device pipeline split — a request owns the device only
  between ``await_grant`` and ``release``; its host phases (codec
  decode before, codec encode after) run on its own handler thread, so
  the encode/decode of request B overlaps the device phase of request A;
* ``BoundedSchedulerCache`` — an LRU bound (entries + approximate
  bytes) with eviction metrics on the per-fingerprint DeviceScheduler
  cache, so a fleet of heterogeneous clusters cannot OOM the sidecar;
* the continuous-batching coalescer — a granted solve (the batch
  LEADER) collects up to ``max_batch - 1`` queued problems in the same
  compile-shape bucket (``collect_batch``; distinct fingerprints, fair
  vtime scan order) and solves them all under ONE exclusive device grant
  as a vmapped multi-problem batch (models/provisioner.solve_batch), the
  scheduler-gateway analogue of continuous batching in LLM serving.
  ``release_batch`` charges each tenant its pod-weighted share of the
  grant's device seconds so the WFQ vclock stays honest, and the shed
  estimator divides the backlog by the observed problems-per-grant so
  admission doesn't over-shed once batching raises throughput.

The gateway never creates threads: it sequences the caller's own handler
threads (ThreadingHTTPServer hands every request its own thread) with one
re-entrant lock and per-ticket events. All shared state is mutated under
``self._lock`` — including inside the ``_locked``-suffixed helpers, which
re-enter the RLock so the discipline is syntactically visible to
graftlint's GL302/GL303 and not an unstated caller contract.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

DEFAULT_TENANT = "default"

# the priority lane: provisioning solves ahead of consolidation sweeps —
# pending pods are unschedulable RIGHT NOW, a consolidation sweep is an
# optimization that can wait one grant
LANE_SOLVE = "solve"
LANE_SWEEP = "sweep"
_LANES = (LANE_SOLVE, LANE_SWEEP)

# admission defaults (service flags / operator passthrough override)
DEFAULT_QUEUE_DEPTH = 16
DEFAULT_CACHE_ENTRIES = 4
DEFAULT_CACHE_BYTES = 256 << 20
# continuous-batching defaults FOR THE SOLVERD FLAGS (the FleetGateway
# constructor itself defaults to max_batch=1/window=0 — batching off — so
# every pre-batching embedder keeps its exact semantics): one grant may
# coalesce up to 8 compatible problems, and a leader waits at most a few
# ms for still-decoding requests to reach the queue
DEFAULT_MAX_BATCH = 8
DEFAULT_BATCH_WINDOW_MS = 2.0
# distinct tenants the gateway keeps state for (vtime, wait samples): the
# id is client-supplied, so on a long-lived shared sidecar a client that
# varies it (a template interpolating a run id) must hit a bound, not a
# slow leak — idle tenants past the cap are forgotten and simply rejoin
# at the virtual clock like any idle tenant
TENANT_STATE_CAP = 1024
# device-time prior before any observation exists (a fresh sidecar must
# not shed its very first requests on a made-up estimate of infinity)
DEVICE_P50_BOOT = 0.5


class ShedError(Exception):
    """A request rejected by admission control (never by a fault).

    ``reason``: ``capacity`` (queue full), ``deadline`` (the remaining
    client deadline cannot cover the estimated queue wait + p50 device
    time), ``expired`` (the deadline lapsed while queued). ``retry_after``
    is the server's estimate, in seconds, of when a retry would be
    admitted — the HTTP layer ships it as the ``Retry-After`` header.
    """

    def __init__(self, reason: str, retry_after: float, message: str = ""):
        super().__init__(message or f"shed ({reason})")
        self.reason = reason
        self.retry_after = retry_after


class DrainError(Exception):
    """The gateway is draining: admission is closed and queued requests
    are being flushed ahead of a clean restart. The HTTP layer answers
    503 (drain ≠ shed ≠ fault: the client degrades this solve to greedy
    without charging the circuit breaker — the sidecar ANSWERED, it is
    restarting, not dead)."""

    def __init__(self, message: str = "gateway draining"):
        super().__init__(message)


class UnknownMemberError(LookupError):
    """A member-indexed fleet entry point (router ``set_member_addr``,
    supervisor ``drain``/``retire_member``, …) named an index outside the
    live member set. With dynamic membership (elastic scale, ISSUE 17)
    indices shift under retirement, so a stale index is an expected
    coordination race, not a programming error — callers catch THIS
    (``LookupError``) and re-observe, instead of a bare ``IndexError``
    escaping from list internals."""

    def __init__(self, index: int, size: int, site: str = ""):
        where = f" in {site}" if site else ""
        super().__init__(
            f"member index {index} outside live member set"
            f" [0, {size}){where}"
        )
        self.index = index
        self.size = size
        self.site = site


class QuarantinedError(Exception):
    """A request refused because its problem fingerprint is quarantined as
    a poison pill. The HTTP layer answers 422; the client routes the solve
    straight to greedy (and quarantines locally) without burning a device
    grant or charging the breaker."""

    def __init__(self, fingerprint: str, message: str = ""):
        super().__init__(
            message or f"fingerprint {fingerprint[:12]} quarantined"
        )
        self.fingerprint = fingerprint


def parse_tenant_weights(spec: str) -> Dict[str, float]:
    """``"a=3,b=1.5"`` -> ``{"a": 3.0, "b": 1.5}`` (the --tenant-weights
    flag format). Unlisted tenants get the gateway's default weight."""
    out: Dict[str, float] = {}
    for part in filter(None, (p.strip() for p in (spec or "").split(","))):
        name, _, value = part.partition("=")
        if not name or not value:
            raise ValueError(f"malformed tenant weight {part!r}")
        weight = float(value)
        if weight <= 0:
            raise ValueError(f"tenant weight must be positive: {part!r}")
        out[name] = weight
    return out


class Ticket:
    """One admitted request's pass through the gateway."""

    __slots__ = (
        "tenant", "lane", "submitted_at", "deadline_at",
        "ready_at", "granted_at", "event", "state",
        # continuous batching: the shape-bucket key + problem fingerprint
        # (set by the daemon after its host-phase decode, BEFORE
        # await_grant), the decoded payload a batch leader solves on the
        # member's behalf, and the result handoff (leader publishes,
        # member's handler thread encodes)
        "bucket", "fingerprint", "payload", "result", "error", "done",
        "batched_member",
    )

    def __init__(self, tenant: str, lane: str, submitted_at: float,
                 deadline_at: Optional[float]):
        self.tenant = tenant
        self.lane = lane
        self.submitted_at = submitted_at
        self.deadline_at = deadline_at
        self.ready_at: Optional[float] = None
        self.granted_at: Optional[float] = None
        self.event = threading.Event()
        # pending | queued | granted | batched | shed | drained | done
        self.state = "pending"
        # ONE-WAY marker set by collect_batch: the daemon branches member
        # vs leader on THIS, not on the mutable `state` — release_batch
        # overwrites a member's state to "done" while its handler thread
        # may still be waking, and a member that raced past that overwrite
        # on a state check would take the leader path without a grant
        self.batched_member = False
        self.bucket: Optional[str] = None
        self.fingerprint: Optional[str] = None
        self.payload = None
        self.result = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()


class FleetGateway:
    """Admission control + weighted fair device scheduling for N tenants.

    Life of a request (one handler thread end to end)::

        ticket = gateway.submit(tenant, lane, deadline)   # may shed
        problem = decode(body)            # host phase, device NOT held
        gateway.await_grant(ticket)       # fair-queued; may shed (expired)
        ...device solve...                # the ONLY exclusive section
        gateway.release(ticket, device_seconds)
        response = encode(results)        # host phase, device NOT held

    Fairness is virtual-time weighted fair queueing: each tenant
    accumulates ``device_seconds / weight`` per grant, and the dispatcher
    always grants the backlogged tenant with the smallest virtual time —
    so a tenant hammering the gateway advances its own clock and cannot
    starve a quiet one, while a weight-3 tenant gets ~3x the device share
    of a weight-1 tenant under contention. A tenant returning from idle
    is bumped to the current virtual clock so it cannot claim the device
    for its entire idle period retroactively.
    """

    def __init__(
        self,
        max_depth: int = DEFAULT_QUEUE_DEPTH,
        weights: Optional[Dict[str, float]] = None,
        default_weight: float = 1.0,
        p50_boot: float = DEVICE_P50_BOOT,
        window: int = 64,
        time_fn=time.monotonic,
        max_batch: int = 1,
        batch_window: float = 0.0,
    ):
        if max_depth <= 0:
            raise ValueError(f"max_depth must be positive, got {max_depth}")
        if max_batch <= 0:
            raise ValueError(f"max_batch must be positive, got {max_batch}")
        if batch_window < 0:
            raise ValueError(
                f"batch_window must be >= 0, got {batch_window}"
            )
        self.max_depth = max_depth
        self.weights = dict(weights or {})
        self.default_weight = default_weight
        # continuous batching: a granted solve may collect up to
        # max_batch-1 compatible queued problems (same shape bucket,
        # distinct fingerprints) to ride its device grant as one vmapped
        # batch; batch_window (seconds) bounds how long the leader may
        # hold the device idle waiting for still-decoding requests to
        # reach the queue. max_batch=1 is the pre-batching gateway.
        self.max_batch = max_batch
        self.batch_window = batch_window
        self.time_fn = time_fn
        # RLock on purpose: the _locked helpers re-acquire it so every
        # shared-state write is syntactically inside a `with self._lock`
        self._lock = threading.RLock()
        self._device_times: deque = deque(maxlen=window)
        self._p50_boot = p50_boot
        # submitted and not yet finished (queued + decoding + on device)
        self._pending = 0
        # tenant -> lane -> FIFO of ready tickets
        self._queued: Dict[str, Dict[str, deque]] = {}
        self._vtime: Dict[str, float] = {}
        self._vclock = 0.0
        self._active: Optional[Ticket] = None
        # bench/test observability (the REGISTRY instruments aggregate
        # process-wide; these are per-gateway and resettable)
        self._wait_samples: Dict[str, deque] = {}
        self._shed_counts: Dict[str, int] = {}
        self._grant_count = 0
        # batch accounting: per-grant problem counts (the shed estimator's
        # amortization factor), members currently riding a leader's grant,
        # lifetime coalesced-problem count
        self._batch_sizes: deque = deque(maxlen=window)
        self._batched_inflight = 0
        self._coalesced = 0
        # per-lane count of tickets still in state "pending" (submitted,
        # host decode running, not yet queued): what the batching window
        # consults — only a mid-decode SOLVE request can coalesce, so a
        # leader must not hold the device idle for sweep traffic
        self._preparing_counts = {lane: 0 for lane in _LANES}
        # drain mode: admission closed, queue flushed with 503s ahead of a
        # clean (supervisor-respawned) process exit
        self._draining = False

    # -- admission ---------------------------------------------------------

    def device_p50(self) -> float:
        with self._lock:
            return self._device_p50_locked()

    def _device_p50_locked(self) -> float:
        """Observed per-GRANT device p50. One observation is recorded per
        exclusive device grant (release_batch), NOT per request — with
        batching on, one grant serves several requests, and an estimator
        that multiplied the backlog by a per-request time would over-shed
        exactly when batching raises effective throughput."""
        if not self._device_times:
            return self._p50_boot
        ts = sorted(self._device_times)
        return ts[len(ts) // 2]

    def _avg_batch_locked(self) -> float:
        """Observed mean problems-per-grant (>= 1): the amortization
        factor the expected-wait model divides the backlog by."""
        if not self._batch_sizes:
            return 1.0
        return max(sum(self._batch_sizes) / len(self._batch_sizes), 1.0)

    def submit(
        self,
        tenant: str = DEFAULT_TENANT,
        lane: str = LANE_SOLVE,
        deadline: Optional[float] = None,
    ) -> Ticket:
        """Admission decision, made BEFORE the request body is decoded (a
        shed must cost the sidecar nothing). Raises ShedError (overload),
        DrainError (restarting), or returns a Ticket the caller must
        resolve via await_grant+release (or abandon on a pre-grant
        failure)."""
        if lane not in _LANES:
            raise ValueError(f"unknown lane {lane!r}")
        with self._lock:
            if self._draining:
                raise DrainError()
            now = self.time_fn()
            p50 = self._device_p50_locked()
            batch = self._avg_batch_locked()
            if self._pending >= self.max_depth:
                # the backlog drains one GRANT (~avg_batch requests) per
                # ~p50 device seconds; the whole backlog must clear
                # before a retry is admitted
                grants_left = -(-self._pending // max(int(batch), 1))
                retry_after = max(grants_left * p50, p50)
                self._count_shed_locked(tenant, "capacity")
                raise ShedError(
                    "capacity", retry_after,
                    f"admission queue full ({self._pending}/{self.max_depth})",
                )
            if deadline is not None:
                # expected wait = grants needed to serve everyone ahead
                # plus this request, at the observed per-grant p50 and the
                # observed batch amortization (avg problems per grant) —
                # NOT one grant per pending request, which would over-shed
                # whenever batching raises effective throughput
                grants_needed = max(
                    (self._pending + 1) / batch, 1.0
                )
                estimate = grants_needed * p50
                if deadline < estimate:
                    retry_after = max(estimate - deadline, p50)
                    self._count_shed_locked(tenant, "deadline")
                    raise ShedError(
                        "deadline", retry_after,
                        f"deadline {deadline:.3f}s cannot cover estimated"
                        f" {estimate:.3f}s (p50 device/grant {p50:.3f}s,"
                        f" avg batch {batch:.2f}, {self._pending} ahead)",
                    )
            self._pending += 1
            ticket = Ticket(
                tenant, lane, now,
                None if deadline is None else now + deadline,
            )
            self._preparing_counts[lane] += 1
            self._export_depth_locked()
            return ticket

    def _count_shed_locked(self, tenant: str, reason: str) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        with self._lock:
            self._shed_counts[reason] = self._shed_counts.get(reason, 0) + 1
        m.SOLVERD_SHED.inc({"tenant": tenant, "reason": reason})

    # -- fair queueing -----------------------------------------------------

    def await_grant(self, ticket: Ticket) -> None:
        """Block the calling handler thread until the fair scheduler hands
        this ticket the device. Raises ShedError if the ticket's deadline
        expired while it queued (the client has already degraded to
        greedy; running the solve anyway would burn device time on an
        answer nobody reads), or DrainError when the gateway drained the
        queue out from under it."""
        with self._lock:
            if self._draining:
                ticket.state = "drained"
                self._pending -= 1
                self._preparing_counts[ticket.lane] -= 1
                self._export_depth_locked()
                raise DrainError()
            ticket.ready_at = self.time_fn()
            ticket.state = "queued"
            self._preparing_counts[ticket.lane] -= 1
            lanes = self._queued.get(ticket.tenant)
            if lanes is None:
                lanes = self._queued[ticket.tenant] = {
                    lane: deque() for lane in _LANES
                }
            if not any(lanes[lane] for lane in _LANES):
                # returning from idle: jump to the current virtual clock —
                # an idle period is not a credit voucher
                self._vtime[ticket.tenant] = max(
                    self._vtime.get(ticket.tenant, 0.0), self._vclock
                )
            lanes[ticket.lane].append(ticket)
            self._dispatch_locked()
        ticket.event.wait()
        if ticket.state == "shed":
            raise ShedError(
                "expired", self.device_p50(),
                "deadline expired while queued",
            )
        if ticket.state == "drained":
            raise DrainError()

    def _dispatch_locked(self) -> None:
        with self._lock:
            if self._active is not None:
                return
            from karpenter_core_tpu.metrics import wiring as m

            now = self.time_fn()
            while True:
                ticket = self._pick_locked()
                if ticket is None:
                    return
                if (
                    ticket.deadline_at is not None
                    and now > ticket.deadline_at
                ):
                    ticket.state = "shed"
                    self._pending -= 1
                    self._count_shed_locked(ticket.tenant, "expired")
                    self._export_depth_locked()
                    ticket.event.set()
                    continue
                break
            ticket.state = "granted"
            ticket.granted_at = now
            self._active = ticket
            # monotone: a stale-vtime grant (a sweep held back behind the
            # solve lane) must not roll the clock backwards, or the
            # idle-rejoin bump would re-open the retroactive-credit hole
            self._vclock = max(
                self._vclock, self._vtime.get(ticket.tenant, 0.0)
            )
            self._grant_count += 1
            self._record_wait_locked(ticket, now)
            ticket.event.set()

    def _record_wait_locked(self, ticket: Ticket, now: float) -> None:
        """Grant-time queue-wait bookkeeping, shared by the dispatcher and
        the batch coalescer: the per-tenant p99 the shed estimator, bench,
        and snapshot() read must see EVERY way off the queue identically."""
        with self._lock:
            from karpenter_core_tpu.metrics import wiring as m

            wait = now - (ticket.ready_at or now)
            m.SOLVERD_QUEUE_WAIT.observe(wait, {"tenant": ticket.tenant})
            samples = self._wait_samples.get(ticket.tenant)
            if samples is None:
                samples = self._wait_samples[ticket.tenant] = deque(
                    maxlen=512
                )
            samples.append(wait)

    def _pick_locked(self) -> Optional[Ticket]:
        """Smallest-virtual-time backlogged tenant; the solve lane drains
        before any sweep is considered (provisioning ahead of
        consolidation). Ties break on tenant name for determinism."""
        with self._lock:
            for lane in _LANES:
                candidates = [
                    (self._vtime.get(tenant, 0.0), tenant)
                    for tenant, lanes in self._queued.items()
                    if lanes[lane]
                ]
                if candidates:
                    _, tenant = min(candidates)
                    return self._queued[tenant][lane].popleft()
            return None

    def release(self, ticket: Ticket, device_seconds: float) -> None:
        """Device phase over: record the observation, charge the tenant's
        virtual time, and grant the next ticket (the single-problem
        wrapper over release_batch — a solo grant IS a batch of one)."""
        self.release_batch([(ticket, 1.0)], device_seconds)

    # -- continuous batching (coalesce compatible queued problems) ---------

    def collect_batch(self, leader: Ticket, limit: int = None) -> List[Ticket]:
        """Pop up to ``limit`` queued solve-lane tickets compatible with
        the GRANTED leader — same shape bucket, DISTINCT problem
        fingerprints (a fingerprint maps to one cached DeviceScheduler,
        which is single-solve stateful) — to ride its device grant as one
        vmapped multi-problem batch. Their handler threads wake with
        state="batched" and block in await_batched for the leader's
        per-problem outcome; expired tickets found on the way shed exactly
        as the dispatcher would. Tenants are scanned in virtual-time order
        so coalescing cannot become a side door around fair queueing."""
        if limit is None:
            limit = self.max_batch - 1
        members: List[Ticket] = []
        if limit <= 0 or leader.bucket is None:
            return members
        with self._lock:
            if self._active is not leader:
                return members
            now = self.time_fn()
            seen = {leader.fingerprint}
            for tenant in sorted(
                self._queued, key=lambda t: (self._vtime.get(t, 0.0), t)
            ):
                if len(members) >= limit:
                    break
                q = self._queued[tenant][LANE_SOLVE]
                kept: deque = deque()
                while q and len(members) < limit:
                    t = q.popleft()
                    if (
                        t.bucket is None
                        or t.bucket != leader.bucket
                        or t.fingerprint in seen
                    ):
                        kept.append(t)
                        continue
                    if t.deadline_at is not None and now > t.deadline_at:
                        t.state = "shed"
                        self._pending -= 1
                        self._count_shed_locked(t.tenant, "expired")
                        t.event.set()
                        continue
                    t.batched_member = True
                    t.state = "batched"
                    t.granted_at = now
                    seen.add(t.fingerprint)
                    self._record_wait_locked(t, now)
                    members.append(t)
                    t.event.set()
                while q:  # preserve FIFO order for everything skipped
                    kept.append(q.popleft())
                self._queued[tenant][LANE_SOLVE] = kept
            self._batched_inflight += len(members)
            self._export_depth_locked()
            return members

    def compatible_queued(self, leader: Ticket) -> int:
        """How many queued solve-lane tickets collect_batch could pop for
        this leader RIGHT NOW (same shape bucket, distinct fingerprints).
        The batching window's short-circuit: a leader whose batch is
        already fillable from the queue must not hold the device idle
        waiting for more."""
        if leader.bucket is None:
            return 0
        with self._lock:
            seen = {leader.fingerprint}
            n = 0
            for lanes in self._queued.values():
                for t in lanes[LANE_SOLVE]:
                    if t.bucket == leader.bucket and t.fingerprint not in seen:
                        seen.add(t.fingerprint)
                        n += 1
            return n

    def preparing(self, lane: str = LANE_SOLVE) -> int:
        """Tickets in the given lane submitted but not yet queued —
        requests still in their host decode phase. The batching window
        only pays off when one of these could reach the queue before the
        leader dispatches, so the daemon consults this before holding the
        device idle for the window; it is per-lane because only a
        mid-decode SOLVE request can ever coalesce onto a solve grant —
        sweep traffic must not buy device idle."""
        with self._lock:
            return self._preparing_counts.get(lane, 0)

    def finish_batched(self, ticket: Ticket, result=None,
                       error: BaseException = None) -> None:
        """Leader -> member handoff: publish one member's per-problem
        outcome and wake its handler thread (which encodes its own
        response — the host fan-out stays off the device window)."""
        ticket.result = result
        ticket.error = error
        ticket.done.set()

    def await_batched(self, ticket: Ticket):
        """Member side: block until the batch leader publishes this
        problem's outcome; re-raise its ISOLATED error (one poisoned
        batch member fails alone) or return the result."""
        ticket.done.wait()
        if ticket.error is not None:
            raise ticket.error
        return ticket.result

    def release_batch(
        self, shares: List[tuple], device_seconds: float
    ) -> None:
        """One device grant finished having served ``len(shares)``
        problems: record ONE per-grant device-time observation (the
        admission estimator's unit is the grant, not the request), charge
        each tenant its share of the batch's device seconds (the daemon
        weights shares by problem pod count), and grant the next ticket.

        ``shares``: ``[(ticket, weight), ...]`` — leader first, then the
        collected members; weights are normalized here."""
        from karpenter_core_tpu.metrics import wiring as m

        with self._lock:
            dt = max(device_seconds, 0.0)
            self._device_times.append(dt)
            self._batch_sizes.append(len(shares))
            m.SOLVERD_BATCH_SIZE.observe(float(len(shares)))
            if len(shares) > 1:
                self._coalesced += len(shares) - 1
                m.SOLVERD_BATCH_COALESCED.inc(by=len(shares) - 1)
            total = sum(max(s, 0.0) for _, s in shares) or 1.0
            for ticket, share in shares:
                weight = max(
                    self.weights.get(ticket.tenant, self.default_weight),
                    1e-9,
                )
                self._vtime[ticket.tenant] = (
                    self._vtime.get(ticket.tenant, 0.0)
                    + dt * (max(share, 0.0) / total) / weight
                )
                if ticket.state == "batched":
                    self._batched_inflight -= 1
                ticket.state = "done"
                self._pending -= 1
            self._active = None
            self._export_depth_locked()
            self._dispatch_locked()
            self._prune_locked()

    def _prune_locked(self) -> None:
        """Bound the per-tenant maps. Tenant ids arrive from the client,
        so without pruning every distinct id leaks a vtime float, a lane
        dict, and a wait deque for the sidecar's lifetime."""
        with self._lock:
            # empty lane dicts are pure bookkeeping — recreated on demand
            for tenant in [
                t for t, lanes in self._queued.items()
                if not any(lanes[lane] for lane in _LANES)
            ]:
                del self._queued[tenant]
            if len(self._vtime) > TENANT_STATE_CAP:
                # an idle tenant at-or-behind the clock carries no
                # information: rejoining would bump it to the clock anyway
                for tenant in [
                    t for t, v in self._vtime.items()
                    if t not in self._queued and v <= self._vclock
                ]:
                    del self._vtime[tenant]
            if len(self._vtime) > TENANT_STATE_CAP:
                # still over (many ahead-of-clock idles): trim smallest
                # vtime first — forgetting forgives at most their lead
                idle = sorted(
                    (v, t) for t, v in self._vtime.items()
                    if t not in self._queued
                )
                for _v, tenant in idle[: len(self._vtime) - TENANT_STATE_CAP]:
                    del self._vtime[tenant]
            if len(self._wait_samples) > TENANT_STATE_CAP:
                for tenant in [
                    t for t in self._wait_samples if t not in self._queued
                ][: len(self._wait_samples) - TENANT_STATE_CAP]:
                    del self._wait_samples[tenant]

    def abandon(self, ticket: Ticket) -> None:
        """A request failed between submit and grant (decode error, client
        gone): return its admission slot. Safe on granted tickets too (a
        device-phase exception path), where it behaves like a zero-cost
        release."""
        with self._lock:
            if ticket.state == "queued":
                lanes = self._queued.get(ticket.tenant)
                if lanes is not None:
                    for lane in _LANES:
                        try:
                            lanes[lane].remove(ticket)
                        except ValueError:
                            pass
            if ticket.state == "granted" and self._active is ticket:
                self._active = None
            if ticket.state in ("pending", "queued", "granted", "batched"):
                if ticket.state == "batched":
                    self._batched_inflight -= 1
                if ticket.state == "pending":
                    self._preparing_counts[ticket.lane] -= 1
                ticket.state = "done"
                self._pending -= 1
                self._export_depth_locked()
            self._dispatch_locked()

    # -- drain (the crash-only restart path) -------------------------------

    def drain(self) -> int:
        """Close admission and flush every queued ticket with a drain
        rejection (their handler threads answer 503 — queued requests must
        never just VANISH into a process exit). The active device ticket,
        if any, is left to finish or be watchdog-killed; returns the number
        of tickets flushed."""
        with self._lock:
            self._draining = True
            flushed = 0
            for lanes in list(self._queued.values()):
                for lane in _LANES:
                    while lanes[lane]:
                        ticket = lanes[lane].popleft()
                        ticket.state = "drained"
                        self._pending -= 1
                        flushed += 1
                        ticket.event.set()
            self._export_depth_locked()
            return flushed

    def resume(self) -> None:
        """Re-open admission (in-thread test servers; a real sidecar exits
        after drain and respawns fresh)."""
        with self._lock:
            self._draining = False

    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def set_batch_window(self, seconds: float) -> None:
        """Retune the coalescing window live (brownout rung 2 widens it
        to force deeper batches; descent restores the original)."""
        if seconds < 0:
            raise ValueError(f"batch_window must be >= 0, got {seconds}")
        with self._lock:
            self.batch_window = seconds

    def set_max_depth(self, depth: int) -> None:
        """Retune admission capacity live (brownout rung 3 halves it so
        shedding starts earlier; descent restores the original). Already
        queued tickets above a lowered bound stay queued — the bound
        gates NEW admissions only."""
        if depth <= 0:
            raise ValueError(f"max_depth must be positive, got {depth}")
        with self._lock:
            self.max_depth = depth

    def batch_stats(self) -> dict:
        """Lightweight batch telemetry for /healthz (snapshot() computes
        percentiles — too heavy for a probe path)."""
        with self._lock:
            return {
                "max_batch": self.max_batch,
                "window_s": self.batch_window,
                "coalesced": self._coalesced,
                "mean_size": round(self._avg_batch_locked(), 3),
                # members riding a leader's grant RIGHT NOW — nonzero
                # while a coalesced batch is on the device
                "inflight_members": self._batched_inflight,
            }

    # -- observability -----------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return self._pending

    def saturated(self) -> bool:
        with self._lock:
            return self._pending >= self.max_depth

    def _export_depth_locked(self) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        with self._lock:
            m.SOLVERD_QUEUE_DEPTH.set(float(self._pending))

    def snapshot(self, reset: bool = False) -> dict:
        """Per-gateway stats for the bench/tests (the REGISTRY instruments
        are process-global and never reset): per-tenant queue-wait
        percentiles over the recent sample window, shed counts by reason,
        grant count, current depth."""
        with self._lock:
            def q(samples: List[float], p: float) -> float:
                if not samples:
                    return 0.0
                ts = sorted(samples)
                return ts[min(int(round(p * (len(ts) - 1))), len(ts) - 1)]

            out = {
                "tenants": {
                    tenant: {
                        "n": len(samples),
                        "wait_p50_s": round(q(list(samples), 0.50), 6),
                        "wait_p99_s": round(q(list(samples), 0.99), 6),
                    }
                    for tenant, samples in sorted(self._wait_samples.items())
                },
                "sheds": dict(sorted(self._shed_counts.items())),
                "grants": self._grant_count,
                "depth": self._pending,
                "draining": self._draining,
                "device_p50_s": round(self._device_p50_locked(), 6),
                "batch": {
                    "max_batch": self.max_batch,
                    "window_s": self.batch_window,
                    "coalesced": self._coalesced,
                    "mean_size": round(self._avg_batch_locked(), 3),
                },
            }
            if reset:
                self._wait_samples = {}
                self._shed_counts = {}
                self._grant_count = 0
                self._batch_sizes.clear()
                self._coalesced = 0
            return out


# poison-pill defaults (service flags / client kwargs override)
QUARANTINE_STRIKES = 3
QUARANTINE_TTL = 300.0
QUARANTINE_CAP = 1024


class PoisonQuarantine:
    """TTL'd poison-pill ledger over request digests (codec.request_digest:
    sha256 of the canonical body for full-wire requests — PR 4 made wire
    bytes canonical per logical problem — and the manifest CORE for
    delta-wire requests, so the digest stays stable across retries AND
    across the miss/re-upload handshake's changing upload payloads).

    A problem that crashes, hangs, corrupts its result, or fails
    verification ``strikes`` times inside the TTL window is quarantined:
    for ``ttl`` seconds it routes straight to the greedy path (client
    site) or is refused pre-decode with 422 (gateway site) instead of
    burning device grants — and, for the wedge-the-process shapes,
    sidecar respawns — for every tenant. A success clears the strike
    count; quarantine entries expire on their own (the problem gets a
    fresh chance — the fault may have been environmental).

    The optional journal is the crash-only half: the gateway records the
    fingerprint it is ABOUT to solve (``begin``) and clears it on
    completion (``done``), so a poison pill that kills the process is
    found in the journal at next boot and charged a strike even though
    the process that hit it never got to say so.

    All shared state is mutated under ``self._lock`` (the ``_locked``
    helper discipline graftlint GL302/GL303 checks)."""

    def __init__(
        self,
        strikes: int = QUARANTINE_STRIKES,
        ttl: float = QUARANTINE_TTL,
        cap: int = QUARANTINE_CAP,
        time_fn=time.monotonic,
        site: str = "client",
        journal_path: Optional[str] = None,
    ):
        if strikes <= 0:
            raise ValueError(f"strikes must be positive, got {strikes}")
        self.strikes = strikes
        self.ttl = ttl
        self.cap = cap
        self.time_fn = time_fn
        self.site = site
        self.journal_path = journal_path
        self._lock = threading.RLock()
        self._strike_counts: Dict[str, tuple] = {}  # fp -> (count, last_at)
        self._entries: Dict[str, float] = {}  # fp -> quarantined_until
        self._inflight: set = set()
        if journal_path is not None:
            self._recover_journal()

    # -- the ledger --------------------------------------------------------

    def strike(self, fingerprint: str, reason: str = "fault") -> bool:
        """Record one fault against a fingerprint; returns True when this
        strike tipped it into quarantine."""
        with self._lock:
            now = self.time_fn()
            count, last_at = self._strike_counts.get(fingerprint, (0, now))
            if now - last_at > self.ttl:
                count = 0  # stale streak: faults outside the window forgive
            count += 1
            self._strike_counts[fingerprint] = (count, now)
            if count < self.strikes:
                self._prune_locked(now)
                return False
            self._entries[fingerprint] = now + self.ttl
            del self._strike_counts[fingerprint]
            self._prune_locked(now)
            self._export_locked()
            return True

    def poison(self, fingerprint: str) -> None:
        """Quarantine immediately (the gateway already counted its strikes
        and told us via 422 — no reason to re-learn locally)."""
        with self._lock:
            self._entries[fingerprint] = self.time_fn() + self.ttl
            self._strike_counts.pop(fingerprint, None)
            self._prune_locked(self.time_fn())
            self._export_locked()

    def quarantined(self, fingerprint: str) -> bool:
        with self._lock:
            until = self._entries.get(fingerprint)
            if until is None:
                return False
            if self.time_fn() >= until:
                del self._entries[fingerprint]
                self._export_locked()
                return False
            return True

    def clear(self, fingerprint: str) -> None:
        """A success: the problem is not poison — drop its strike streak.
        An ACTIVE quarantine entry stays until its TTL (a success can only
        have come from the greedy path while quarantined)."""
        with self._lock:
            self._strike_counts.pop(fingerprint, None)

    def size(self) -> int:
        with self._lock:
            now = self.time_fn()
            stale = [fp for fp, t in self._entries.items() if now >= t]
            for fp in stale:
                del self._entries[fp]
            if stale:
                self._export_locked()
            return len(self._entries)

    def _prune_locked(self, now: float) -> None:
        """Bound both maps: fingerprints are derived from client-supplied
        bodies, so an unbounded ledger is a memory leak with extra steps."""
        with self._lock:
            if len(self._strike_counts) > self.cap:
                stale = sorted(
                    self._strike_counts.items(), key=lambda kv: kv[1][1]
                )
                for fp, _ in stale[: len(self._strike_counts) - self.cap]:
                    del self._strike_counts[fp]
            expired = [fp for fp, t in self._entries.items() if now >= t]
            for fp in expired:
                del self._entries[fp]
            if len(self._entries) > self.cap:
                soonest = sorted(self._entries.items(), key=lambda kv: kv[1])
                for fp, _ in soonest[: len(self._entries) - self.cap]:
                    del self._entries[fp]

    def _export_locked(self) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        with self._lock:
            m.SOLVER_QUARANTINE_ENTRIES.set(
                float(len(self._entries)), {"site": self.site}
            )

    # -- crash-only journal ------------------------------------------------

    def begin(self, fingerprint: str) -> None:
        """Mark a fingerprint in flight on the device. If the process dies
        before ``done``, the next boot finds it in the journal and charges
        the crash it never lived to report."""
        if self.journal_path is None:
            return
        with self._lock:
            self._inflight.add(fingerprint)
            self._write_journal_locked()

    def done(self, fingerprint: str) -> None:
        if self.journal_path is None:
            return
        with self._lock:
            self._inflight.discard(fingerprint)
            self._write_journal_locked()

    def _write_journal_locked(self) -> None:
        import json as _json
        import os as _os

        with self._lock:
            # write-temp + atomic rename: the journal exists to survive a
            # process death, so the death must never catch it half-written
            # (a torn in-place rewrite would parse as garbage at recovery
            # and silently forget the very strike it was recording)
            tmp = f"{self.journal_path}.tmp"
            try:
                # graftlint: disable=GL705 -- deliberate: the write+rename
                # must stay serialized with the snapshot it records, or two
                # racing writers can land an OLDER journal over a newer one
                # (lost strike at recovery). The quarantine lock guards only
                # strike metadata — never the device grant (GL304 covers
                # that) — and the journal is a few hundred bytes on local
                # disk, so the tail this blocks is bounded and private.
                with open(tmp, "w") as f:
                    _json.dump(
                        {
                            "inflight": sorted(self._inflight),
                            "strikes": {
                                fp: count
                                for fp, (count, _at) in
                                self._strike_counts.items()
                            },
                        },
                        f,
                    )
                _os.replace(tmp, self.journal_path)
            except OSError:
                pass  # journal loss degrades protection, never the solve

    def _recover_journal(self) -> None:
        import json as _json

        try:
            with open(self.journal_path) as f:
                state = _json.load(f)
        except (OSError, ValueError):
            return
        now = self.time_fn()
        with self._lock:
            for fp, count in dict(state.get("strikes", {})).items():
                self._strike_counts[fp] = (int(count), now)
        # every fingerprint in flight at death gets the strike the dead
        # process could not record — N wedge-deaths in a row quarantine it
        for fp in state.get("inflight", []):
            self.strike(fp, "crash-recovered")
        # persist the merged view with the inflight set CLEARED: the
        # strike is recorded now, and a later clean boot must not
        # re-charge it
        with self._lock:
            self._write_journal_locked()


class BoundedSchedulerCache:
    """LRU over fingerprint -> DeviceScheduler with an entry AND an
    approximate-byte bound, so a fleet of heterogeneous clusters (every
    distinct problem half is its own entry) cannot grow the sidecar's
    memory without bound. ``approx_bytes`` is the caller's proxy for the
    entry's weight — solverd passes the encoded request size, which
    tracks catalog/node-count scale without walking device buffers.
    Evictions are observable (`solverd_scheduler_cache_evictions_total`
    by reason, entry/byte gauges) so a fleet dashboard can tell "cache
    too small for this tenant mix" from "cold tenant"."""

    def __init__(
        self,
        max_entries: int = DEFAULT_CACHE_ENTRIES,
        max_bytes: int = DEFAULT_CACHE_BYTES,
    ):
        if max_entries <= 0:
            raise ValueError(
                f"max_entries must be positive, got {max_entries}"
            )
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self._lock = threading.RLock()
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()
        self._bytes = 0
        self.evictions: Dict[str, int] = {}

    def get(self, fingerprint: str):
        with self._lock:
            hit = self._entries.get(fingerprint)
            if hit is None:
                return None
            self._entries.move_to_end(fingerprint)
            return hit[0]

    def put(self, fingerprint: str, scheduler, approx_bytes: int) -> None:
        with self._lock:
            old = self._entries.pop(fingerprint, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[fingerprint] = (scheduler, int(approx_bytes))
            self._bytes += int(approx_bytes)
            while len(self._entries) > self.max_entries:
                self._evict_locked("entries")
            # strict bound — even a single oversized problem may not pin
            # more than the budget (it still SERVES, just uncached)
            while self._bytes > self.max_bytes and self._entries:
                self._evict_locked("bytes")
            self._export_locked()

    def _evict_locked(self, reason: str) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        with self._lock:
            _fp, (_sched, nbytes) = self._entries.popitem(last=False)
            self._bytes -= nbytes
            self.evictions[reason] = self.evictions.get(reason, 0) + 1
        m.SOLVERD_SCHED_CACHE_EVICTIONS.inc({"reason": reason})

    def _export_locked(self) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        with self._lock:
            m.SOLVERD_SCHED_CACHE_ENTRIES.set(float(len(self._entries)))
            m.SOLVERD_SCHED_CACHE_BYTES.set(float(self._bytes))

    # dict-like views the solverd tests/ops surface read

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, fingerprint: str) -> bool:
        with self._lock:
            return fingerprint in self._entries

    def values(self) -> list:
        with self._lock:
            return [sched for sched, _bytes in self._entries.values()]

    def total_bytes(self) -> int:
        with self._lock:
            return self._bytes
