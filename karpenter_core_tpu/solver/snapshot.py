"""Cluster-snapshot → device-tensor codec.

One ``Snapshot`` is the device-resident image of everything one solve needs:
pod equivalence classes, instance-type catalog, nodeclaim templates, and
existing nodes, all encoded over a single closed-world vocabulary
(solver/vocab.py). This is the host↔device boundary the reference never had
— its moral equivalent is the scheduler-input assembly in
provisioner.go:215-284 (NodePool listing, instance types, topology-domain
universe).

Pods collapse into equivalence classes first (identical requirements,
tolerations, and resource requests are exchangeable in the FFD loop — the
reference walks them one at a time, we batch them; scheduler.go:208-266).
50k pods from a handful of deployments typically collapse to a few hundred
classes, which is what makes the device scan short.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import Pod, RESOURCE_PODS, Taint
from karpenter_core_tpu.cloudprovider.types import InstanceType
from karpenter_core_tpu.scheduling import Requirements
from karpenter_core_tpu.solver.gangs import pod_gang_sig
from karpenter_core_tpu.utils.disruption import priority_tier
from karpenter_core_tpu.solver.vocab import (
    EntityMasks,
    FrozenVocab,
    Vocab,
    encode_requirements_batch,
)

# Default resource axis; extended resources append dynamically.
BASE_RESOURCES = ("cpu", "memory", "pods", "ephemeral-storage")


@dataclass
class PodClass:
    """An equivalence class of pending pods."""

    requirements: Requirements
    strict_requirements: Requirements
    tolerations: tuple
    requests: dict
    pods: List[Pod] = field(default_factory=list)
    # gangsched (ISSUE 10): the class's priority tier
    # (utils/disruption.priority_tier — 0 for the k8s default) and its
    # gang signature (solver/gangs.pod_gang_sig — None outside any gang).
    # Both are part of the spec signature below, so a class is always
    # tier- and gang-homogeneous; plain pods carry the defaults and their
    # signatures (hence every prepared-state cache key) are unchanged.
    tier: int = 0
    gang: Optional[tuple] = None
    # the raw-spec equivalence key this class was grouped under (see
    # _spec_signature). Everything the solver encodes per class — value
    # masks, strict masks, quantized request vectors, taint rows — is a
    # pure function of (signature, vocab, catalog), which is what lets the
    # prepared-state cache in models/provisioner reuse encoded rows across
    # solves and relaxation rounds instead of re-running the numpy encode
    # for every class every round.
    signature: tuple = ()

    @property
    def count(self) -> int:
        return len(self.pods)


def _spec_signature(pod: Pod, label_aware: bool) -> tuple:
    """Raw-spec equivalence key. Strictly finer than (or equal to) the
    requirement-level signature — two pods with identical selector/affinity/
    toleration/request/spread fields always produce identical Requirements —
    so grouping by it is sound and skips building Requirements per pod.

    When the solve carries topology groups (label_aware), the key also
    covers pod-(anti-)affinity terms and the pod's own labels: labels decide
    which groups COUNT the pod (TopologyGroup.selects), terms decide which
    groups CONSTRAIN it, so pods differing in either are not exchangeable.
    Topology-free solves skip both so deployment-distinct labels don't
    fragment the 50k-pod class collapse.

    Priority tiers and gang membership (ISSUE 10) append a trailing
    component ONLY when non-default: the kernel packs tiers in order and
    commits gangs atomically, so pods differing in either are not
    exchangeable — but a default-tier gang-free pod's signature is
    byte-identical to the pre-gang one (the off-by-default parity the
    prepared caches and wire fingerprints rest on). The suffixed tuples
    cannot collide with the unsuffixed ones (lengths 3/12 vs 2/11)."""
    tier = priority_tier(pod.priority)
    gang = pod_gang_sig(pod)
    suffix = () if tier == 0 and gang is None else ((tier, gang),)
    # fast path for the dominant 50k-batch shape: resource-only pods (no
    # affinity/tolerations/spread/ports/volumes). The short tuple can never
    # collide with the full 10-tuple below.
    if (
        pod.affinity is None
        and not pod.tolerations
        and not pod.topology_spread_constraints
        and not pod.host_ports
        and not pod.volumes
        and not pod.volume_requirements
        and not pod.node_selector
    ):
        return (
            tuple(sorted(pod.resource_requests.items())),
            tuple(sorted((pod.metadata.labels or {}).items()))
            if label_aware
            else (),
        ) + suffix
    affinity_sig = None
    pod_aff_sig = None
    pod_anti_sig = None
    if pod.affinity is not None:
        if pod.affinity.node_affinity is not None:
            na = pod.affinity.node_affinity
            affinity_sig = (
                tuple(na.required),
                tuple(na.preferred),
            )
        if pod.affinity.pod_affinity is not None:
            pa = pod.affinity.pod_affinity
            pod_aff_sig = (tuple(pa.required), tuple(pa.preferred))
        if pod.affinity.pod_anti_affinity is not None:
            pa = pod.affinity.pod_anti_affinity
            pod_anti_sig = (tuple(pa.required), tuple(pa.preferred))
    return (
        tuple(sorted(pod.node_selector.items())),
        affinity_sig,
        pod_aff_sig,
        pod_anti_sig,
        tuple(sorted((pod.metadata.labels or {}).items()))
        if label_aware
        else (),
        tuple(sorted((t.key, t.operator, t.value, t.effect) for t in pod.tolerations)),
        tuple(sorted(pod.resource_requests.items())),
        tuple(pod.topology_spread_constraints),
        # hostPort pods must form their own class so the decode path always
        # runs per-pod HostPortUsage conflict checks (nodeclaim.go add path);
        # sharing a class with port-free twins would skip them
        tuple(sorted(pod.host_ports)),
        # PVC-derived requirements and volume identities both affect
        # placement (zone pins; attach-limit accounting on existing nodes)
        tuple(pod.volume_requirements),
        tuple(pod.volumes),
    ) + suffix


def group_pods(pods: Sequence[Pod], label_aware: bool = True) -> List[PodClass]:
    """Dedupe pods into equivalence classes. Signature covers everything the
    resource+requirements+taints solve observes; pods with affinity/spread
    constraints get their own per-constraint signatures (handled by the
    topology-aware path). Requirements are built once per class, not per
    pod — the 50k-pod path spends its time here otherwise."""
    classes: Dict[tuple, PodClass] = {}
    for pod in pods:
        sig = _spec_signature(pod, label_aware)
        cls = classes.get(sig)
        if cls is None:
            cls = PodClass(
                requirements=Requirements.from_pod(pod),
                strict_requirements=Requirements.from_pod_strict(pod),
                tolerations=tuple(pod.tolerations),
                requests=dict(pod.resource_requests),
                signature=(label_aware, sig),
                tier=priority_tier(pod.priority),
                gang=pod_gang_sig(pod),
            )
            classes[sig] = cls
        cls.pods.append(pod)
    return list(classes.values())


@dataclass
class Snapshot:
    """Encoded solve inputs (numpy; jax device put happens in models/)."""

    vocab: FrozenVocab
    resource_names: List[str]
    well_known: np.ndarray  # [K] bool

    # pod classes
    classes: List[PodClass]
    class_masks: EntityMasks
    class_requests: np.ndarray  # [C, R]
    class_counts: np.ndarray  # [C] int32
    class_tolerates: np.ndarray  # [C, TA] bool

    # instance types
    instance_types: List[InstanceType]
    it_masks: EntityMasks
    it_allocatable: np.ndarray  # [T, R]
    it_min_price: np.ndarray  # [T] cheapest available offering price (inf if none)
    it_has_offering: np.ndarray  # [T] bool any available offering

    # taint vocabulary
    taints: List[Taint]

    @property
    def C(self) -> int:
        return len(self.classes)

    @property
    def T(self) -> int:
        return len(self.instance_types)

    @property
    def R(self) -> int:
        return len(self.resource_names)


def encode_snapshot(
    pods: Sequence[Pod],
    instance_types: Sequence[InstanceType],
    extra_requirements: Sequence[Requirements] = (),
    extra_taints: Sequence[Sequence[Taint]] = (),
) -> Tuple[Snapshot, Optional[EntityMasks], Optional[np.ndarray]]:
    """Encode pods + catalog, plus an optional extra entity group sharing the
    vocab — e.g. nodeclaim templates (one Requirements per template, one taint
    list per template) or existing nodes.

    Returns (snapshot, extra_masks [S,...], extra_taint_matrix [S, TA]).
    """
    classes = group_pods(pods)

    vocab = Vocab()
    for cls in classes:
        vocab.observe_requirements(cls.requirements)
    for it in instance_types:
        vocab.observe_requirements(it.requirements)
        for off in it.offerings:
            vocab.observe_requirements(off.requirements)
    for reqs in extra_requirements:
        vocab.observe_requirements(reqs)
    frozen = vocab.finalize()

    well_known = np.zeros((frozen.K,), dtype=bool)
    # graftlint: disable=GL201 -- writes land at vocab-assigned kid
    # indices, so iteration order cannot affect the plane
    for key, kid in frozen.keys.items():
        well_known[kid] = key in apilabels.WELL_KNOWN_LABELS
    frozen.well_known_mask = well_known

    # resource axis
    resource_names = list(BASE_RESOURCES)
    seen = set(resource_names)
    for coll in (
        [c.requests for c in classes],
        [it.allocatable() for it in instance_types],
    ):
        for rl in coll:
            for name in rl:
                if name not in seen:
                    seen.add(name)
                    resource_names.append(name)

    class_masks = encode_requirements_batch(frozen, [c.requirements for c in classes])
    it_masks = encode_requirements_batch(
        frozen, [it.requirements for it in instance_types]
    )

    C, R, T = len(classes), len(resource_names), len(instance_types)
    class_requests = np.zeros((C, R), dtype=np.float32)
    for i, cls in enumerate(classes):
        for j, name in enumerate(resource_names):
            class_requests[i, j] = cls.requests.get(name, 0.0)
        # every pod occupies one slot of the 'pods' resource
        class_requests[i, resource_names.index(RESOURCE_PODS)] += 1.0
    class_counts = np.array([c.count for c in classes], dtype=np.int32)

    it_allocatable = np.zeros((T, R), dtype=np.float32)
    it_min_price = np.full((T,), np.inf, dtype=np.float32)
    it_has_offering = np.zeros((T,), dtype=bool)
    for i, it in enumerate(instance_types):
        alloc = it.allocatable()
        for j, name in enumerate(resource_names):
            it_allocatable[i, j] = alloc.get(name, 0.0)
        available = it.offerings.available()
        if available:
            it_has_offering[i] = True
            it_min_price[i] = min(o.price for o in available)

    # taint vocabulary: union over extra taint groups (templates/nodes);
    # classes precompute toleration per taint host-side (exact semantics).
    taint_list: List[Taint] = []
    taint_ids: Dict[Taint, int] = {}
    for group in extra_taints:
        for t in group:
            if t not in taint_ids:
                taint_ids[t] = len(taint_list)
                taint_list.append(t)
    TA = max(len(taint_list), 1)
    class_tolerates = np.zeros((C, TA), dtype=bool)
    for i, cls in enumerate(classes):
        # graftlint: disable=GL201 -- writes land at tid indices assigned
        # above in extra_taints arrival order, so iteration order cannot
        # affect the matrix
        for t, tid in taint_ids.items():
            class_tolerates[i, tid] = any(
                tol.tolerates(t) for tol in cls.tolerations
            )

    snapshot = Snapshot(
        vocab=frozen,
        resource_names=resource_names,
        well_known=well_known,
        classes=classes,
        class_masks=class_masks,
        class_requests=class_requests,
        class_counts=class_counts,
        class_tolerates=class_tolerates,
        instance_types=list(instance_types),
        it_masks=it_masks,
        it_allocatable=it_allocatable,
        it_min_price=it_min_price,
        it_has_offering=it_has_offering,
        taints=taint_list,
    )

    extra_masks = (
        encode_requirements_batch(frozen, list(extra_requirements))
        if extra_requirements
        else None
    )
    extra_taint_matrix = None
    if extra_taints:
        extra_taint_matrix = np.zeros((len(extra_taints), TA), dtype=bool)
        for i, group in enumerate(extra_taints):
            for t in group:
                tid = taint_ids.get(t)
                if tid is not None:
                    extra_taint_matrix[i, tid] = True
    return snapshot, extra_masks, extra_taint_matrix
