"""TierAutoscaler: the closed loop that sizes the solverd fleet (ISSUE 17).

The tier is horizontally scaled (PR 13) but was statically sized: a fixed
``--solver-fleet=N`` wastes members through a quiet night or sheds load
through a surge. Elasticity here is POLICY, not new lifecycle work — the
crash-only drain contract (PR 8), digest-affinity routing with a one-miss
re-upload handshake (PR 13) and the respawn-storm alarm (PR 14) already
make member churn cheap and observable. This module adds the control loop
on top:

* **Signals** come from what the tier already exports: per-member
  queue-wait p50/p99 and shed rate from the gateway snapshot (served at
  ``GET /statz``), queue depth and draining state from the same snapshot,
  spill/in-flight counts from the router. Adapters normalize them into a
  single scalar **pressure** (>= 1.0 means the tier is over its queue-wait
  budget) plus per-member load, so the policy itself never does I/O.
* **Hysteresis**: separate up/down pressure thresholds, separate
  consecutive-observation streak requirements, separate per-direction
  cooldowns, and hard min/max member bounds. The middle band between the
  thresholds resets both streaks — a flapping signal scales nothing.
* **Flap containment**: scale-up is suppressed while ``respawn_storm()``
  fires (growing a melting tier feeds the melt), and scale-down never
  picks a member that is draining or currently answering a spill.
* **Scale-down = drain**: the victim is the least-loaded member, retired
  through the faultless ``POST /drain`` path (``DRAIN_EXIT_CODE``, zero
  backoff charge) via ``FleetSupervisor.retire_member()``; the router's
  rendezvous hash runs over the live member set, so retiring member k
  remaps only k's digests — one miss/re-upload round each, breakers
  untouched, fallbacks unmoved (the PR 13 respawn contract extended to
  resize).
* **Brownout ladder**: at max members with pressure still over budget the
  loop climbs an explicit degradation ladder instead of shedding blind —
  rung 1 serves ``relax`` requests in FFD mode (the anytime answer,
  verifier unchanged), rung 2 widens the batch window for deeper
  coalescing, rung 3 halves queue capacity so shedding starts earlier.
  Each rung has its own enter/exit hysteresis and is exported as a
  metric-labeled state on ``/healthz``; verification is never disabled on
  any rung. Rungs enter 1->2->3 and exit 3->2->1, strictly in order.

Lock discipline (GL302/GL304): ``step()`` is gather -> decide -> actuate.
``observe()`` and every actuation (HTTP drain, subprocess spawn) run with
NO autoscaler lock held; only the pure decision runs under
``_state_lock``. The decision log (``decisions``) is the deterministic
record the twin replays byte-identically.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

# the explicit degradation ladder above max scale; service.SolverDaemon
# imports this so the daemon-side rung validation and the policy can
# never disagree about how deep the ladder goes
BROWNOUT_MAX_RUNG = 3


@dataclass
class MemberSignal:
    """One member's load as seen at observation time."""

    member: str
    depth: int = 0
    inflight: int = 0
    spilling: int = 0
    draining: bool = False
    wait_p99_s: float = 0.0


@dataclass
class TierSignals:
    """One observation of the whole tier, normalized by an adapter.

    ``pressure`` is the scalar the hysteresis runs on: the tier's worst
    queue-wait p99 over its budget (so >= 1.0 means over budget), bumped
    to at least 1.0 whenever the observation window saw sheds — a shed IS
    the over-budget signal, whatever the percentiles say."""

    members: List[MemberSignal] = field(default_factory=list)
    pressure: float = 0.0
    storm: bool = False


class TierAutoscaler:
    """Hysteresis + cooldown control loop over a tier adapter.

    The adapter (``SpawnedTier`` for supervised subprocesses, the twin's
    virtual tier, the bench's in-thread tier) provides::

        observe() -> TierSignals     # may block on I/O; no lock held
        scale_up() -> None           # spawn + route one more member
        scale_down(index) -> None    # drain, retire, un-route member
        set_rung(rung) -> None       # push the brownout rung to members

    ``step()`` runs one control iteration and returns the actions taken.
    Call it from the reconcile loop (the operator) or a virtual-clock
    tick (the twin); ``start()`` runs it on a background thread for
    standalone deployments.
    """

    def __init__(
        self,
        tier,
        min_members: int,
        max_members: int,
        *,
        up_pressure: float = 1.0,
        down_pressure: float = 0.3,
        up_stable: int = 2,
        down_stable: int = 3,
        up_cooldown_s: float = 30.0,
        down_cooldown_s: float = 120.0,
        rung_up_stable: int = 2,
        rung_down_stable: int = 2,
        time_fn: Callable[[], float] = time.monotonic,
        on_decision: Optional[Callable[[str, str], None]] = None,
    ):
        if min_members < 1:
            raise ValueError(f"min_members must be >= 1, got {min_members}")
        if max_members < min_members:
            raise ValueError(
                f"max_members ({max_members}) < min_members ({min_members})"
            )
        if down_pressure >= up_pressure:
            raise ValueError(
                "down_pressure must sit below up_pressure "
                f"({down_pressure} >= {up_pressure}) — equal thresholds flap"
            )
        self.tier = tier
        self.min_members = min_members
        self.max_members = max_members
        self.up_pressure = up_pressure
        self.down_pressure = down_pressure
        self.up_stable = max(1, up_stable)
        self.down_stable = max(1, down_stable)
        self.up_cooldown_s = up_cooldown_s
        self.down_cooldown_s = down_cooldown_s
        self.rung_up_stable = max(1, rung_up_stable)
        self.rung_down_stable = max(1, rung_down_stable)
        self.time_fn = time_fn
        self.on_decision = on_decision
        self._state_lock = threading.RLock()
        self.rung = 0
        self._up_streak = 0
        self._down_streak = 0
        self._rung_up_streak = 0
        self._rung_down_streak = 0
        self._last_up_at: Optional[float] = None
        self._last_down_at: Optional[float] = None
        # deterministic decision log: (t, action, detail) — the twin
        # replays this byte-identically and the bench reads rung order
        self.decisions: List[Tuple[float, str, str]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the control iteration --------------------------------------------

    def step(self) -> List[Tuple[str, object]]:
        """One gather -> decide -> actuate iteration.

        Observation and actuation both block on I/O, so neither runs
        under ``_state_lock`` — only the pure policy does. Single-caller
        by contract (the reconcile loop or the background thread, never
        both)."""
        signals = self.tier.observe()
        now = float(self.time_fn())
        actions = self._decide(signals, now)
        for action, arg in actions:
            self._actuate(action, arg, signals)
        if self.on_decision is not None:
            for action, arg in actions:
                self.on_decision(action, str(arg))
        return actions

    def _decide(
        self, signals: TierSignals, now: float
    ) -> List[Tuple[str, object]]:
        with self._state_lock:
            n = len(signals.members)
            over = signals.pressure >= self.up_pressure
            under = signals.pressure <= self.down_pressure
            if over:
                self._up_streak += 1
                self._down_streak = 0
            elif under:
                self._down_streak += 1
                self._up_streak = 0
            else:
                # the hysteresis band: a signal bouncing between the
                # thresholds earns neither direction
                self._up_streak = 0
                self._down_streak = 0

            # rung streaks only accumulate where the ladder applies:
            # climb pressure only counts at max size (below max,
            # capacity comes first), descent pressure only counts while
            # a rung is held
            if n >= self.max_members and over:
                self._rung_up_streak += 1
            else:
                self._rung_up_streak = 0
            if self.rung > 0 and not over:
                self._rung_down_streak += 1
            else:
                self._rung_down_streak = 0

            actions: List[Tuple[str, object]] = []
            if over:
                if signals.storm:
                    # never grow a melting tier: a respawn storm means
                    # new members would join the same melt
                    actions.append(
                        ("hold", "respawn storm suppresses scale-up")
                    )
                elif (
                    n < self.max_members
                    and self._up_streak >= self.up_stable
                    and self._cooled(
                        self._last_up_at, self.up_cooldown_s, now
                    )
                ):
                    self._last_up_at = now
                    self._up_streak = 0
                    actions.append(
                        (
                            "up",
                            f"pressure={signals.pressure:.3f}"
                            f" n={n}->{n + 1}",
                        )
                    )
                elif (
                    n >= self.max_members
                    and self.rung < BROWNOUT_MAX_RUNG
                    and self._rung_up_streak >= self.rung_up_stable
                ):
                    self.rung += 1
                    self._rung_up_streak = 0
                    actions.append(("rung_up", self.rung))
            elif self.rung > 0:
                # descend the ladder fully before any scale-down: a
                # tier that still holds a rung was overloaded a moment
                # ago
                if self._rung_down_streak >= self.rung_down_stable:
                    self.rung -= 1
                    self._rung_down_streak = 0
                    actions.append(("rung_down", self.rung))
            elif (
                under
                and n > self.min_members
                and self._down_streak >= self.down_stable
                and self._cooled(self._last_down_at, self.down_cooldown_s, now)
            ):
                victim = self._victim(signals)
                if victim is None:
                    actions.append(
                        (
                            "hold",
                            "no drainable member (all spilling or draining)",
                        )
                    )
                else:
                    self._last_down_at = now
                    self._down_streak = 0
                    actions.append(("down", victim))
            for action, arg in actions:
                self.decisions.append((round(now, 3), action, str(arg)))
            return actions

    @staticmethod
    def _cooled(last_at: Optional[float], cooldown: float, now: float) -> bool:
        return last_at is None or now - last_at >= cooldown

    @staticmethod
    def _victim(signals: TierSignals) -> Optional[int]:
        """Least-loaded retirable member index, or None.

        A member mid-drain is already leaving; a member answering a spill
        is the tier's safety valve RIGHT NOW — draining it would turn a
        refusal-with-answer into a loss. Ties break on the lowest index
        so twin replays pick the same victim byte-for-byte."""
        candidates = [
            (ms.inflight + ms.spilling, ms.depth, i)
            for i, ms in enumerate(signals.members)
            if not ms.draining and ms.spilling == 0
        ]
        if not candidates:
            return None
        return min(candidates)[2]

    def _actuate(self, action: str, arg: object, signals: TierSignals) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        n = len(signals.members)
        if action == "up":
            self.tier.scale_up()
            m.SOLVER_FLEET_SCALE.inc({"direction": "up"})
            m.SOLVER_FLEET_SIZE.set(float(n + 1))
        elif action == "down":
            self.tier.scale_down(int(arg))
            m.SOLVER_FLEET_SCALE.inc({"direction": "down"})
            m.SOLVER_FLEET_SIZE.set(float(n - 1))
        elif action in ("rung_up", "rung_down"):
            self.tier.set_rung(int(arg))
            m.SOLVER_FLEET_SCALE.inc({"direction": action})

    # -- optional background loop -----------------------------------------

    def start(self, interval_s: float = 10.0) -> None:
        """Run ``step()`` on a daemon thread every ``interval_s`` until
        ``stop()``; the operator instead calls step() from reconcile, so
        this path is for standalone tiers."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.step()
                except Exception:  # noqa: BLE001 — the loop must survive
                    pass

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class SpawnedTier:
    """Production adapter: FleetSupervisor-spawned members + FleetRouter(s).

    ``observe()`` polls every member's ``GET /statz?reset=1`` (the gateway
    snapshot: per-tenant queue-wait percentiles over the window since the
    last poll, shed counts, depth, draining) and folds the router's
    in-flight/spill counts in; pressure is the tier's worst per-tenant
    wait p99 over ``wait_budget_s``, bumped to the over-budget threshold
    whenever the window saw sheds. All member lists (supervisor members,
    every router's members) stay index-aligned: scale events mutate them
    in lockstep.
    """

    def __init__(
        self,
        supervisor,
        routers,
        make_client,
        wait_budget_s: float = 1.0,
        poll_timeout: float = 5.0,
    ):
        if wait_budget_s <= 0:
            raise ValueError(
                f"wait_budget_s must be positive, got {wait_budget_s}"
            )
        self.supervisor = supervisor
        self.routers = list(routers)
        self.make_client = make_client
        self.wait_budget_s = wait_budget_s
        self.poll_timeout = poll_timeout

    def _statz(self, addr: str) -> Optional[dict]:
        import json
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://{addr}/statz?reset=1", timeout=self.poll_timeout
            ) as resp:
                return json.loads(resp.read().decode())
        except (OSError, ValueError):
            return None

    def observe(self) -> TierSignals:
        storm = self.supervisor.respawn_storm()
        loads = self.routers[0].member_loads() if self.routers else {}
        members: List[MemberSignal] = []
        pressure = 0.0
        shed_seen = False
        for sup in self.supervisor.members:
            stats = self._statz(sup.addr) if sup.alive() else None
            inflight, spilling = loads.get(sup.member, (0, 0))
            if stats is None:
                # down or unreachable: respawn in flight — treat like a
                # draining member (never a scale-down victim)
                members.append(
                    MemberSignal(
                        member=sup.member,
                        inflight=inflight,
                        spilling=spilling,
                        draining=True,
                    )
                )
                continue
            p99 = max(
                (t.get("wait_p99_s", 0.0) for t in stats["tenants"].values()),
                default=0.0,
            )
            sheds = sum(int(v) for v in stats.get("sheds", {}).values())
            shed_seen = shed_seen or sheds > 0
            pressure = max(pressure, p99 / self.wait_budget_s)
            members.append(
                MemberSignal(
                    member=sup.member,
                    depth=int(stats.get("depth", 0)),
                    inflight=inflight,
                    spilling=spilling,
                    draining=bool(stats.get("draining", False)),
                    wait_p99_s=p99,
                )
            )
        if shed_seen:
            pressure = max(pressure, 1.0)
        return TierSignals(members=members, pressure=pressure, storm=storm)

    def scale_up(self) -> None:
        idx = self.supervisor.add_member()
        sup = self.supervisor.members[idx]
        for router in self.routers:
            router.add_member(
                self.make_client(sup.addr, sup.member), member_id=sup.member
            )

    def scale_down(self, index: int) -> None:
        # un-route FIRST so no new solve lands on the victim, then drain:
        # anything already in flight gets the gateway's 503 flush and
        # spills to a surviving member (an answered refusal, no breaker
        # charge)
        for router in self.routers:
            router.remove_member(index)
        self.supervisor.retire_member(index)

    def set_rung(self, rung: int) -> None:
        import json
        import urllib.request

        body = json.dumps({"rung": rung}).encode()
        for sup in self.supervisor.members:
            if not sup.alive():
                continue
            req = urllib.request.Request(
                f"http://{sup.addr}/brownout",
                data=body,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=self.poll_timeout):
                    pass
            except (OSError, ValueError):
                continue
