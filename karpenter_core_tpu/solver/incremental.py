"""incsolve: churn-proportional incremental re-solve (ISSUE 16).

The delta wire (PR 13) made steady-state requests cheap to *ship*; this
module makes them cheap to *solve*. A :class:`PackingLedger` retains the
previous solve's accepted packing keyed by the manifest's (mode-suffixed)
problem fingerprint. When the next request names its predecessor
(``prev_fingerprint`` on the wire), the :class:`IncrementalEngine` diffs
the decoded problem against the remembered one at three granularities —
the problem CORE (nodepools / catalog / daemonsets / ICE / slot ceiling),
the per-node digests (codec's canonical SimNode encoding), and the pod
equivalence classes (solver/snapshot.group_pods) — and replays every
placement the diff proves untouched:

* **warm**   — nothing changed: the recorded packing replays verbatim
  (recorded pod uids re-bound to the current pod objects by uid, then by
  class-interchangeability), no scheduler is ever constructed.
* **partial** — some classes are dirty (new signature, count change, a
  prior error, or a prior placement on a node whose digest moved): clean
  classes stay pinned to their recorded claims/nodes as CLOSED occupancy,
  and only the dirty pods re-enter a host-greedy sub-solve against the
  nodes' reduced availability.
* **full**   — ledger miss (amnesia), core change, topology/gang/eviction
  structure, or a dirty set past the proportionality bound: the inner
  DeviceScheduler solves fresh (lazily constructed — warm replays never
  pay for one). When a prior entry exists and the backend is relax, the
  recorded per-class nodepool seeds the kernel's fractional warm start
  (``DeviceScheduler._relax_warm`` → ops/relax warm_template).
* **drift_reset** — the drift controller forced the full solve: either
  the configured interval since the last full elapsed, or a replayed
  packing regressed past the node-count bound vs the last full baseline
  (incremental packings must not ratchet into bad node sets).
* **rejected** — a replayed packing failed the UNMODIFIED ResultVerifier
  (solver/verify.py, the same trust anchor fresh results face): the
  replay is discarded and a fresh solve serves. Deliberately *not*
  routed through ``verify.reject`` — ``solver_result_rejected_total`` is
  the wire/device-corruption signal and the acceptance battery pins it
  at zero; an engine self-check firing is a degradation, not a client-
  facing rejection.

Every outcome lands on ``solver_incremental_total{outcome=...}`` and the
final result (replayed or fresh) is remembered under the CURRENT
fingerprint, so steady-state churn pays one diff + one sub-solve per
round regardless of cluster size. The ledger is bounded (entries and
approximate bytes, LRU) and lives with the digest-affinity-routed fleet
member (solver/remote.FleetRouter pins a snapshot's manifests to one
member, so its ledger keeps hitting); a respawned member's empty ledger
is indistinguishable from a miss — amnesia degrades to a full solve,
never to a wrong bind.
"""
from __future__ import annotations

import copy
import math
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

DEFAULT_MAX_ENTRIES = 128
DEFAULT_MAX_BYTES = 64 * 1024 * 1024
# drift controller: force a full solve every N solves even when every
# round replays clean (interval), and whenever a replayed packing needs
# more than baseline*(1+slack) fresh nodes (regression trigger)
DEFAULT_FULL_INTERVAL = 16
DEFAULT_REGRESSION_SLACK = 0.02
# proportionality bound: past this the diff bookkeeping stops paying for
# itself and the full path's vmapped kernel wins anyway
DEFAULT_MAX_DIRTY_FRACTION = 0.25
DEFAULT_MAX_DIRTY_PODS = 512


@dataclass
class LedgerEntry:
    """One remembered packing: everything replay needs, nothing heavier.

    Placements are recorded as uid/name references (the result-wire
    shape, solver/codec.encode_solve_results) plus the per-class uid
    partition — live Pod/claim objects are NOT retained, so an entry's
    footprint scales with the uid count, not the object graph."""

    key: str
    core_digest: str
    topo_digest: str
    node_digests: Dict[str, str]
    label_aware: bool
    # class signature -> {"count", "uids", "exist_nodes", "pool",
    # "errored", "gangy"}
    classes: Dict[tuple, dict]
    # recorded result, wire-shaped: claims keep the live Requirements
    # object (read-only from here on) + instance-type NAMES
    claims: List[dict]
    existing: List[Tuple[str, List[str]]]
    errors: Dict[str, str]
    evictions: Dict[str, List[str]]
    node_count: int
    baseline_nodes: int
    solves_since_full: int = 0
    nbytes: int = 0


class PackingLedger:
    """Bounded LRU store of LedgerEntry by mode-suffixed fingerprint
    (the SegmentStore/BoundedSchedulerCache idiom one tier up)."""

    def __init__(
        self,
        max_entries: int = DEFAULT_MAX_ENTRIES,
        max_bytes: int = DEFAULT_MAX_BYTES,
    ):
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self._entries: "OrderedDict[str, LedgerEntry]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.evictions: Dict[str, int] = {}

    def get(self, key: str) -> Optional[LedgerEntry]:
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
            return e

    def remember(self, entry: LedgerEntry) -> None:
        with self._lock:
            old = self._entries.pop(entry.key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[entry.key] = entry
            self._bytes += entry.nbytes
            while len(self._entries) > self.max_entries:
                self._drop_oldest_locked("entries")
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                self._drop_oldest_locked("bytes")
        self._export()

    def _drop_oldest_locked(self, reason: str) -> None:
        _, dropped = self._entries.popitem(last=False)
        self._bytes -= dropped.nbytes
        self.evictions[reason] = self.evictions.get(reason, 0) + 1

    def _export(self) -> None:
        from karpenter_core_tpu.metrics import wiring as m

        with self._lock:
            m.SOLVER_LEDGER_ENTRIES.set(float(len(self._entries)))
            m.SOLVER_LEDGER_BYTES.set(float(self._bytes))

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "evictions": dict(self.evictions),
            }


# -- problem digests -------------------------------------------------------


def _digest(obj) -> str:
    from karpenter_core_tpu.solver import segments

    return segments.digest_of(segments.canonical_bytes(obj))


def _core_digest(problem: dict) -> str:
    """The problem half that invalidates EVERY placement when it moves:
    nodepools, instance-type catalog, daemonset overhead, ICE snapshot,
    slot ceiling. Canonical codec encodings, so object identity and
    relist order never churn it."""
    from karpenter_core_tpu.kube import serial
    from karpenter_core_tpu.solver import codec

    table, pools = codec._encode_it_table(problem["instance_types"])
    return _digest({
        "nodepools": [
            serial.encode(np_)
            for np_ in sorted(
                problem["nodepools"], key=lambda n: n.metadata.name
            )
        ],
        "it_table": table,
        "it_pools": pools,
        "daemonset_pods": [
            serial.encode(p)
            for p in sorted(
                problem["daemonset_pods"], key=codec._pod_sort_key
            )
        ],
        "unavailable_offerings": sorted(
            list(k) for k in problem["unavailable_offerings"]
        ),
        "max_slots": problem["max_slots"],
    })


def _topo_digest(problem: dict) -> str:
    from karpenter_core_tpu.solver import codec

    return _digest(codec._encode_topology(problem.get("topology")))


def _node_digests(existing_nodes) -> Dict[str, str]:
    from karpenter_core_tpu.solver import codec

    return {
        n.name: _digest(codec._encode_sim_node(n)) for n in existing_nodes
    }


# -- the engine ------------------------------------------------------------


class IncrementalScheduler:
    """The lazy wrapper solver/service swaps onto a solve_batch entry
    when the request names a predecessor. Duck-types the scheduler
    surface the batch leader touches (``solver_mode``, ``relax_budget_s``
    assignment, ``.solve(pods)`` via solve_batch's compat generator); the
    inner DeviceScheduler is only constructed if the engine decides it
    needs one, so a warm replay never pays device/prepare cost."""

    def __init__(
        self,
        engine: "IncrementalEngine",
        problem: dict,
        make_inner: Callable[[], object],
    ):
        self._engine = engine
        self._problem = problem
        self._make_inner = make_inner
        self.solver_mode = problem.get("solver_mode") or "ffd"
        self.relax_budget_s: Optional[float] = None

    def solve(self, pods: List) -> object:
        return self._engine.solve(
            self._problem, pods, self._make_inner,
            relax_budget_s=self.relax_budget_s,
        )


class IncrementalEngine:
    """The decision tree + replay machinery over one PackingLedger."""

    def __init__(
        self,
        ledger: Optional[PackingLedger] = None,
        full_interval: int = DEFAULT_FULL_INTERVAL,
        max_dirty_fraction: float = DEFAULT_MAX_DIRTY_FRACTION,
        max_dirty_pods: int = DEFAULT_MAX_DIRTY_PODS,
        regression_slack: float = DEFAULT_REGRESSION_SLACK,
    ):
        self.ledger = ledger if ledger is not None else PackingLedger()
        self.full_interval = full_interval
        self.max_dirty_fraction = max_dirty_fraction
        self.max_dirty_pods = max_dirty_pods
        self.regression_slack = regression_slack
        # last-solve debug surface for tests/healthz: outcome, reason,
        # dirty/pinned accounting, verifier violations (strings)
        self.last: dict = {}

    def wrap(
        self, problem: dict, make_inner: Callable[[], object]
    ) -> IncrementalScheduler:
        return IncrementalScheduler(self, problem, make_inner)

    def stats(self) -> dict:
        return {
            "enabled": True,
            "full_interval": self.full_interval,
            "max_dirty_fraction": self.max_dirty_fraction,
            "max_dirty_pods": self.max_dirty_pods,
            "regression_slack": self.regression_slack,
            "ledger": self.ledger.stats(),
            "last": {
                k: v
                for k, v in self.last.items()
                if k in ("outcome", "reason", "dirty_classes",
                         "dirty_pods", "pinned_pods")
            },
        }

    # -- solve -------------------------------------------------------------

    def solve(
        self,
        problem: dict,
        pods: List,
        make_inner: Callable[[], object],
        relax_budget_s: Optional[float] = None,
    ):
        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.solver.snapshot import group_pods

        mode = problem.get("solver_mode") or "ffd"
        cur_key = problem["fingerprint"]
        prev_fp = problem.get("prev_fingerprint") or ""
        entry = (
            self.ledger.get(f"{prev_fp}+m{mode}") if prev_fp else None
        )

        label_aware = problem.get("topology") is not None
        classes = group_pods(pods, label_aware)
        core = _core_digest(problem)
        topo = _topo_digest(problem)
        nodes = _node_digests(problem["existing_nodes"])

        outcome, reason, results = self._attempt(
            problem, pods, classes, entry, core, topo, nodes,
        )
        if results is None:
            # every non-replay path lands here: build (or cache-hit) the
            # real scheduler and solve fresh — seeding the relax warm
            # start from the prior packing when one is remembered
            results = self._full_solve(
                entry, pods, make_inner, relax_budget_s
            )
        self.last.update({"outcome": outcome, "reason": reason})
        m.SOLVER_INCREMENTAL.inc({"outcome": outcome})
        baseline = (
            entry.baseline_nodes
            if entry is not None and outcome in ("warm", "partial")
            else len(results.new_node_claims)
        )
        since_full = (
            entry.solves_since_full + 1
            if entry is not None and outcome in ("warm", "partial")
            else 0
        )
        self.ledger.remember(self._record(
            cur_key, classes, results, core, topo, nodes, label_aware,
            baseline, since_full,
        ))
        return results

    def _attempt(self, problem, pods, classes, entry, core, topo, nodes):
        """Decide warm/partial/full and build the replayed Results for
        the replay outcomes (None = caller runs the full solve)."""
        if entry is None:
            self.last = {"dirty_classes": 0, "dirty_pods": 0,
                         "pinned_pods": 0, "violations": []}
            return "full", "miss", None
        if entry.solves_since_full + 1 >= self.full_interval:
            self.last = {"dirty_classes": 0, "dirty_pods": 0,
                         "pinned_pods": 0, "violations": []}
            return "drift_reset", "interval", None
        if entry.core_digest != core:
            self.last = {"dirty_classes": 0, "dirty_pods": 0,
                         "pinned_pods": 0, "violations": []}
            return "full", "core_changed", None

        cur = {c.signature: c for c in classes}
        dirty = {
            sig
            for sig, c in cur.items()
            if (rec := entry.classes.get(sig)) is None
            or rec["count"] != len(c.pods)
        }
        removed = set(entry.classes) - set(cur)
        nodes_changed = entry.node_digests != nodes
        topo_changed = entry.topo_digest != topo

        if not dirty and not removed and not nodes_changed \
                and not topo_changed:
            results = self._replay_warm(problem, cur, entry)
            if results is not None:
                ok, label = self._self_verify(problem, pods, results)
                if ok:
                    self.last.update({
                        "dirty_classes": 0, "dirty_pods": 0,
                        "pinned_pods": len(pods),
                    })
                    return "warm", "", results
                return "rejected", label, None
            return "full", "replay_failed", None

        # structural bail-outs: pinning interacts with cross-class state
        # (skew domains, gang atomicity, eviction credit) the cheap diff
        # cannot attribute — those problems always solve fresh
        if problem.get("topology") is not None or topo_changed:
            self._reset_last()
            return "full", "topology", None
        if entry.evictions:
            self._reset_last()
            return "full", "evictions", None
        gangy = any(
            c.gang is not None or c.tier != 0 for c in classes
        ) or any(rec.get("gangy") for rec in entry.classes.values())
        if gangy:
            self._reset_last()
            return "full", "gangs", None

        # classes whose prior placement touched a dirty/removed node, or
        # that recorded an unschedulable pod (freed/changed capacity may
        # admit them now), re-enter the scan with the dirty set
        dirty_nodes = {
            name
            for name in set(entry.node_digests) | set(nodes)
            if entry.node_digests.get(name) != nodes.get(name)
        }
        for sig, rec in entry.classes.items():
            if sig in cur and sig not in dirty:
                if rec["errored"] or any(
                    n in dirty_nodes for n in rec["exist_nodes"]
                ):
                    dirty.add(sig)
        dirty_pods = sum(len(cur[s].pods) for s in dirty)
        bound = max(
            self.max_dirty_pods,
            int(self.max_dirty_fraction * max(len(pods), 1)),
        )
        if dirty_pods > bound:
            self._reset_last()
            return "full", "too_dirty", None

        results = self._replay_partial(
            problem, cur, entry, dirty, dirty_nodes
        )
        if results is None:
            return "full", "replay_failed", None
        ok, label = self._self_verify(problem, pods, results)
        if not ok:
            return "rejected", label, None
        ceiling = max(
            entry.baseline_nodes + 1,
            int(math.ceil(
                entry.baseline_nodes * (1.0 + self.regression_slack)
            )),
        )
        if len(results.new_node_claims) > ceiling:
            return "drift_reset", "node_regression", None
        self.last.update({
            "dirty_classes": len(dirty),
            "dirty_pods": dirty_pods,
            "pinned_pods": len(pods) - dirty_pods,
        })
        return "partial", "", results

    def _reset_last(self):
        self.last = {"dirty_classes": 0, "dirty_pods": 0,
                     "pinned_pods": 0, "violations": []}

    # -- replay ------------------------------------------------------------

    def _uid_map(self, cur, entry, sigs) -> Optional[Dict[str, object]]:
        """Recorded pod uid -> current Pod, per clean class: identity
        first (an unchanged pod replays its own placement — the byte-
        parity path), then queue order (pods inside one equivalence
        class are interchangeable by construction)."""
        uid_map: Dict[str, object] = {}
        for sig in sigs:
            rec_uids = entry.classes[sig]["uids"]
            cur_pods = cur[sig].pods
            if len(rec_uids) != len(cur_pods):
                return None
            by_uid = {p.uid: p for p in cur_pods}
            rec_set = set(rec_uids)
            spares = iter(
                p for p in cur_pods if p.uid not in rec_set
            )
            for u in rec_uids:
                p = by_uid.get(u)
                uid_map[u] = p if p is not None else next(spares)
        return uid_map

    def _pool_context(self, problem):
        """templates/overhead/it_by_name for claim reconstruction — the
        solver/remote._materialize recipe against the decoded problem."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.nodeclaimtemplate import (  # noqa: E501
            NodeClaimTemplate,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (  # noqa: E501
            _daemon_compatible,
        )
        from karpenter_core_tpu.utils import resources as resutil

        it_by_name: Dict[str, object] = {}
        for its in problem["instance_types"].values():
            for it in its:
                it_by_name.setdefault(it.name, it)
        templates: Dict[str, object] = {}
        overhead: Dict[str, dict] = {}
        for np_ in problem["nodepools"]:
            nct = NodeClaimTemplate.from_nodepool(np_)
            templates[np_.name] = nct
            overhead[np_.name] = resutil.requests_for_pods(*[
                p for p in problem["daemonset_pods"]
                if _daemon_compatible(nct, p)
            ])
        return templates, overhead, it_by_name

    def _rebuild_claim(self, c, uid_map, templates, overhead, it_by_name):
        """One recorded claim back to a live InFlightNodeClaim carrying
        only the uids the map covers; None when its pool vanished (the
        core digest should have caught that — degrade, don't guess)."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (  # noqa: E501
            InFlightNodeClaim,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (  # noqa: E501
            Topology,
        )
        from karpenter_core_tpu.utils import resources as resutil

        template = templates.get(c["nodepool"])
        if template is None:
            return None
        kept = [uid_map[u] for u in c["pod_uids"] if u in uid_map]
        if not kept:
            return ()
        claim = InFlightNodeClaim(
            template,
            Topology(),
            overhead[c["nodepool"]],
            [it_by_name[n] for n in c["instance_types"] if n in it_by_name],
        )
        claim.requirements = c["requirements"]
        if len(kept) == len(c["pod_uids"]):
            claim.requests = dict(c["requests"])
        else:
            # a partially-kept claim re-sums overhead + surviving pods;
            # the recorded total counted pods that re-entered the scan
            req = dict(overhead[c["nodepool"]])
            for k, v in resutil.requests_for_pods(*kept).items():
                req[k] = req.get(k, 0.0) + v
            claim.requests = req
        claim.pods = kept
        return claim

    def _replay_warm(self, problem, cur, entry):
        """Zero-diff replay: recorded claims/sims/errors/evictions
        re-bound to the current pod objects, order preserved."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (  # noqa: E501
            ExistingNodeSim,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (  # noqa: E501
            Results,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (  # noqa: E501
            Topology,
        )

        uid_map = self._uid_map(cur, entry, list(entry.classes))
        if uid_map is None:
            return None
        templates, overhead, it_by_name = self._pool_context(problem)
        claims = []
        for c in entry.claims:
            claim = self._rebuild_claim(
                c, uid_map, templates, overhead, it_by_name
            )
            if claim is None:
                return None
            if claim != ():
                claims.append(claim)
        node_by_name = {n.name: n for n in problem["existing_nodes"]}
        sims = []
        for name, uids in entry.existing:
            node = node_by_name.get(name)
            if node is None:
                return None
            sim = ExistingNodeSim(node, Topology(), {})
            sim.pods = [uid_map[u] for u in uids if u in uid_map]
            sims.append(sim)
        return Results(
            new_node_claims=claims,
            existing_nodes=sims,
            pod_errors={
                uid_map[u].uid: msg
                for u, msg in entry.errors.items()
                if u in uid_map
            },
            evictions={
                n: list(uids) for n, uids in entry.evictions.items()
            },
        )

    def _replay_partial(self, problem, cur, entry, dirty, dirty_nodes):
        """Pin every clean placement, host-greedy-solve the dirty pods
        against what capacity the pins leave, merge per node."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.inflight import (  # noqa: E501
            ExistingNodeSim,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (  # noqa: E501
            Results,
            Scheduler,
        )
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (  # noqa: E501
            Topology,
        )
        from karpenter_core_tpu.utils import resources as resutil

        clean = [
            sig for sig in entry.classes
            if sig in cur and sig not in dirty
        ]
        uid_map = self._uid_map(cur, entry, clean)
        if uid_map is None:
            return None
        templates, overhead, it_by_name = self._pool_context(problem)
        claims = []
        for c in entry.claims:
            claim = self._rebuild_claim(
                c, uid_map, templates, overhead, it_by_name
            )
            if claim is None:
                return None
            if claim != ():
                claims.append(claim)
        # pinned occupancy on existing nodes (clean classes never sit on
        # a dirty node — the diff marked those classes dirty)
        pinned_by_node: Dict[str, list] = {}
        for name, uids in entry.existing:
            kept = [uid_map[u] for u in uids if u in uid_map]
            if kept:
                pinned_by_node[name] = kept

        dirty_pods = [
            p for sig in dirty for p in cur[sig].pods
        ]
        sub_by_node: Dict[str, list] = {}
        sub_errors: Dict[str, str] = {}
        if dirty_pods:
            clones = []
            for n in problem["existing_nodes"]:
                clone = copy.copy(n)
                avail = dict(n.available)
                for p in pinned_by_node.get(n.name, ()):  # subtract pins
                    for k, v in resutil.requests_for_pods(p).items():
                        avail[k] = max(avail.get(k, 0.0) - v, 0.0)
                clone.available = avail
                # the greedy sub-solve never preempts; an evictable view
                # on the clone would only confuse downstream accounting
                clone.evictable = ()
                clones.append(clone)
            sub = Scheduler(
                problem["nodepools"],
                problem["instance_types"],
                existing_nodes=clones,
                daemonset_pods=problem["daemonset_pods"],
                topology=None,
                unavailable_offerings=problem["unavailable_offerings"],
            ).solve(dirty_pods)
            claims.extend(sub.new_node_claims)
            sub_errors = dict(sub.pod_errors)
            for sim in sub.existing_nodes:
                if sim.pods:
                    sub_by_node[sim.name] = list(sim.pods)

        sims = []
        for n in problem["existing_nodes"]:
            sim = ExistingNodeSim(n, Topology(), {})
            sim.pods = (
                pinned_by_node.get(n.name, [])
                + sub_by_node.get(n.name, [])
            )
            sims.append(sim)
        return Results(
            new_node_claims=claims,
            existing_nodes=sims,
            pod_errors=sub_errors,
            evictions={},
        )

    # -- verification / full solve ----------------------------------------

    def _self_verify(self, problem, pods, results):
        """The unmodified trust anchor over the replayed packing. Any
        violation discards the replay for a fresh solve — and is kept
        OFF the solver_result_rejected_total counter on purpose (module
        docstring): this is self-distrust, not a client-facing reject."""
        from karpenter_core_tpu.solver.verify import ResultVerifier

        violations = ResultVerifier(
            problem["nodepools"],
            problem["instance_types"],
            existing_nodes=problem["existing_nodes"],
            daemonset_pods=problem["daemonset_pods"],
            topology=problem["topology"],
            unavailable_offerings=problem["unavailable_offerings"],
        ).verify(results, pods)
        self.last = {
            "violations": [str(v) for v in violations],
            "dirty_classes": 0, "dirty_pods": 0, "pinned_pods": 0,
        }
        if violations:
            return False, "verify:" + ",".join(
                sorted({v.reason for v in violations})
            )
        return True, ""

    def _full_solve(self, entry, pods, make_inner, relax_budget_s):
        inner = make_inner()
        if getattr(inner, "solver_mode", "ffd") == "relax":
            # reset-don't-set, the cached-scheduler rule service.py
            # applies one layer up (a stale budget/warm map on a cached
            # DeviceScheduler must never leak across requests)
            inner.relax_budget_s = relax_budget_s
            inner._relax_warm = (
                {
                    sig: rec["pool"]
                    for sig, rec in entry.classes.items()
                    if rec.get("pool")
                }
                if entry is not None
                else None
            ) or None
        return inner.solve(pods)

    # -- recording ---------------------------------------------------------

    def _record(
        self, key, classes, results, core, topo, nodes, label_aware,
        baseline, since_full,
    ) -> LedgerEntry:
        uid_sig: Dict[str, tuple] = {}
        recs: Dict[tuple, dict] = {}
        for c in classes:
            recs[c.signature] = {
                "count": len(c.pods),
                "uids": [p.uid for p in c.pods],
                "exist_nodes": set(),
                "pool": None,
                "errored": False,
                "gangy": c.gang is not None or c.tier != 0,
            }
            for p in c.pods:
                uid_sig[p.uid] = c.signature
        claims = []
        for cl in results.new_node_claims:
            pool = cl.template.nodepool_name
            claims.append({
                "nodepool": pool,
                "instance_types": [
                    it.name for it in cl.instance_type_options
                ],
                "requirements": cl.requirements,
                "requests": dict(cl.requests),
                "pod_uids": [p.uid for p in cl.pods],
            })
            for p in cl.pods:
                rec = recs.get(uid_sig.get(p.uid))
                if rec is not None and rec["pool"] is None:
                    rec["pool"] = pool
        existing = []
        for sim in results.existing_nodes:
            uids = [p.uid for p in sim.pods]
            existing.append((sim.name, uids))
            for u in uids:
                rec = recs.get(uid_sig.get(u))
                if rec is not None:
                    rec["exist_nodes"].add(sim.name)
        errors = dict(results.pod_errors)
        for u in errors:
            rec = recs.get(uid_sig.get(u))
            if rec is not None:
                rec["errored"] = True
        evictions = {
            n: list(uids)
            for n, uids in (
                getattr(results, "evictions", None) or {}
            ).items()
        }
        nbytes = 512 + 64 * len(nodes) + 48 * len(uid_sig)
        nbytes += sum(
            128 + 48 * len(c["pod_uids"]) + 24 * len(c["instance_types"])
            + 32 * len(c["requests"])
            for c in claims
        )
        nbytes += sum(64 + 48 * len(u) for _, u in existing)
        nbytes += sum(96 + len(msg) for msg in errors.values())
        return LedgerEntry(
            key=key,
            core_digest=core,
            topo_digest=topo,
            node_digests=nodes,
            label_aware=label_aware,
            classes=recs,
            claims=claims,
            existing=existing,
            errors=errors,
            evictions=evictions,
            node_count=len(results.new_node_claims),
            baseline_nodes=baseline,
            solves_since_full=since_full,
            nbytes=nbytes,
        )
