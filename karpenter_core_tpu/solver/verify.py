"""ResultVerifier: the host-side trust anchor over every solve result.

Constraint-based packing is only safe when the output provably satisfies
the hard constraints ("Priority Matters", PAPERS.md): the operator turns a
``Results`` into NodeClaims and pod bindings, so a corrupt wire result, a
solver bug, or a future optimizing backend (CvxCluster-style relaxation
behind the Solver seam, ROADMAP item 4) that hands back an infeasible
assignment would otherwise reach the cluster unchecked. This module is a
cheap, INDEPENDENT re-check of every hard constraint over the final
assignment — it shares no code with the device kernels and none of the
solver's incremental state, which is what makes it a trust anchor rather
than a second copy of the bug.

Checked invariants (one ``Violation`` per breach, reason-coded):

* ``conservation``  — every input pod lands exactly once OR is reported
                      unschedulable; never both, never neither
* ``double_place``  — a pod appears in two placement groups
* ``structure``     — unknown pod uids, empty fresh claims, instance-type
                      options outside the claim's pool catalog
* ``capacity``      — per-node arithmetic: daemonset overhead (recomputed
                      independently per template) + the group's pod
                      requests must fit at least one surviving instance-
                      type option (fresh claims) / the node's available
                      (existing nodes)
* ``taint``         — every pod tolerates its node's NoSchedule/NoExecute
                      taints (PreferNoSchedule is soft: relaxation may
                      legitimately add the toleration solver-side)
* ``selector``      — node selector / volume zone pins / required node
                      affinity are compatible with the group's
                      requirements or labels (a zone-pinned pod on a
                      claim bound to another zone fails here)
* ``anti_affinity`` — required hostname pod-anti-affinity: no co-located
                      pod matches the term's selector
* ``spread``        — DoNotSchedule topology-spread bounds: hostname
                      spreads bound the per-node count by maxSkew; zone
                      spreads bound max-min over the eligible domains
* ``offering``      — every fresh claim retains at least one available,
                      requirement-compatible offering outside the ICE
                      snapshot (a packing onto stocked-out capacity is a
                      guaranteed create→ICE→delete round)
* ``eviction``      — preemption legality (gangsched, ISSUE 10): every
                      eviction claim's victim is strictly lower tier
                      (utils/disruption.priority_tier) than some pod its
                      freed capacity admitted on that node; a claim that
                      admits nothing is a dangling drain for free
* ``eviction_unknown`` — an eviction claim naming a node outside the solve
                      input or a uid outside that node's evictable set —
                      the operator would drain a pod the solve never saw
* ``gang``          — gang atomicity: a pod group is fully placed (its
                      min-count) or fully unschedulable; a partially
                      materialized gang deadlocks the workload while
                      holding capacity

The pass is O(pods) with per-class dedup: constraint checks depend only on
a pod's spec equivalence class (solver/snapshot._spec_signature), so each
(group, class) pair is checked once and 50k-pod solves verify in
milliseconds, not a second greedy re-solve. Relaxation-aware: only
relax-IMMUNE requirements are enforced (preferences.py can strip preferred
terms, ScheduleAnyway spreads, and all-but-one required affinity term
solver-side, and a sidecar relaxes ITS pod copies, not the caller's), so a
legitimately relaxed result never false-positives — the fuzz-parity suite
pins that guarantee across every seed.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from karpenter_core_tpu.api import labels as apilabels
from karpenter_core_tpu.api.objects import (
    RESOURCE_PODS,
    TAINT_EFFECT_PREFER_NO_SCHEDULE,
    Pod,
)
from karpenter_core_tpu.scheduling import Requirements, Taints
from karpenter_core_tpu.scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
)
from karpenter_core_tpu.utils import resources as resutil

# capacity comparisons tolerate fixed-point/quantization noise exactly like
# the fuzz-parity invariant checker: a relative ULP band plus an absolute
# floor for tiny quantities
_REL_TOL = 1e-9
_ABS_TOL = 1e-6

REASONS = (
    "conservation",
    "double_place",
    "structure",
    "capacity",
    "taint",
    "selector",
    "anti_affinity",
    "spread",
    "offering",
    "eviction",
    "eviction_unknown",
    "gang",
    "gang_distance",
)


@dataclass
class Violation:
    reason: str
    detail: str

    def __str__(self) -> str:
        return f"[{self.reason}] {self.detail}"


def _fits_with_tolerance(requests: dict, allocatable: dict) -> bool:
    return all(
        qty <= allocatable.get(name, 0.0) * (1 + _REL_TOL) + _ABS_TOL
        for name, qty in requests.items()
    )


def _hard_taints(taints) -> Taints:
    """NoSchedule/NoExecute only: PreferNoSchedule is soft by k8s semantics
    and the relaxation loop may have added the toleration to the SOLVER's
    pod copy (preferences.py), which a sidecar never ships back."""
    return Taints(
        t for t in taints if t.effect != TAINT_EFFECT_PREFER_NO_SCHEDULE
    )


def _immune_requirements(pod: Pod) -> Requirements:
    """The relax-immune half of a pod's scheduling requirements: node
    selector + PVC-derived zone pins. Required node-affinity terms are
    checked separately (any-term: relaxation pops terms from the front but
    can never invent one)."""
    reqs = Requirements.from_labels(pod.node_selector)
    if pod.volume_requirements:
        reqs.add(
            *Requirements.from_node_selector_requirements(
                pod.volume_requirements
            ).values()
        )
    return reqs


def _affinity_term_sets(pod: Pod) -> List[Requirements]:
    """One Requirements per required node-affinity term (terms are OR'd:
    the solver satisfied SOME term, and relaxation only removes terms, so
    a sound check is 'compatible with at least one')."""
    na = pod.affinity.node_affinity if pod.affinity else None
    if na is None or not na.required:
        return []
    return [
        Requirements.from_node_selector_requirements(t.match_expressions)
        for t in na.required
    ]


class _ClassCheck:
    """Per-spec-class cached views (the dedup that keeps the verifier
    O(classes) on the constraint half)."""

    __slots__ = (
        "requests", "immune_reqs", "affinity_alts", "pod",
        "anti_terms", "spread_hard",
    )

    def __init__(self, pod: Pod):
        self.pod = pod
        self.requests = resutil.requests_for_pods(pod)
        self.immune_reqs = _immune_requirements(pod)
        self.affinity_alts = _affinity_term_sets(pod)
        anti = pod.affinity.pod_anti_affinity if pod.affinity else None
        # required hostname anti-affinity only: zone-level anti-affinity
        # needs cross-group attribution the cheap pass doesn't attempt
        self.anti_terms = [
            t for t in (anti.required if anti else [])
            if t.topology_key == apilabels.LABEL_HOSTNAME
            and t.label_selector is not None
        ]
        # DoNotSchedule spreads are relax-immune (only ScheduleAnyway is
        # ever stripped)
        self.spread_hard = [
            c for c in pod.topology_spread_constraints
            if c.when_unsatisfiable == "DoNotSchedule"
        ]


class ResultVerifier:
    """One verifier per solve world (the same constructor inputs every
    scheduler takes), reusable across that world's results."""

    def __init__(
        self,
        nodepools,
        instance_types: Dict[str, list],
        existing_nodes=None,
        daemonset_pods=None,
        topology=None,
        unavailable_offerings: "frozenset | set" = frozenset(),
    ):
        self.nodepools = list(nodepools)
        self.instance_types = instance_types
        self.existing_by_name = {n.name: n for n in (existing_nodes or [])}
        self.daemonset_pods = list(daemonset_pods or [])
        self.topology = topology
        self.unavailable_offerings = frozenset(unavailable_offerings)
        self._pool_catalog_names = {
            pool: {it.name for it in its}
            for pool, its in instance_types.items()
        }
        # daemon overhead per template is recomputed here, independently of
        # the solver's own cache — _daemon_compatible is the shared oracle
        self._overhead_by_pool: Dict[str, dict] = {}
        # zone universe for spread bounds: every zone some nodepool could
        # actually create capacity in, plus existing nodes' zones
        self._zone_universe = self._zones()

    def _zones(self) -> set:
        """The zone half of the solver's own domain universe (the domains
        the greedy/device Topology enforces skew against): pool-intersected
        instance-type zones plus existing nodes' zones — NOT the raw
        offering zones, which a pool restriction may forbid."""
        from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
            domain_universe,
        )

        zones = set(
            domain_universe(self.nodepools, self.instance_types).get(
                apilabels.LABEL_TOPOLOGY_ZONE, set()
            )
        )
        for node in self.existing_by_name.values():
            z = node.labels.get(apilabels.LABEL_TOPOLOGY_ZONE)
            if z:
                zones.add(z)
        return zones

    def _overhead(self, template) -> dict:
        from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
            _daemon_compatible,
        )

        cached = self._overhead_by_pool.get(template.nodepool_name)
        if cached is None:
            cached = resutil.requests_for_pods(*[
                p for p in self.daemonset_pods
                if _daemon_compatible(template, p)
            ])
            self._overhead_by_pool[template.nodepool_name] = cached
        return cached

    # -- the pass ----------------------------------------------------------

    def verify(self, results, pods: List[Pod]) -> List[Violation]:
        """All violations in one result (empty list = trusted). ``pods``
        is the exact solve input — conservation is defined against it."""
        out: List[Violation] = []
        class_cache: Dict[tuple, _ClassCheck] = {}
        # two-level cache: the signature is itself ~µs/pod, so repeat
        # lookups for the same pod object (capacity, anti-affinity, and
        # spread passes all touch every pod) hit the id() level instead
        pod_cache: Dict[int, _ClassCheck] = {}

        def check_of(pod: Pod) -> _ClassCheck:
            from karpenter_core_tpu.solver.snapshot import _spec_signature

            got = pod_cache.get(id(pod))
            if got is not None:
                return got
            sig = _spec_signature(pod, True)
            got = class_cache.get(sig)
            if got is None:
                got = class_cache[sig] = _ClassCheck(pod)
            pod_cache[id(pod)] = got
            return got

        # conservation is tracked by OBJECT IDENTITY: both the inproc and
        # the materialized (sidecar) paths bind the caller's own Pod
        # objects into the result, so id() is a uid read that costs a
        # pointer — the uid property (3 string attribute hops per read ×
        # 150k reads at 50k pods) only gets touched on the failure paths,
        # and the counting itself runs in C (Counter over map(id, ...))
        from collections import Counter
        from itertools import chain

        known_ids = set(map(id, pods))

        groups = []  # (label, group_requirements, group_labels, pods, kind)
        for i, claim in enumerate(results.new_node_claims):
            groups.append((f"claim[{i}]", claim, None, claim.pods, "claim"))
        for sim in results.existing_nodes:
            node = self.existing_by_name.get(sim.name)
            if node is None:
                if sim.pods:
                    out.append(Violation(
                        "structure",
                        f"existing node {sim.name!r} is not part of the"
                        " solve input",
                    ))
                continue
            groups.append((f"node[{sim.name}]", sim, node, sim.pods, "node"))

        placed = Counter(chain.from_iterable(
            map(id, group_pods) for _l, _g, _n, group_pods, _k in groups
        ))
        unknown = set(placed) - known_ids
        if unknown:
            for label, _g, _n, group_pods, _k in groups:
                for p in group_pods:
                    if id(p) in unknown:
                        out.append(Violation(
                            "structure",
                            f"{label} places unknown pod uid {p.uid!r}",
                        ))

        # eviction-claim capacity credit (gangsched, ISSUE 10): placements
        # on a node with eviction claims assume the victims' freed
        # capacity (the operator drains before binding), so the capacity
        # check must see it — but ONLY capacity a claim can actually free
        # (uids resolved against the node's own evictable set; legality
        # and unknown-uid violations are reported by _verify_gangsched)
        ev_credit = self._eviction_credit(results)

        for label, group, node, group_pods, kind in groups:
            if kind == "claim" and not group_pods:
                out.append(Violation(
                    "structure", f"{label} holds no pods (a node for free)"
                ))
            if kind == "claim":
                out.extend(self._verify_claim(label, group, check_of))
            else:
                out.extend(self._verify_existing(
                    label, node, group, check_of,
                    credit=ev_credit.get(node.name),
                ))

        # conservation: exactly-once XOR reported unschedulable
        errors = results.pod_errors
        pget = placed.get
        for p in pods:
            n = pget(id(p), 0)
            if n == 1:
                if errors and p.uid in errors:
                    out.append(Violation(
                        "conservation",
                        f"pod {p.metadata.name!r} both placed and reported"
                        f" unschedulable ({errors[p.uid]!r})",
                    ))
            elif n > 1:
                out.append(Violation(
                    "double_place",
                    f"pod {p.metadata.name!r} placed {n} times",
                ))
            elif not errors or p.uid not in errors:
                out.append(Violation(
                    "conservation",
                    f"pod {p.metadata.name!r} neither placed nor reported"
                    " unschedulable",
                ))

        # fast exit for the constraint-free bulk path (the 50k plain-pod
        # shape): every result pod is in pod_cache by now, so one scan of
        # the CLASS cache tells whether any spread work exists at all
        if any(c.spread_hard for c in class_cache.values()):
            out.extend(self._verify_spread(results, check_of))
        out.extend(self._verify_gangsched(results, pods, placed))
        return out

    # -- gangsched claims (ISSUE 10) ---------------------------------------

    def _eviction_credit(self, results) -> Dict[str, dict]:
        """Per-node freed capacity from the result's eviction claims —
        resolved against each node's OWN evictable set so a forged uid
        can never mint capacity (it reports eviction_unknown instead)."""
        evictions = getattr(results, "evictions", None)
        if not evictions:
            return {}
        credit: Dict[str, dict] = {}
        for node_name, uids in evictions.items():
            node = self.existing_by_name.get(node_name)
            if node is None:
                continue
            ev_by_uid = {
                e.uid: e for e in getattr(node, "evictable", ()) or ()
            }
            freed = [
                ev_by_uid[u].requests for u in uids if u in ev_by_uid
            ]
            if freed:
                credit[node_name] = resutil.merge(*freed)
        return credit

    def _verify_gangsched(self, results, pods, placed) -> List[Violation]:
        """Eviction-claim legality + gang atomicity over the final
        assignment. Independent of the kernel: tiers re-derive through
        utils/disruption.priority_tier (the single tier ordering all
        three layers share) and gang membership re-derives from the pod
        annotations (solver/gangs), not from any solver state — which is
        also why the gang scan below runs unconditionally: any gate that
        skipped it would have to trust the solver's own "no gangs" claim.
        The price is one O(pods) annotation pass per verification."""
        from karpenter_core_tpu.solver.gangs import (
            MAX_HOP_DISTANCE,
            claim_topo_labels,
            gang_max_hops_for,
            gang_members,
            gang_min_count,
            placement_hop_bound,
            pod_gang_rank,
            pod_gang_sig,
            topo_sort_key,
        )
        from karpenter_core_tpu.utils.disruption import priority_tier

        out: List[Violation] = []
        evictions = getattr(results, "evictions", None) or {}
        if evictions:
            placed_on: Dict[str, list] = {}
            for sim in results.existing_nodes:
                placed_on.setdefault(sim.name, []).extend(sim.pods)
            for node_name, uids in sorted(evictions.items()):
                node = self.existing_by_name.get(node_name)
                if node is None:
                    out.append(Violation(
                        "eviction_unknown",
                        f"eviction claim targets node {node_name!r}"
                        " outside the solve input",
                    ))
                    continue
                ev_by_uid = {
                    e.uid: e for e in getattr(node, "evictable", ()) or ()
                }
                admitted = placed_on.get(node_name) or []
                # GANG-FREE admitted pods only: both preemption halves gate
                # on solver/gangs.GANG_FREE (device: gang_j == GANG_FREE;
                # host: pod_gang_sig(p) is None), so a claim whose only
                # positive-tier admitted pod is a gang member cannot be
                # legitimate preemption output — the eviction would be
                # serving a placement the atomicity backstop may strip
                max_tier = max(
                    (
                        priority_tier(p.priority)
                        for p in admitted
                        if pod_gang_sig(p) is None
                    ),
                    default=None,
                )
                if max_tier is None:
                    out.append(Violation(
                        "eviction",
                        f"eviction claim on {node_name!r} admits no placed"
                        " gang-free pod — a drain that enables nothing"
                        " preemption could have produced",
                    ))
                elif max_tier <= 0:
                    # the preemption pass serves POSITIVE tiers only: a
                    # claim on a node whose admitted pods are all tier<=0
                    # cannot be its output, whatever the victims' tiers —
                    # rejects forged claims riding an all-default solve
                    out.append(Violation(
                        "eviction",
                        f"eviction claim on {node_name!r} admits no"
                        f" positive-tier pod (max tier {max_tier}) —"
                        " preemption serves positive tiers only",
                    ))
                    max_tier = None  # victim checks below would be vacuous
                for uid in uids:
                    victim = ev_by_uid.get(uid)
                    if victim is None:
                        out.append(Violation(
                            "eviction_unknown",
                            f"eviction claim on {node_name!r} names uid"
                            f" {uid!r} outside the node's evictable set",
                        ))
                        continue
                    vt = priority_tier(victim.priority)
                    if max_tier is not None and vt >= max_tier:
                        out.append(Violation(
                            "eviction",
                            f"illegal preemption on {node_name!r}: victim"
                            f" {uid!r} (tier {vt}) is not strictly below"
                            f" any admitted pod (max tier {max_tier})",
                        ))
        members = gang_members(pods)
        colocated = any(
            (g := pod_gang_sig(p)) is not None and (g[2] or g[3])
            for mp in members.values()
            for p in mp
        )
        # zone / template attribution per placed pod, built only when a
        # gang declares co-location (O(placements) otherwise skipped)
        zone_of: Dict[int, str] = {}
        pool_of: Dict[int, str] = {}
        if colocated:
            for claim in results.new_node_claims:
                zr = claim.requirements.get(apilabels.LABEL_TOPOLOGY_ZONE)
                zvals = zr.sorted_values() if zr is not None else []
                for p in claim.pods:
                    pool_of[id(p)] = claim.template.nodepool_name
                    if len(zvals) == 1:
                        zone_of[id(p)] = zvals[0]
            for sim in results.existing_nodes:
                node = self.existing_by_name.get(sim.name)
                z = (node.labels or {}).get(
                    apilabels.LABEL_TOPOLOGY_ZONE
                ) if node is not None else None
                for p in sim.pods:
                    if z:
                        zone_of[id(p)] = z
        # network-topology attribution (topoaware, ISSUE 20): full topo
        # label dict per placed pod — a fresh claim attributes through its
        # single-valued requirements (claim_topo_labels, the zone rule
        # extended down the hierarchy), an existing node through its
        # labels. Built only when some gang declares a hop bound or
        # carries ranked members.
        topo_of: Dict[int, dict] = {}
        needs_topo = any(
            ((g := pod_gang_sig(p)) is not None and g[4] is not None)
            or pod_gang_rank(p) is not None
            for mp in members.values()
            for p in mp
        )
        if needs_topo:
            for claim in results.new_node_claims:
                lab = claim_topo_labels(claim)
                for p in claim.pods:
                    topo_of[id(p)] = lab
            for sim in results.existing_nodes:
                node = self.existing_by_name.get(sim.name)
                lab = dict(node.labels or {}) if node is not None else {}
                for p in sim.pods:
                    topo_of[id(p)] = lab
        for name, mpods in sorted(members.items()):
            bound = [p for p in mpods if placed.get(id(p), 0)]
            min_count = gang_min_count(mpods)
            if 0 < len(bound) < min_count:
                out.append(Violation(
                    "gang",
                    f"pod group {name!r} partially materialized:"
                    f" {len(bound)}/{len(mpods)} placed, below min-count"
                    f" {min_count} — a gang commits whole or not at all",
                ))
                continue
            if not bound:
                continue
            # co-location flags OR across members (collect_gangs contract)
            same_zone = any(
                (g := pod_gang_sig(p)) is not None and g[2] for p in mpods
            )
            same_tmpl = any(
                (g := pod_gang_sig(p)) is not None and g[3] for p in mpods
            )
            if same_zone:
                # soundness over completeness: only attributable members
                # (single-valued claim zone / labeled existing node) count
                zones = {
                    zone_of[id(p)] for p in bound if id(p) in zone_of
                }
                if len(zones) > 1:
                    out.append(Violation(
                        "gang",
                        f"pod group {name!r} declares same-zone but its"
                        f" members span zones {sorted(zones)}",
                    ))
            if same_tmpl:
                pools = {
                    pool_of[id(p)] for p in bound if id(p) in pool_of
                }
                if len(pools) > 1:
                    out.append(Violation(
                        "gang",
                        f"pod group {name!r} declares same-node-template"
                        f" but its fresh members span templates"
                        f" {sorted(pools)}",
                    ))
            # hard max-hops bound (topoaware, ISSUE 20), re-derived purely
            # from annotations + labels via the SOUND bound: only
            # attributable placements count and a level only raises the
            # bound when both sides carry it and differ — a cluster
            # without rack labels can never manufacture a violation
            # (soundness over completeness)
            max_hops = gang_max_hops_for(mpods)
            if max_hops is not None and max_hops < MAX_HOP_DISTANCE:
                worst = placement_hop_bound(
                    [topo_of.get(id(p)) for p in bound]
                )
                if worst > max_hops:
                    out.append(Violation(
                        "gang_distance",
                        f"pod group {name!r} placement provably spans"
                        f" {worst} network hops, above its declared"
                        f" max-hops bound {max_hops}",
                    ))
            # rank adjacency: within one equivalence class, members sorted
            # by rank must occupy rack-attributable placements in
            # non-decreasing network order (each domain holds one
            # contiguous rank run) — exactly what the solver-side
            # rank_order_pods permutation guarantees, re-derived here
            # from annotations + labels alone
            ranked = [p for p in bound if pod_gang_rank(p) is not None]
            if ranked:
                from karpenter_core_tpu.solver.snapshot import (
                    _spec_signature,
                )

                by_cls: Dict[tuple, list] = {}
                for p in ranked:
                    lab = topo_of.get(id(p)) or {}
                    if not lab.get(apilabels.LABEL_TOPOLOGY_RACK):
                        continue  # unattributable: soundness first
                    by_cls.setdefault(_spec_signature(p, True), []).append(
                        (pod_gang_rank(p), topo_sort_key(lab))
                    )
                for pairs in by_cls.values():
                    pairs.sort()
                    keys = [k for _r, k in pairs]
                    if keys != sorted(keys):
                        out.append(Violation(
                            "gang_distance",
                            f"pod group {name!r} rank order is not"
                            " network-adjacent: rank-sorted members do"
                            " not occupy their topology domains as"
                            " contiguous runs",
                        ))
                        break
        return out

    # -- per-group checks --------------------------------------------------

    def _verify_claim(self, label, claim, check_of) -> List[Violation]:
        out: List[Violation] = []
        pool = claim.template.nodepool_name
        catalog_names = self._pool_catalog_names.get(pool)
        if catalog_names is None:
            return [Violation(
                "structure", f"{label} targets unknown nodepool {pool!r}"
            )]
        foreign = [
            it.name for it in claim.instance_type_options
            if it.name not in catalog_names
        ]
        if foreign:
            out.append(Violation(
                "structure",
                f"{label} offers instance types outside nodepool"
                f" {pool!r}'s catalog: {foreign[:3]}",
            ))
        if not claim.instance_type_options:
            out.append(Violation(
                "capacity", f"{label} retains no instance-type option"
            ))
            return out

        # capacity: independently recomputed daemon overhead + pod sums
        # (shared bucketing helper — see _bucket_group_pods)
        totals = dict(self._overhead(claim.template))
        hard_taints = _hard_taints(claim.template.taints)
        class_counts = self._bucket_group_pods(
            label, claim.pods, totals, hard_taints, check_of, out
        )
        for c, n in class_counts.values():
            for name, qty in c.requests.items():
                totals[name] = totals.get(name, 0.0) + qty * n
            out.extend(self._check_pod_on_claim(
                label, claim, c, hard_taints
            ))
        fits_one = any(
            _fits_with_tolerance(totals, it.allocatable())
            for it in claim.instance_type_options
        )
        if not fits_one:
            out.append(Violation(
                "capacity",
                f"{label} requests {resutil.to_string(totals)} exceed every"
                f" surviving option"
                f" ({[it.name for it in claim.instance_type_options][:3]})",
            ))
        out.extend(self._check_offerings(label, claim))
        if any(c.anti_terms for c, _n in class_counts.values()):
            out.extend(self._check_anti_affinity(
                label, claim.pods, check_of
            ))
        return out

    def _bucket_group_pods(
        self, label, group_pods, totals, hard_taints, check_of, out
    ) -> Dict[int, list]:
        """The shared 50k hot loop: split one group's pods into the
        constraint-free bulk (accumulated INLINE into ``totals`` — their
        only verifiable obligations are capacity and the group's hard
        taints, and the taint verdict is identical for every
        toleration-less pod so one representative check per group
        suffices) and the per-class machinery for everything else.
        Returns ``class_counts`` (id(_ClassCheck) -> [check, count]);
        the classes' requests are NOT yet folded into totals.

        The fast-path gate lists exactly the fields that change a
        VERIFIED obligation: affinity (selector/anti), tolerations, hard
        spreads, node selector, volume zone pins. host_ports/volumes are
        not checked by this pass, so they don't gate. One helper, two
        callers — a future checked field is added to ONE gate."""
        class_counts: Dict[int, list] = {}
        plain = 0
        plain_rep = None
        tget = totals.get  # bound locals: this loop IS the 50k hot path
        for p in group_pods:
            if (
                p.affinity is None
                and not p.tolerations
                and not p.topology_spread_constraints
                and not p.node_selector
                and not p.volume_requirements
            ):
                plain += 1
                plain_rep = p
                for name, qty in p.resource_requests.items():
                    totals[name] = tget(name, 0.0) + qty
                continue
            c = check_of(p)
            slot = class_counts.get(id(c))
            if slot is None:
                class_counts[id(c)] = [c, 1]
            else:
                slot[1] += 1
        if plain:
            totals[RESOURCE_PODS] = (
                totals.get(RESOURCE_PODS, 0.0) + float(plain)
            )
            if hard_taints:
                errs = hard_taints.tolerates(plain_rep)
                if errs:
                    out.append(Violation(
                        "taint",
                        f"{label}: {plain} toleration-less pods"
                        f" {'; '.join(errs)}",
                    ))
        return class_counts

    def _check_pod_on_claim(self, label, claim, c, hard_taints):
        out: List[Violation] = []
        errs = hard_taints.tolerates(c.pod)
        if errs:
            out.append(Violation(
                "taint",
                f"{label}: pod {c.pod.metadata.name!r} {'; '.join(errs)}",
            ))
        errs = claim.requirements.compatible(
            c.immune_reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
        )
        if errs:
            out.append(Violation(
                "selector",
                f"{label}: pod {c.pod.metadata.name!r} selector/volume pins"
                f" incompatible: {errs}",
            ))
        if c.affinity_alts and not any(
            not claim.requirements.compatible(
                alt, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
            )
            for alt in c.affinity_alts
        ):
            out.append(Violation(
                "selector",
                f"{label}: pod {c.pod.metadata.name!r} satisfies none of"
                " its required node-affinity terms",
            ))
        return out

    def _verify_existing(
        self, label, node, sim, check_of, credit=None
    ) -> List[Violation]:
        from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
            node_daemon_pods,
        )

        out: List[Violation] = []
        if not sim.pods:
            return out
        daemons = resutil.requests_for_pods(
            *node_daemon_pods(node, self.daemonset_pods)
        )
        base = resutil.subtract(daemons, node.daemon_requests)
        totals = {k: max(v, 0.0) for k, v in base.items()}
        hard_taints = _hard_taints(node.taints)
        node_reqs = Requirements.from_labels(node.labels)
        class_counts = self._bucket_group_pods(
            label, sim.pods, totals, hard_taints, check_of, out
        )
        for c, n in class_counts.values():
            p = c.pod
            for name, qty in c.requests.items():
                totals[name] = totals.get(name, 0.0) + qty * n
            errs = hard_taints.tolerates(p)
            if errs:
                out.append(Violation(
                    "taint",
                    f"{label}: pod {p.metadata.name!r} {'; '.join(errs)}",
                ))
            errs = node_reqs.compatible(c.immune_reqs)
            if errs:
                out.append(Violation(
                    "selector",
                    f"{label}: pod {p.metadata.name!r} selector/volume pins"
                    f" incompatible with node labels: {errs}",
                ))
            if c.affinity_alts and not any(
                not node_reqs.compatible(alt) for alt in c.affinity_alts
            ):
                out.append(Violation(
                    "selector",
                    f"{label}: pod {p.metadata.name!r} satisfies none of"
                    " its required node-affinity terms",
                ))
        # eviction claims free capacity on this node (drain-before-bind):
        # the credit was resolved against the node's own evictable set
        avail = (
            resutil.merge(dict(node.available), credit)
            if credit else node.available
        )
        if not _fits_with_tolerance(totals, avail):
            out.append(Violation(
                "capacity",
                f"{label} requests {resutil.to_string(totals)} exceed node"
                f" available {resutil.to_string(dict(avail))}",
            ))
        elif credit and _fits_with_tolerance(totals, node.available):
            # the claim must be LOAD-BEARING: a legitimate preemption only
            # fires when the placements could NOT fit the ordinary free
            # capacity (kernel and host twin both gate on it). A claim on
            # a node whose placements fit without the freed credit drains
            # real workload to enable nothing — the forged-claim shape a
            # tier comparison alone cannot catch (any higher-tier pod that
            # landed through ordinary capacity would legalize it).
            out.append(Violation(
                "eviction",
                f"{label}: eviction claim is not load-bearing — placed"
                f" requests {resutil.to_string(totals)} fit the node's own"
                f" available {resutil.to_string(dict(node.available))}",
            ))
        if any(c.anti_terms for c, _n in class_counts.values()):
            out.extend(self._check_anti_affinity(
                label, sim.pods, check_of
            ))
        return out

    def _check_offerings(self, label, claim) -> List[Violation]:
        """At least one option must keep an available, compatible offering
        outside the ICE snapshot — otherwise the launch is a guaranteed
        create→ICE→delete round the solve was supposed to route around."""
        for it in claim.instance_type_options:
            for o in it.offerings:
                if not o.available:
                    continue
                if o.key(it.name) in self.unavailable_offerings:
                    continue
                if not claim.requirements.intersects(o.requirements):
                    return []
        return [Violation(
            "offering",
            f"{label} retains no available offering compatible with its"
            " requirements outside the unavailable-offerings snapshot",
        )]

    def _check_anti_affinity(self, label, group_pods, check_of):
        out: List[Violation] = []
        if len(group_pods) < 2:
            return out
        for p in group_pods:
            c = check_of(p)
            for term in c.anti_terms:
                matches = sum(
                    1 for q in group_pods
                    if term.label_selector.matches(q.metadata.labels or {})
                )
                # the pod itself may match its own selector (self-anti):
                # any OTHER match on the same host is the violation
                own = 1 if term.label_selector.matches(
                    p.metadata.labels or {}
                ) else 0
                if matches > own or (own and matches > 1):
                    out.append(Violation(
                        "anti_affinity",
                        f"{label}: pod {p.metadata.name!r} co-located with"
                        " a pod matching its required hostname"
                        " anti-affinity selector",
                    ))
                    break
        return out

    # -- topology spread ---------------------------------------------------

    def _verify_spread(self, results, check_of) -> List[Violation]:
        """DoNotSchedule spread bounds over the FINAL assignment.

        hostname: a fresh hostname is always creatable, so the domain min
        floats at zero and each node's matching count is bounded by
        maxSkew. zone: counts aggregate over groups attributable to a
        single zone (claims pin one after a spread placement; existing
        nodes are labeled) plus the topology context's existing pods;
        max-min over the ELIGIBLE domains (the universe intersected with
        zones any matching pod could actually take) is bounded by maxSkew.
        Unattributable groups (multi-zone claims) skip the zone check for
        their constraints — soundness over completeness."""
        out: List[Violation] = []
        # collect the distinct hard constraints present in the result
        constraints = {}
        for claim in results.new_node_claims:
            for p in claim.pods:
                for cons in check_of(p).spread_hard:
                    constraints.setdefault(
                        (cons.topology_key, cons.label_selector,
                         cons.max_skew), cons
                    )
        for sim in results.existing_nodes:
            for p in sim.pods:
                for cons in check_of(p).spread_hard:
                    constraints.setdefault(
                        (cons.topology_key, cons.label_selector,
                         cons.max_skew), cons
                    )
        if not constraints:
            return out

        groups = []
        for i, claim in enumerate(results.new_node_claims):
            zone = None
            if claim.requirements.has(apilabels.LABEL_TOPOLOGY_ZONE):
                zvals = claim.requirements[
                    apilabels.LABEL_TOPOLOGY_ZONE
                ].sorted_values()
                if len(zvals) == 1:
                    zone = zvals[0]
            groups.append((f"claim[{i}]", zone, claim.pods, True))
        for sim in results.existing_nodes:
            node = self.existing_by_name.get(sim.name)
            zone = (
                node.labels.get(apilabels.LABEL_TOPOLOGY_ZONE)
                if node is not None else None
            )
            groups.append((f"node[{sim.name}]", zone, sim.pods, False))

        for (key, selector, max_skew), cons in constraints.items():
            if selector is None:
                continue
            if key == apilabels.LABEL_HOSTNAME:
                for label, _zone, group_pods, _fresh in groups:
                    n = sum(
                        1 for p in group_pods
                        if check_of(p).spread_hard
                        and selector.matches(p.metadata.labels or {})
                        and any(
                            c.topology_key == key
                            and c.label_selector == selector
                            for c in check_of(p).spread_hard
                        )
                    )
                    if n > max_skew:
                        out.append(Violation(
                            "spread",
                            f"{label}: {n} pods matching hostname spread"
                            f" {selector} exceed maxSkew {max_skew}",
                        ))
            elif key == apilabels.LABEL_TOPOLOGY_ZONE:
                counts: Dict[str, int] = {}
                attributable = True
                eligible: set = set()
                for _label, zone, group_pods, _fresh in groups:
                    matching = [
                        p for p in group_pods
                        if selector.matches(p.metadata.labels or {})
                    ]
                    if not matching:
                        continue
                    # a selector cohort where some matching pods do NOT
                    # carry the constraint can legally end up skewed (only
                    # constrained placements check the bound) — counting a
                    # subset would manufacture skew, so skip such cohorts:
                    # soundness over completeness
                    if any(
                        not any(
                            c.topology_key == key
                            and c.label_selector == selector
                            for c in check_of(p).spread_hard
                        )
                        for p in matching
                    ):
                        attributable = False
                        break
                    if zone is None:
                        attributable = False
                        break
                    counts[zone] = counts.get(zone, 0) + len(matching)
                    for p in matching:
                        eligible |= self._allowed_zones(check_of(p))
                if not attributable or not counts:
                    continue
                # the topology context's already-bound matching pods count
                # toward the domains too
                if self.topology is not None:
                    for p, labels, name in self.topology.existing_pods:
                        if p.uid in self.topology.excluded_pods:
                            continue
                        if not selector.matches(p.metadata.labels or {}):
                            continue
                        z = labels.get(apilabels.LABEL_TOPOLOGY_ZONE)
                        if z is None:
                            node = self.existing_by_name.get(name)
                            z = (
                                node.labels.get(apilabels.LABEL_TOPOLOGY_ZONE)
                                if node is not None else None
                            )
                        if z is not None:
                            counts[z] = counts.get(z, 0) + 1
                domains = eligible & self._zone_universe or eligible
                if not domains:
                    continue
                # BOTH ends range over the eligible domains only: the
                # topology context may hold historical matching pods in a
                # zone these pods cannot take (affinity-pinned elsewhere),
                # and the solver legally ignores that zone's count — so
                # must the skew bound, or legitimate placements reject
                low = min(counts.get(z, 0) for z in domains)
                high = max(counts.get(z, 0) for z in domains)
                if high - low > max_skew:
                    out.append(Violation(
                        "spread",
                        f"zone spread {selector}: domain counts {counts}"
                        f" skew {high - low} > maxSkew {max_skew}",
                    ))
        return out

    def _allowed_zones(self, c: _ClassCheck) -> set:
        """Zones this pod class could take at all (its immune requirements
        + any affinity alternative), bounding the spread domain set."""
        base = set(self._zone_universe)
        if c.immune_reqs.has(apilabels.LABEL_TOPOLOGY_ZONE):
            zreq = c.immune_reqs[apilabels.LABEL_TOPOLOGY_ZONE]
            if not zreq.complement:
                base = set(zreq.sorted_values())
        if not c.affinity_alts:
            return base
        allowed: set = set()
        for alt in c.affinity_alts:
            if not alt.has(apilabels.LABEL_TOPOLOGY_ZONE):
                return base  # some alternative allows any zone
            areq = alt[apilabels.LABEL_TOPOLOGY_ZONE]
            if areq.complement:
                return base
            allowed |= set(areq.sorted_values())
        return base & allowed if allowed else base


def verify_frontier(frontier) -> Optional[str]:
    """Structural verification of a consolidation-frontier response: None
    when trustworthy, else the defect. The sweep's (ok, n_new, price_lb)
    triples feed binary decisions directly, so garbage here silently
    mis-sizes a disruption command."""
    if frontier is None:
        return None  # "unrepresentable" is a valid, honest answer
    if not isinstance(frontier, list):
        return f"frontier is {type(frontier).__name__}, not a list"
    for i, entry in enumerate(frontier):
        if not isinstance(entry, tuple) or len(entry) != 3:
            return f"frontier[{i}] is not an (ok, n_new, price_lb) triple"
        ok, n_new, price = entry
        if not isinstance(ok, bool):
            return f"frontier[{i}].ok is {type(ok).__name__}, not bool"
        if not isinstance(n_new, int) or isinstance(n_new, bool):
            return f"frontier[{i}].n_new is not an int"
        if n_new < 0:
            return f"frontier[{i}].n_new is negative ({n_new})"
        if not isinstance(price, float) or price != price or price < 0:
            return f"frontier[{i}].price_lb is not a finite non-negative float"
    return None


def reject(violations: List[Violation], path: str, recorder=None) -> None:
    """The shared rejection side effects: one counter bump per distinct
    reason (`solver_result_rejected_total{reason,path}`) and a Warning
    event when a recorder rides along. The CALLER owns the degradation
    (greedy re-solve / host binary search)."""
    from karpenter_core_tpu.metrics import wiring as m

    for reason in sorted({v.reason for v in violations}):
        m.SOLVER_RESULT_REJECTED.inc({"reason": reason, "path": path})
    if recorder is not None:
        from karpenter_core_tpu.events import Event

        recorder.publish(Event(
            involved_object="Solver/result",
            type="Warning",
            reason="SolverResultRejected",
            message=(
                f"{path} solve result failed verification"
                f" ({len(violations)} violation(s):"
                f" {'; '.join(str(v) for v in violations[:3])})"
                " — degraded to greedy"
            ),
        ))
