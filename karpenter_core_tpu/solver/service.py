"""solverd: the TPU solver as a supervised sidecar process.

SURVEY §7 / BASELINE frame the paper's architecture as Go reconcilers
feeding pod×InstanceType tensor problems to a TPU solver across a process
boundary; this server IS that boundary's solver side, promoted from the
codec-only seam (solver/codec.py called itself "the solver's process
boundary" while nothing served it). It speaks HTTP+npz instead of
gRPC+proto — same split, stdlib transport (the kube/httpserver.py pattern):

* ``POST /solve``        — full scheduler input -> DeviceScheduler.solve
                           (schedulers cached per problem fingerprint, so
                           repeat solves against an unchanged cluster reuse
                           the prepared-state caches across RPC calls)
* ``POST /consolidate``  — consolidation prefix sweep (frontier_core)
* ``GET  /healthz``      — liveness + readiness (warm-up finished)
* ``GET  /metrics``      — the sidecar's own registry, exposition format
* ``POST /profile``      — toggle jax.profiler trace capture around solves
                           (requires ``--profile-dir``); GET reports state

Responses carry ``X-Solver-Seconds`` (device solve wall time) so the client
can split its RPC histogram into transit vs kernel. Boot enables the
persistent XLA compile cache and optionally pre-warms the common class-count
shape buckets (the bench restart-probe path), turning the first-batch
compile cliff into a cache load.

Run: ``python -m karpenter_core_tpu.solver.service --port 0``
"""
from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from karpenter_core_tpu.kube.httpserver import read_body, send_body
from karpenter_core_tpu.solver import codec

_OCTET = "application/octet-stream"


class SolverDaemon:
    """Request execution, transport-free (tests drive it directly).

    Schedulers are cached per problem fingerprint (everything in the solve
    request EXCEPT the pending pods — see codec.problem_fingerprint): a
    control plane re-solving against an unchanged cluster reuses the same
    DeviceScheduler across RPC calls, which carries the prepared-state
    caches (vocab-keyed catalog tensors, per-class rows, device-resident
    class steps) across the wire boundary. Any change to the problem half
    changes the fingerprint and builds a fresh scheduler, so cached and
    uncached solves are packing-identical by construction (conformance
    battery in tests/test_solverd.py). Solves serialize on a lock — the
    sidecar owns one device, and a cached DeviceScheduler is not
    reentrant."""

    _SCHED_CACHE_CAP = 4

    def __init__(self, profile_dir: str = None):
        self.ready = False
        self.solves = 0
        self.profile_dir = profile_dir
        self.profiling = False
        self._sched_cache = {}
        self._lock = threading.Lock()
        self._state_lock = threading.Lock()

    # -- endpoints ---------------------------------------------------------

    def solve(self, body: bytes):
        """bytes -> (response bytes, solve seconds)."""
        from karpenter_core_tpu.metrics import wiring as m
        from karpenter_core_tpu.models.provisioner import DeviceScheduler

        problem = codec.decode_solve_request(body)
        with self._lock:
            scheduler = self._sched_cache.get(problem["fingerprint"])
            if scheduler is None:
                m.SOLVERD_SCHED_CACHE.inc({"outcome": "miss"})
                scheduler = DeviceScheduler(
                    problem["nodepools"],
                    problem["instance_types"],
                    existing_nodes=problem["existing_nodes"],
                    daemonset_pods=problem["daemonset_pods"],
                    max_slots=problem["max_slots"],
                    topology=problem["topology"],
                    unavailable_offerings=problem["unavailable_offerings"],
                )
                if len(self._sched_cache) >= self._SCHED_CACHE_CAP:
                    del self._sched_cache[next(iter(self._sched_cache))]
                self._sched_cache[problem["fingerprint"]] = scheduler
            else:
                m.SOLVERD_SCHED_CACHE.inc({"outcome": "hit"})
                # the fingerprint ignores the pod-derived excluded-uid
                # list; hand the cached scheduler this request's live
                # topology context so exclusions are never stale
                scheduler.update_topology_context(problem["topology"])
            t0 = time.perf_counter()
            with self._maybe_profile():
                results = scheduler.solve(problem["pods"])
            dt = time.perf_counter() - t0
            # counter increment stays under the solve lock: handler threads
            # run concurrently and a bare += is a lost update
            self.solves += 1
        return codec.encode_solve_results(results, dt), dt

    def _maybe_profile(self):
        """jax.profiler trace context when profiling is toggled on and a
        --profile-dir was configured; a no-op context otherwise. Lets TPU
        traces be captured from a RUNNING sidecar (POST /profile) without
        a redeploy."""
        import contextlib

        if self.profiling and self.profile_dir:
            import jax.profiler

            return jax.profiler.trace(self.profile_dir)
        return contextlib.nullcontext()

    def toggle_profile(self, enable: bool = None) -> dict:
        # read-modify-write (enable=None flips the current state) under its
        # own small lock: two concurrent POST /profile toggles must not both
        # read the same old value. Deliberately NOT self._lock — a toggle
        # must not queue behind a multi-second solve.
        with self._state_lock:
            if enable is None:
                enable = not self.profiling
            self.profiling = bool(enable) and self.profile_dir is not None
            return {
                "profiling": self.profiling,
                "profile_dir": self.profile_dir,
                "configured": self.profile_dir is not None,
            }

    def consolidate(self, body: bytes):
        from karpenter_core_tpu.models.consolidation import frontier_core

        req = codec.decode_frontier_request(body)
        t0 = time.perf_counter()
        frontier = frontier_core(
            req["nodepools"],
            req["instance_types"],
            req["cand_nodes"],
            req["keep_nodes"],
            req["daemonset_pods"],
            req["base_pods"],
            req["candidate_pods"],
            max_slots=req["max_slots"],
        )
        dt = time.perf_counter() - t0
        return codec.encode_frontier_response(frontier), dt

    # -- boot warm-up ------------------------------------------------------

    def warm_up(self, prewarm: bool = False) -> None:
        """Compile-cache bootstrap: always point XLA's persistent cache at
        the repo-local directory; with ``prewarm`` also run the synthetic
        shape-bucket solves so a restarted sidecar serves its first real
        batch from the jit cache instead of a compile cliff."""
        from karpenter_core_tpu.utils.jaxenv import (
            enable_persistent_compile_cache,
        )

        enable_persistent_compile_cache()
        if prewarm:
            from karpenter_core_tpu.api.nodepool import NodePool, NodePoolSpec
            from karpenter_core_tpu.api.objects import ObjectMeta
            from karpenter_core_tpu.cloudprovider.kwok import build_catalog
            from karpenter_core_tpu.models.provisioner import DeviceScheduler

            pool = NodePool(metadata=ObjectMeta(name="prewarm"))
            pool.spec = NodePoolSpec()
            catalog = build_catalog(cpu_grid=[1, 2, 4, 8], mem_factors=[2, 4])
            DeviceScheduler(
                [pool], {"prewarm": catalog}, max_slots=256
            ).prewarm()
        self.ready = True


class _Handler(BaseHTTPRequestHandler):
    server_version = "karpenter-solverd/1"
    daemon: SolverDaemon

    def log_message(self, *args) -> None:  # quiet
        pass

    def do_GET(self) -> None:
        path = self.path.split("?")[0]
        if path == "/healthz":
            ok = self.daemon.ready
            send_body(
                self,
                200 if ok else 503,
                (b'{"ok": true}' if ok else b'{"ok": false}'),
            )
        elif path == "/metrics":
            from karpenter_core_tpu.metrics.registry import REGISTRY

            send_body(
                self, 200, REGISTRY.render().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/profile":
            import json as _json

            send_body(
                self, 200,
                _json.dumps(self.daemon.toggle_profile(
                    self.daemon.profiling  # GET reports, never toggles
                )).encode(),
            )
        else:
            send_body(self, 404, b'{"error": "not found"}')

    def do_POST(self) -> None:
        path, _, query = self.path.partition("?")
        body = read_body(self)
        try:
            if path == "/solve":
                out, dt = self.daemon.solve(body)
            elif path == "/consolidate":
                out, dt = self.daemon.consolidate(body)
            elif path == "/profile":
                import json as _json
                from urllib.parse import parse_qs

                q = parse_qs(query)
                enable = None
                if "enable" in q:
                    enable = q["enable"][0] not in ("0", "false", "off")
                state = self.daemon.toggle_profile(enable)
                return send_body(self, 200, _json.dumps(state).encode())
            else:
                return send_body(self, 404, b'{"error": "not found"}')
        except Exception as e:
            return send_body(
                self, 500, repr(e).encode(), ctype="text/plain"
            )
        send_body(
            self, 200, out, _OCTET, headers={"X-Solver-Seconds": f"{dt:.6f}"}
        )


def serve(
    port: int,
    host: str = "127.0.0.1",
    daemon: SolverDaemon = None,
    ready: bool = True,
) -> ThreadingHTTPServer:
    """Serve solverd on host:port in a daemon thread; returns the server
    (port 0 picks a free one — server_address[1]). ``ready=True`` marks the
    daemon ready immediately (in-thread test servers skip warm-up)."""
    d = daemon or SolverDaemon()
    if ready:
        d.ready = True
    handler = type("BoundSolverd", (_Handler,), {"daemon": d})
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.daemon_ = d
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description="karpenter TPU solver sidecar")
    ap.add_argument("--port", type=int, default=8181)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument(
        "--prewarm", action="store_true",
        help="compile the common shape buckets before serving traffic",
    )
    ap.add_argument(
        "--profile-dir", default=None,
        help="directory for jax.profiler traces; solves are wrapped in a"
        " trace capture while profiling is toggled on via POST /profile"
        " (off by default), so TPU-side traces can be grabbed from a"
        " running sidecar without redeploying",
    )
    args = ap.parse_args()

    daemon = SolverDaemon(profile_dir=args.profile_dir)
    httpd = serve(args.port, host=args.host, daemon=daemon, ready=False)
    # the supervisor (solver/supervisor.py) reads this line to learn the
    # bound address — same handshake as kube/httpserver.py
    print(
        f"listening on {httpd.server_address[0]}:{httpd.server_address[1]}",
        flush=True,
    )
    daemon.warm_up(prewarm=args.prewarm)
    print("ready", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        httpd.server_close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
