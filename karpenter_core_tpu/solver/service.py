"""solverd: the TPU solver as a supervised sidecar process.

SURVEY §7 / BASELINE frame the paper's architecture as Go reconcilers
feeding pod×InstanceType tensor problems to a TPU solver across a process
boundary; this server IS that boundary's solver side, promoted from the
codec-only seam (solver/codec.py called itself "the solver's process
boundary" while nothing served it). It speaks HTTP+npz instead of
gRPC+proto — same split, stdlib transport (the kube/httpserver.py pattern):

* ``POST /solve``        — full scheduler input -> DeviceScheduler.solve
* ``POST /consolidate``  — consolidation prefix sweep (frontier_core)
* ``GET  /healthz``      — liveness + readiness (warm-up finished)
* ``GET  /metrics``      — the sidecar's own registry, exposition format

Responses carry ``X-Solver-Seconds`` (device solve wall time) so the client
can split its RPC histogram into transit vs kernel. Boot enables the
persistent XLA compile cache and optionally pre-warms the common class-count
shape buckets (the bench restart-probe path), turning the first-batch
compile cliff into a cache load.

Run: ``python -m karpenter_core_tpu.solver.service --port 0``
"""
from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from karpenter_core_tpu.kube.httpserver import read_body, send_body
from karpenter_core_tpu.solver import codec

_OCTET = "application/octet-stream"


class SolverDaemon:
    """Request execution, transport-free (tests drive it directly)."""

    def __init__(self):
        self.ready = False
        self.solves = 0

    # -- endpoints ---------------------------------------------------------

    def solve(self, body: bytes):
        """bytes -> (response bytes, solve seconds)."""
        from karpenter_core_tpu.models.provisioner import DeviceScheduler

        problem = codec.decode_solve_request(body)
        scheduler = DeviceScheduler(
            problem["nodepools"],
            problem["instance_types"],
            existing_nodes=problem["existing_nodes"],
            daemonset_pods=problem["daemonset_pods"],
            max_slots=problem["max_slots"],
            topology=problem["topology"],
            unavailable_offerings=problem["unavailable_offerings"],
        )
        t0 = time.perf_counter()
        results = scheduler.solve(problem["pods"])
        dt = time.perf_counter() - t0
        self.solves += 1
        return codec.encode_solve_results(results, dt), dt

    def consolidate(self, body: bytes):
        from karpenter_core_tpu.models.consolidation import frontier_core

        req = codec.decode_frontier_request(body)
        t0 = time.perf_counter()
        frontier = frontier_core(
            req["nodepools"],
            req["instance_types"],
            req["cand_nodes"],
            req["keep_nodes"],
            req["daemonset_pods"],
            req["base_pods"],
            req["candidate_pods"],
            max_slots=req["max_slots"],
        )
        dt = time.perf_counter() - t0
        return codec.encode_frontier_response(frontier), dt

    # -- boot warm-up ------------------------------------------------------

    def warm_up(self, prewarm: bool = False) -> None:
        """Compile-cache bootstrap: always point XLA's persistent cache at
        the repo-local directory; with ``prewarm`` also run the synthetic
        shape-bucket solves so a restarted sidecar serves its first real
        batch from the jit cache instead of a compile cliff."""
        from karpenter_core_tpu.utils.jaxenv import (
            enable_persistent_compile_cache,
        )

        enable_persistent_compile_cache()
        if prewarm:
            from karpenter_core_tpu.api.nodepool import NodePool, NodePoolSpec
            from karpenter_core_tpu.api.objects import ObjectMeta
            from karpenter_core_tpu.cloudprovider.kwok import build_catalog
            from karpenter_core_tpu.models.provisioner import DeviceScheduler

            pool = NodePool(metadata=ObjectMeta(name="prewarm"))
            pool.spec = NodePoolSpec()
            catalog = build_catalog(cpu_grid=[1, 2, 4, 8], mem_factors=[2, 4])
            DeviceScheduler(
                [pool], {"prewarm": catalog}, max_slots=256
            ).prewarm()
        self.ready = True


class _Handler(BaseHTTPRequestHandler):
    server_version = "karpenter-solverd/1"
    daemon: SolverDaemon

    def log_message(self, *args) -> None:  # quiet
        pass

    def do_GET(self) -> None:
        path = self.path.split("?")[0]
        if path == "/healthz":
            ok = self.daemon.ready
            send_body(
                self,
                200 if ok else 503,
                (b'{"ok": true}' if ok else b'{"ok": false}'),
            )
        elif path == "/metrics":
            from karpenter_core_tpu.metrics.registry import REGISTRY

            send_body(
                self, 200, REGISTRY.render().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        else:
            send_body(self, 404, b'{"error": "not found"}')

    def do_POST(self) -> None:
        path = self.path.split("?")[0]
        body = read_body(self)
        try:
            if path == "/solve":
                out, dt = self.daemon.solve(body)
            elif path == "/consolidate":
                out, dt = self.daemon.consolidate(body)
            else:
                return send_body(self, 404, b'{"error": "not found"}')
        except Exception as e:
            return send_body(
                self, 500, repr(e).encode(), ctype="text/plain"
            )
        send_body(
            self, 200, out, _OCTET, headers={"X-Solver-Seconds": f"{dt:.6f}"}
        )


def serve(
    port: int,
    host: str = "127.0.0.1",
    daemon: SolverDaemon = None,
    ready: bool = True,
) -> ThreadingHTTPServer:
    """Serve solverd on host:port in a daemon thread; returns the server
    (port 0 picks a free one — server_address[1]). ``ready=True`` marks the
    daemon ready immediately (in-thread test servers skip warm-up)."""
    d = daemon or SolverDaemon()
    if ready:
        d.ready = True
    handler = type("BoundSolverd", (_Handler,), {"daemon": d})
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.daemon_ = d
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description="karpenter TPU solver sidecar")
    ap.add_argument("--port", type=int, default=8181)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument(
        "--prewarm", action="store_true",
        help="compile the common shape buckets before serving traffic",
    )
    args = ap.parse_args()

    daemon = SolverDaemon()
    httpd = serve(args.port, host=args.host, daemon=daemon, ready=False)
    # the supervisor (solver/supervisor.py) reads this line to learn the
    # bound address — same handshake as kube/httpserver.py
    print(
        f"listening on {httpd.server_address[0]}:{httpd.server_address[1]}",
        flush=True,
    )
    daemon.warm_up(prewarm=args.prewarm)
    print("ready", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        httpd.server_close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
